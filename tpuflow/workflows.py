"""Workflow API (L7): the user-facing functions of the framework.

These are the TPU-native equivalents of the reference notebooks' public
surface (SURVEY.md §1 L7): ``train_and_evaluate`` ≙
``train_and_evaluate_hvd`` (P1/03_model_training_distributed.py:282-375)
and ``train_and_package`` ≙ ``train_model_petastorm_data_ingest``
(P2/03_pyfunc_distributed_inference.py:253-409). Where the reference
composes Spark/Petastorm/Horovod/MLflow, these compose
data/train/track/packaging over one device mesh.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

from tpuflow.core import is_primary
from tpuflow.core.config import Config, DataConfig, ModelConfig, TrainConfig
from tpuflow.data.loader import make_converter
from tpuflow.data.table import Table, TableStore
from tpuflow.models import build_model
from tpuflow.packaging import save_packaged_model
from tpuflow.parallel.mesh import build_mesh, world_size
from tpuflow.track import TrackingStore
from tpuflow.train import SystemMetricsCallback, TrackingCallback, Trainer


def _with_overrides(
    config: Optional[Config],
    learning_rate=None,
    dropout=None,
    batch_size=None,
    epochs=None,
    checkpoint_dir=None,
) -> Config:
    """Copy of ``config`` with the HPO-style overrides applied — the
    caller's Config is never mutated, so one shared Config can back a
    whole trial sweep."""
    import copy

    cfg = copy.deepcopy(config) if config is not None else Config()
    if learning_rate is not None:
        cfg.train.learning_rate = learning_rate
    if dropout is not None:
        cfg.model.dropout = dropout
    if batch_size is not None:
        cfg.data.batch_size = batch_size
    if epochs is not None:
        cfg.train.epochs = epochs
    if checkpoint_dir is not None:
        cfg.train.checkpoint_dir = checkpoint_dir
    return cfg


def train_and_evaluate(
    train_table: Table,
    val_table: Table,
    config: Optional[Config] = None,
    learning_rate: Optional[float] = None,
    dropout: Optional[float] = None,
    batch_size: Optional[int] = None,
    checkpoint_dir: Optional[str] = None,
    run_id: Optional[str] = None,
    run_name: Optional[str] = None,
    parent_run_id: Optional[str] = None,
    store: Optional[TrackingStore] = None,
    mesh=None,
    model=None,
    epochs: Optional[int] = None,
    cache_dir: Optional[str] = None,
    resume: bool = False,
) -> Tuple[float, float]:
    """Train data-parallel over the mesh.

    ``resume=True`` restores the newest checkpoint under
    ``checkpoint_dir`` (when one exists) and continues from the next
    epoch — the relaunch-after-failure path (SURVEY.md §5.3-5.4).

    Returns (val_loss, val_accuracy, trainer) — the first two are the
    reference's return contract (P1/03:375); the trainer rides along so
    callers can package the trained weights.

    ≙ train_and_evaluate_hvd (P1/03:282-375) and its HPO variant taking
    (learning_rate, dropout, batch_size, checkpoint_dir)
    (P2/02:161-262). Side effects (tracking, checkpoints) are
    primary-process-only; metrics come back replica-averaged.
    """
    cfg = _with_overrides(
        config,
        learning_rate=learning_rate,
        dropout=dropout,
        batch_size=batch_size,
        epochs=epochs,
        checkpoint_dir=checkpoint_dir,
    )

    mesh = mesh if mesh is not None else build_mesh()
    import jax

    procs = jax.process_count()
    local_devices = world_size(mesh) // procs
    # per-DEVICE batch (the reference's per-worker batch with 1 GPU/worker)
    local_batch = cfg.data.batch_size * local_devices

    cache = cache_dir or cfg.data.cache_dir
    conv_t = make_converter(train_table, cache, min_partitions=procs)
    conv_v = make_converter(val_table, cache, min_partitions=procs)
    from tpuflow.core.hw import is_tpu_backend

    reuse = cfg.data.reuse_decode_buffers
    if reuse is None:
        reuse = is_tpu_backend()  # see DataConfig.reuse_decode_buffers
    ds_kwargs = dict(
        img_height=cfg.data.img_height,
        img_width=cfg.data.img_width,
        num_decode_workers=cfg.data.num_decode_workers,
        prefetch=cfg.data.prefetch,
        streaming=cfg.data.streaming,
        shuffle=cfg.data.shuffle,
        shuffle_buffer=cfg.data.shuffle_buffer,
        reuse_buffers=reuse,
        cache_decoded=cfg.data.cache_decoded,
    )

    if model is None:
        model = build_model(
            img_height=cfg.data.img_height,
            img_width=cfg.data.img_width,
            img_channels=cfg.data.img_channels,
            num_classes=cfg.model.num_classes,
            dropout=cfg.model.dropout,
            width_mult=cfg.model.width_mult,
            freeze_backbone=cfg.model.freeze_backbone,
            weights=cfg.model.weights,
            backbone=cfg.model.backbone,
        )

    run = None
    if store is not None and is_primary():
        run = store.start_run(
            run_name=run_name, run_id=run_id, parent_run_id=parent_run_id
        )
        run.log_params(cfg.flat_params())
        run.log_param("world_size", world_size(mesh))

    # plateau/early-stop/checkpoint callbacks wire automatically from
    # cfg.train inside Trainer.fit; only tracking needs the run handle
    callbacks = []
    if run is not None:
        callbacks.append(TrackingCallback(run))
        if cfg.train.log_system_metrics:
            callbacks.append(SystemMetricsCallback(run))

    trainer = Trainer(model, cfg.train, mesh=mesh, run=run)
    initial_epoch = 0
    if resume and cfg.train.checkpoint_dir:
        trainer.init_state(
            (cfg.data.img_height, cfg.data.img_width, cfg.data.img_channels)
        )
        # steps_per_epoch is derivable from the converter's row count
        # (same formula as Dataset.steps_per_epoch), which makes the
        # resume STEP-aware: a preemption checkpoint
        # (cfg.train.checkpoint_on_preempt) restores to its exact
        # mid-epoch position instead of being silently discarded
        spe = max(1, conv_t.num_rows // (local_batch * procs))
        initial_epoch = trainer.maybe_resume(steps_per_epoch=spe)
    # Datasets are built AFTER resume resolution so a resumed run's
    # stream starts at the (seed, initial_epoch) shuffle order instead
    # of replaying epoch 0 — per-epoch orders are seeded by
    # (seed, epoch) in Dataset._epoch_order.
    train_ds = conv_t.make_dataset(
        local_batch,
        cur_shard=jax.process_index(),
        shard_count=procs,
        seed=cfg.train.seed,
        start_epoch=initial_epoch,
        **ds_kwargs,
    )
    val_ds = conv_v.make_dataset(
        local_batch,
        cur_shard=jax.process_index(),
        shard_count=procs,
        seed=cfg.train.seed,
        **ds_kwargs,
    )
    try:
        hist = trainer.fit(
            train_ds, val_ds=val_ds, callbacks=callbacks,
            initial_epoch=initial_epoch,
        ).history
        val_loss = hist.get("val_loss", [float("nan")])[-1]
        val_acc = hist.get("val_accuracy", [float("nan")])[-1]
        if run is not None:
            run.end("FINISHED")
        return val_loss, val_acc, trainer  # trainer returned for packaging
    finally:
        conv_t.delete()  # ≙ converter.delete() (P1/03:425-426)
        conv_v.delete()


def train_and_package(
    store: TrackingStore,
    train_table: Table,
    val_table: Table,
    classes: Sequence[str],
    config: Optional[Config] = None,
    run_name: str = "train_and_package",
    mesh=None,
    model=None,
    model_type: str = "transfer_classifier",
    parent_run_id: Optional[str] = None,
    learning_rate: Optional[float] = None,
    dropout: Optional[float] = None,
    batch_size: Optional[int] = None,
    epochs: Optional[int] = None,
    cache_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """One-shot pipeline: run-create → param log → train → package →
    evaluate → cleanup. ≙ train_model_petastorm_data_ingest
    (P2/03:253-409). Returns {'run_id', 'model_uri', 'val_loss',
    'val_accuracy'}.

    ``parent_run_id`` nests the run as an HPO child (≙ the per-trial
    nested child runs of P2/02:244-247) so each trial logs a loadable
    model; the hyperparameter overrides mirror train_and_evaluate's.
    """
    cfg = _with_overrides(
        config,
        learning_rate=learning_rate,
        dropout=dropout,
        batch_size=batch_size,
        epochs=epochs,
    )
    run = (
        store.start_run(run_name=run_name, parent_run_id=parent_run_id)
        if is_primary()
        else None
    )
    run_id = run.run_id if run is not None else None
    if run is not None:
        # ≙ logging img_params_dict.json as an artifact (P2/03:285-287)
        run.log_dict(
            {
                "img_height": cfg.data.img_height,
                "img_width": cfg.data.img_width,
                "img_channels": cfg.data.img_channels,
                "classes": list(classes),
            },
            "img_params_dict.json",
        )
    val_loss, val_acc, trainer = train_and_evaluate(
        train_table, val_table, config=cfg, run_id=run_id, store=None, mesh=mesh,
        model=model, cache_dir=cache_dir,
    )
    model_uri = None
    if run is not None:
        pkg_dir = os.path.join(run.artifact_path(), "model")
        save_packaged_model(
            pkg_dir,
            params=trainer.state.params,
            batch_stats=trainer.state.batch_stats,
            classes=classes,
            img_height=cfg.data.img_height,
            img_width=cfg.data.img_width,
            img_channels=cfg.data.img_channels,
            model_type=model_type,
            model_config={
                "num_classes": cfg.model.num_classes,
                "dropout": cfg.model.dropout,
                "width_mult": cfg.model.width_mult,
                "freeze_backbone": cfg.model.freeze_backbone,
                "backbone": cfg.model.backbone,
            },
        )
        run.log_params(cfg.flat_params())
        run.log_metrics({"val_loss": val_loss, "val_accuracy": val_acc})
        run.end("FINISHED")
        model_uri = f"runs:/{run.run_id}/model"
    return {
        "run_id": run_id,
        "model_uri": model_uri,
        "val_loss": val_loss,
        "val_accuracy": val_acc,
    }


def lm_train_and_package(
    store: TrackingStore,
    train_tokens,
    val_tokens,
    lm_config: Dict[str, Any],
    batch_size: int,
    train_config: Optional[TrainConfig] = None,
    epochs: Optional[int] = None,
    run_name: str = "lm_train_and_package",
    parent_run_id: Optional[str] = None,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    resume: bool = False,
    generate_defaults: Optional[Dict[str, Any]] = None,
    tokenizer=None,
) -> Dict[str, Any]:
    """The C20 one-shot pipeline for the LM family: run-create → param
    log → LMTrainer fit → package (tpuflow.packaging.lm) → evaluate →
    metrics. Returns {'run_id', 'model_uri', 'val_loss', 'val_ppl'}.
    ``tokenizer`` (a tpuflow.data.text.ByteBPE) is bundled into the
    artifact, enabling PackagedLM's raw-text surface.

    ``resume=True`` restores the newest checkpoint under
    ``checkpoint_dir`` and continues from its epoch (≙
    train_and_evaluate's resume path; the restart half of gang
    relaunch).

    ``lm_config``: build_transformer_lm kwargs that define the
    architecture — stored in the package so the artifact is
    self-contained (≙ the img-params artifact of P2/03:285-287).
    """
    from tpuflow.models import build_transformer_lm
    from tpuflow.packaging import save_packaged_lm
    from tpuflow.train import LMTrainer

    cfg = train_config or TrainConfig()
    run = (
        store.start_run(run_name=run_name, parent_run_id=parent_run_id)
        if is_primary()
        else None
    )
    run_id = run.run_id if run is not None else None
    trainer = LMTrainer(build_transformer_lm(**lm_config), cfg, mesh=mesh)
    initial_epoch = trainer.maybe_resume(checkpoint_dir) if resume else 0
    if run is not None:
        run.log_params(
            {f"lm.{k}": str(v) for k, v in lm_config.items()}
            | {
                "optimizer": cfg.optimizer,
                "learning_rate": cfg.learning_rate,
                "batch_size": batch_size,
                "epochs": epochs if epochs is not None else cfg.epochs,
            }
        )
    metrics = trainer.fit(
        train_tokens,
        batch_size=batch_size,
        epochs=epochs,
        val_tokens=val_tokens,
        checkpoint_dir=checkpoint_dir,
        run=run,
        initial_epoch=initial_epoch,
    )
    model_uri = None
    if run is not None:
        save_packaged_lm(
            os.path.join(run.artifact_path(), "model"),
            params=trainer.state.params,
            model_config=lm_config,
            generate_defaults=generate_defaults,
            tokenizer=tokenizer,
        )
        run.end("FINISHED")
        model_uri = f"runs:/{run.run_id}/model"
    return {
        "run_id": run_id,
        "model_uri": model_uri,
        "val_loss": metrics.get("val_loss"),
        "val_ppl": metrics.get("val_ppl"),
    }
