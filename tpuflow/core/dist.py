"""Process topology + rank-0 gating.

Replaces the reference's Horovod rank machinery (``hvd.init/rank/size/
local_rank``, reference P1/03_model_training_distributed.py:283,295,301)
with JAX process topology. Side effects (tracking, checkpointing) are
gated to the primary process exactly as the reference gates them to
rank 0 (P1/03:360-361, P2/02:206-211).

Multi-host bootstrap (≙ HorovodRunner's pickle→barrier→mpirun cascade,
P1/03:256-263) is a single ``initialize`` call per host process; the
launcher CLI (tpuflow.cli.launch) spawns one process per host.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Optional, TypeVar

import jax

T = TypeVar("T")

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bootstrap multi-host JAX.

    ``np=-1`` analogue: with no arguments and no TPUFLOW_* env vars this is
    a no-op and the program runs single-process (the reference's
    driver-local smoke mode, P1/03:385-397).

    Env fallbacks: TPUFLOW_COORDINATOR, TPUFLOW_NUM_PROCESSES,
    TPUFLOW_PROCESS_ID (set by the launcher CLI).
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("TPUFLOW_COORDINATOR")
    if num_processes is None and "TPUFLOW_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["TPUFLOW_NUM_PROCESSES"])
    if process_id is None and "TPUFLOW_PROCESS_ID" in os.environ:
        process_id = int(os.environ["TPUFLOW_PROCESS_ID"])
    if coordinator_address is None or num_processes in (1, -1):
        return  # single-process mode (explicit np=-1 or nothing configured)
    # num_processes=None with a coordinator: let JAX auto-detect (TPU
    # metadata); never silently degrade to single-process when the user
    # asked for distributed.
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def world_device_count() -> int:
    return jax.device_count()


def is_primary() -> bool:
    """True on the process that owns side effects (≙ hvd.rank() == 0)."""
    return jax.process_index() == 0


def barrier(name: str = "tpuflow_barrier") -> None:
    """Block until every process reaches this point (≙ the gang
    synchronization Spark barrier mode provides around Horovod stages,
    P1/03:256). No-op single-process. Typical use: non-primary
    processes must not read a checkpoint until the primary finished
    writing it."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def primary_only(fn: Callable[..., T]) -> Callable[..., Optional[T]]:
    """Decorator: run ``fn`` only on the primary process, return None elsewhere.

    The by-construction race-avoidance discipline of the reference
    (checkpoints and tracking only from rank 0, P2/02:206-211).
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if is_primary():
            return fn(*args, **kwargs)
        return None

    return wrapper
