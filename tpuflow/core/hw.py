"""Hardware/backend detection helpers.

TPU access can arrive through out-of-tree PJRT plugins whose platform
name is NOT ``"tpu"`` (tunneled/relayed backends), so feature gates
keyed on ``jax.default_backend() == "tpu"`` would silently fall back
to interpret/emulation paths on real hardware. Detection here keys on
the device kind as well as the platform name.
"""

from __future__ import annotations


def is_tpu_backend() -> bool:
    """True when the default JAX backend drives real TPU hardware.

    Used to pick compiled Mosaic kernels (Pallas ``interpret=False``)
    vs the interpreter: platform name ``tpu`` OR a device kind that
    names a TPU generation (covers PJRT plugins with custom platform
    names fronting real chips).
    """
    import re

    import jax

    try:
        if jax.default_backend() == "tpu":
            return True
        d = jax.devices()[0]
    except Exception:
        return False
    kind = (getattr(d, "device_kind", "") or "").lower()
    # "tpu v4" / "TPU v5 lite" / bare generation tags like "v5e" — but
    # NOT arbitrary v-prefixed kinds (e.g. "vgpu"): require v<digit>
    return d.platform == "tpu" or "tpu" in kind or bool(re.match(r"v\d", kind))


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (created if absent) with the thresholds zeroed so EVERY executable
    is cached — the suite and the benches are compile-dominated (72 s
    LM compile recorded in BENCH_LOCAL_r05_lm.json), and a warm cache
    turns repeat compiles into ~0 s deserializes.

    Opt-in via ``TrainConfig.compilation_cache_dir`` (the trainers call
    this at fit time), the launcher's ``--compile-cache`` flag, or
    directly. Safe to call repeatedly; returns False (never raises)
    when the running jax build lacks the config knobs — callers must
    not die over a missing cache.

    Caveat: proven on the TPU path (bench.py has committed ``.xla_cache``
    since r03), but on THIS container's jax 0.4.37 XLA:CPU a
    persistent-cache HIT of an AOT executable can SEGFAULT (reproduced
    at a pristine checkout; see tests/conftest.py) — which is why the
    test suite's enablement is opt-in rather than default.
    """
    import os

    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        if jax.config.jax_compilation_cache_dir != cache_dir:
            # jax memoizes the cache object on FIRST use: a compile
            # that ran before this call (dir unset, or another dir)
            # freezes that state and later config updates silently
            # write nothing (measured on 0.4.37) — drop the memo so
            # mid-process enablement actually takes effect
            try:
                from jax._src.compilation_cache import reset_cache

                reset_cache()
            except Exception:
                pass  # private API; worst case the memo wins as before
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return True
    except Exception:
        return False
