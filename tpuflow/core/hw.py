"""Hardware/backend detection helpers.

TPU access can arrive through out-of-tree PJRT plugins whose platform
name is NOT ``"tpu"`` (tunneled/relayed backends), so feature gates
keyed on ``jax.default_backend() == "tpu"`` would silently fall back
to interpret/emulation paths on real hardware. Detection here keys on
the device kind as well as the platform name.
"""

from __future__ import annotations


def is_tpu_backend() -> bool:
    """True when the default JAX backend drives real TPU hardware.

    Used to pick compiled Mosaic kernels (Pallas ``interpret=False``)
    vs the interpreter: platform name ``tpu`` OR a device kind that
    names a TPU generation (covers PJRT plugins with custom platform
    names fronting real chips).
    """
    import re

    import jax

    try:
        if jax.default_backend() == "tpu":
            return True
        d = jax.devices()[0]
    except Exception:
        return False
    kind = (getattr(d, "device_kind", "") or "").lower()
    # "tpu v4" / "TPU v5 lite" / bare generation tags like "v5e" — but
    # NOT arbitrary v-prefixed kinds (e.g. "vgpu"): require v<digit>
    return d.platform == "tpu" or "tpu" in kind or bool(re.match(r"v\d", kind))
