from tpuflow.core.compat import shard_map  # noqa: F401
from tpuflow.core.hw import (  # noqa: F401
    enable_compilation_cache,
    is_tpu_backend,
)
from tpuflow.core.dist import (  # noqa: F401
    barrier,
    initialize,
    is_primary,
    local_device_count,
    primary_only,
    process_count,
    process_index,
    world_device_count,
)
from tpuflow.core.config import (  # noqa: F401
    Config,
    DataConfig,
    InferConfig,
    ModelConfig,
    TrainConfig,
    TuneConfig,
)
