"""Consistency / debug checks — the race-detection story (§5.2).

The reference has no sanitizers; its only race defenses are
by-construction (rank-0-only side effects, P2/02:206-211) and an
UNCHECKED invariant: after broadcast-init every worker holds identical
weights (P1/03:305-308). Here that invariant is testable machinery:

- ``tree_checksum``: collision-resistant blake2b digest of a pytree's
  raw leaf bytes (keyed by tree path, dtype and shape);
- ``assert_replicated_across_devices``: every device's copy of each
  replicated array is bitwise identical (catches desync introduced by
  non-deterministic host code writing into device buffers);
- ``assert_consistent_across_processes``: checksums agree across all
  hosts of a multi-process job (catches divergent init/restore);
- ``nan_check``: fail fast on non-finite leaves (the jax_debug_nans
  spirit, but usable on live state between steps).

Wire into training with ``TrainConfig(consistency_check_every=N)`` —
the ReplicaConsistencyCheck callback runs these every N epochs from the
primary process's perspective; zero overhead when off.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import numpy as np


def tree_checksum(tree: Any) -> int:
    """Collision-resistant digest of a pytree's raw bytes.

    blake2b over each numeric leaf's bytes, mixed with its tree path,
    dtype and shape — so permutations, sign flips, and value swaps all
    change the digest (unlike a Σ|x|+Σx style sum). Returned as a
    uint64-sized int so it can ride a process allgather."""
    h = hashlib.blake2b(digest_size=8)
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        arr = np.asarray(jax.device_get(leaf))
        if not np.issubdtype(arr.dtype, np.number):
            continue
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return int.from_bytes(h.digest(), "little")


def assert_replicated_across_devices(tree: Any, name: str = "state") -> None:
    """Every addressable shard of each fully-replicated leaf must be
    bitwise identical (the broadcast-init invariant, P1/03:305-308)."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        shards = leaf.addressable_shards
        if len(shards) < 2:
            continue
        # only fully-replicated leaves: every shard spans the whole array
        if any(s.data.shape != leaf.shape for s in shards):
            continue
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            if not np.array_equal(ref, np.asarray(s.data), equal_nan=True):
                raise AssertionError(
                    f"replicated leaf {name}{jax.tree_util.keystr(path)} "
                    f"differs between device {shards[0].device} and "
                    f"{s.device} — replicas have desynced"
                )


def assert_consistent_across_processes(tree: Any, name: str = "state") -> None:
    """All processes must hold the same checksum (multi-host jobs)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils as mhu

    # gather as two uint32 words: uint64 would be silently truncated
    # (or rejected) by jax under the default x64-disabled config
    digest = tree_checksum(tree)
    local = np.array(
        [digest & 0xFFFFFFFF, digest >> 32], np.uint32
    )
    all_sums = np.asarray(mhu.process_allgather(local)).reshape(-1, 2)
    if not np.all(all_sums == all_sums[0]):
        raise AssertionError(
            f"{name} checksum differs across processes: {all_sums.tolist()}"
        )


def nan_check(tree: Any, name: str = "state") -> None:
    """Raise on any non-finite numeric leaf."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        arr = np.asarray(jax.device_get(leaf))
        if np.issubdtype(arr.dtype, np.floating) and not np.all(
            np.isfinite(arr)
        ):
            raise FloatingPointError(
                f"non-finite values in {name}{jax.tree_util.keystr(path)}"
            )
