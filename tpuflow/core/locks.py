"""Shared directory-scoped advisory locking.

One flock helper for every on-disk store that does read-modify-write
commits (tracking runs, versioned tables). A fresh fd per acquisition
means ``flock`` serializes both threads within one process and writers
across processes; platforms without ``fcntl`` degrade to unlocked
writes (the reference's rank-0-only discipline still applies there).
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def dir_lock(path: str, name: str = ".lock"):
    """Exclusive advisory lock on directory ``path`` (created if needed)."""
    try:
        import fcntl
    except ImportError:  # non-POSIX: fall back to unlocked writes
        yield
        return
    os.makedirs(path, exist_ok=True)
    fd = os.open(os.path.join(path, name), os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)
