"""Typed config tree (SURVEY.md §5.6).

The reference scatters configuration across module-level constants
(P1/02_model_training_single_node.py:41-46), a ``DataCfg`` dataclass
(P2/03_pyfunc_distributed_inference.py:85-95) and kwargs dicts
(P2/03:392-409). Here it is one serializable dataclass tree with the
same escape hatches: kwargs dicts thread through, and optimizers are
selectable by name (needed for HPO over optimizer choice, P2/01:154-155).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class DataConfig:
    """≙ DataCfg (reference P2/03:85-95) + the notebook image constants."""

    table_root: str = "./tables"
    database: str = "flowers"
    img_height: int = 224
    img_width: int = 224
    img_channels: int = 3
    batch_size: int = 32
    cache_dir: str = "./loader_cache"
    # streaming=True reads row groups through a bounded shuffle buffer
    # (beyond-memory tables, ≙ Petastorm's reason to exist, P1/03:32-34);
    # default keeps the in-memory fast path for workshop-scale data
    streaming: bool = False
    shuffle: bool = True  # per-epoch seeded shuffle (off ⇒ table order)
    shuffle_buffer: int = 2048
    # None = auto: reuse decode output buffers on TPU backends (halves
    # allocator churn in the infeed); forced off on CPU where JAX may
    # alias numpy arrays zero-copy into device buffers
    reuse_decode_buffers: "bool | None" = None
    num_decode_workers: int = 8
    # cache decoded uint8 rows so epoch 2+ skips JPEG decode
    # (incompatible with streaming): True = host-RAM dict
    # (rows x H x W x 3 bytes of RSS); 'memmap' = disk-backed beside
    # the cache files — flat RSS and PERSISTENT across runs
    # (decode-once per shard x geometry, corrupt flags included)
    cache_decoded: "bool | str" = False
    prefetch: int = 2
    sample_fraction: float = 1.0
    split_seed: int = 42
    val_fraction: float = 0.1


@dataclass
class ModelConfig:
    backbone: str = "mobilenet_v2"
    num_classes: int = 5
    dropout: float = 0.5
    width_mult: float = 1.0
    freeze_backbone: bool = True
    dtype: str = "bfloat16"  # compute dtype; params stay float32
    # converted pretrained-backbone checkpoint path (models/pretrained
    # canonical npz) — ≙ Keras weights='imagenet' (P1/02:164-169)
    weights: "str | None" = None


@dataclass
class TrainConfig:
    optimizer: str = "adam"  # resolved by name, ≙ getattr(tf.keras.optimizers, name)
    learning_rate: float = 1e-3
    scale_lr_by_world_size: bool = True  # ≙ lr × hvd.size(), P1/03:300-302
    warmup_epochs: int = 5  # ≙ LearningRateWarmupCallback, P1/03:315-318
    epochs: int = 3
    reduce_on_plateau_patience: int = 10  # ≙ ReduceLROnPlateau, P1/03:319-322
    # on-device random horizontal flip of training batches (the
    # reference trains with NO augmentation — beyond-reference knob,
    # default off so parity runs stay bit-identical)
    augment_flip: bool = False
    # clip gradients to this global norm before the update (None = off)
    grad_clip_norm: Optional[float] = None
    # label smoothing on the TRAINING loss (eval stays plain CE so
    # val_loss remains comparable across smoothing settings)
    label_smoothing: float = 0.0
    # gradient accumulation: each step's batch splits into this many
    # sequential micro-steps whose gradients average before ONE
    # optimizer update — the standard fit-a-bigger-batch-in-HBM lever
    # (exactly equivalent to the unaccumulated step for mean losses).
    # Honored by LMTrainer; 1 = off.
    grad_accum_steps: int = 1
    # LMTrainer: compute the LM loss with the fused vocab-chunked
    # linear+cross-entropy (tpuflow.ops.xent) — identical math, never
    # materializes the (B*S, vocab) logits tensor (2+ GB at production
    # shapes). Requires a replicated LM head (tensor-parallel size 1).
    fused_loss: bool = False
    # LMTrainer sequence packing: when set, each training row is
    # treated as EOS-delimited packed documents — attention is masked
    # within documents (segment ids + per-document rotary positions
    # derived ON DEVICE from the token stream), and the cross-document
    # next-token prediction is excluded from the loss. None = off
    # (rows are single sequences).
    packed_eos_id: Optional[int] = None
    # superstep execution: fuse this many training steps into ONE jitted
    # lax.scan dispatch over a stacked (K, batch, ...) block — a single
    # host dispatch (and a single device-resident metrics block) per K
    # steps instead of K per-call round-trips. The win is pure framework
    # overhead: when the device step is shorter than the per-call
    # dispatch floor (the flagship's 2.14 ms step vs a ~1.75-2.8 ms
    # floor, MFU_ANALYSIS.md), the python step loop is dispatch-bound
    # and throughput scales ~K× back to the benched steady state.
    # Semantics: K=1 is exactly the classic per-step loop; K>1 runs the
    # SAME step function (same math, same per-step RNG fold-in) as the
    # scan body — bitwise-identical per-step losses/params under a
    # fixed compilation config (pinned by tests/test_superstep.py; at
    # higher XLA opt levels the fused scan body may round differently
    # at the last ulp, the same class of difference as any recompile).
    # Blocks
    # never cross epoch / preempt-sync boundaries, so callback,
    # checkpoint and eval cadence are unchanged; the trade is metric
    # LATENCY (the first loss of a block lands after K steps, and a
    # SIGTERM preemption stop is taken at block granularity).
    superstep: int = 1
    # opt-in persistent XLA compilation cache directory
    # (jax_compilation_cache_dir): compiled executables are reused
    # across processes AND runs — the suite and benches are
    # compile-dominated (72 s LM compile, BENCH_LOCAL_r05_lm.json), so
    # a warm cache turns repeat runs into ~0 s loads. None = off.
    # TPU-proven (bench.py's committed .xla_cache); NOTE on jax 0.4.37
    # XLA:CPU a cache hit can segfault upstream — tests/conftest.py
    # documents the repro, so CPU use is at-your-own-risk until a jax
    # bump.
    compilation_cache_dir: Optional[str] = None
    # post-warmup LR schedule: 'none' (constant — reference parity) or
    # 'cosine' (anneal to min_lr over the full run, the standard LM
    # warmup+cosine recipe); composes with the plateau factor
    lr_decay: str = "none"
    min_lr: float = 0.0
    reduce_on_plateau_factor: float = 0.1
    early_stopping_patience: Optional[int] = None  # ≙ EarlyStopping, P2/03:397-401
    checkpoint_dir: Optional[str] = None
    # preemption-safe training (TPU pods are preemptible; the reference
    # has no analogue): on SIGTERM the Trainer finishes the CURRENT
    # step, writes a step-granular checkpoint-step-{N}.ckpt (atomic,
    # rank-0), and stops cleanly; maybe_resume(steps_per_epoch=...)
    # restores it EXACTLY — same epoch, same position in the stream
    # (fit fast-forwards the skipped batches). Requires checkpoint_dir.
    # Multi-process runs take the stop decision via a synchronized
    # any-host OR-reduction of the SIGTERM flags every
    # preempt_sync_every steps, so all processes stop at the SAME step
    # (identical-collective-schedule invariant preserved; per-VM spot
    # reclamation signals only one host — see tpuflow.train.preempt).
    checkpoint_on_preempt: bool = False
    # overlap epoch-checkpoint WRITES with training: the host fetch
    # (and any ZeRO allgather) stays synchronous, the serialize+write
    # runs on a background thread (tpuflow.ckpt.AsyncCheckpointer) —
    # joined before the next write and at train end
    async_checkpoint: bool = False
    # step cadence of the multi-process preemption agreement broadcast
    # (a host-sync per check — 16 amortizes it away while bounding the
    # post-signal latency to <= 16 steps; ignored single-process)
    preempt_sync_every: int = 16
    # >0: every N epochs assert replicas/processes hold identical state
    # and params are finite (tpuflow.core.debug — the checkable form of
    # the broadcast-init invariant, P1/03:305-308)
    consistency_check_every: int = 0
    # log host/device utilization into the run each epoch with a sys.
    # prefix (≙ the Ganglia dashboards, P1/04:25-30, recorded with the
    # run instead of living in a cluster UI)
    log_system_metrics: bool = False
    # ---- metrics/health plane (ISSUE 5) ----
    # Prometheus text-exposition exporter port (tpuflow.obs.prom):
    # the trainer starts a scrape endpoint at GET :port/metrics when
    # set (0 = ephemeral; the exporter also starts the windowed
    # snapshot ring). None = no exporter thread.
    metrics_port: Optional[int] = None
    # arm the training watchdogs (tpuflow.obs.health): a device-side
    # isfinite(loss) & isfinite(grad_norm) flag rides the step's
    # existing metrics block (zero extra host syncs — a worker thread
    # pays the fetch) and an EWMA loss-spike detector watches the
    # fetched losses. Default off: the flag adds a global-norm
    # reduction to the compiled step, so parity-pinned runs stay
    # bit-identical.
    watchdog: bool = False
    # with watchdog mode: also trip when no training step completes
    # for this many seconds (hung collective / wedged host). Epoch-end
    # eval/checkpoint and mid-fit compiles are excluded (the monitor
    # pauses around them); set this ABOVE the wall time of one
    # superstep block — a fused K-step dispatch is one "step" to the
    # stall clock. None = no stall thread.
    stall_timeout_s: Optional[float] = None
    # where watchdog trips dump their flight-record bundle
    # (tpuflow.obs.flight; inspect with `python -m tpuflow.cli.obs
    # postmortem <dir>`). None = trip without a dump.
    flight_dir: Optional[str] = None
    # ---- fault-tolerance plane (ISSUE 10) ----
    # sharded checkpoints (tpuflow.ckpt.sharded): every process writes
    # ONLY its addressable replica-0 shards
    # (checkpoint-step-{N}.shard-{P}-of-{W}.ckpt + atomic manifest) —
    # no assembling allgather on save, and restore re-slices under a
    # DIFFERENT process count/mesh shape (the elastic-resize and
    # ZeRO-at-scale path). LMTrainer writes its epoch-boundary and
    # preemption checkpoints in this format when set; resume needs
    # maybe_resume(steps_per_epoch=...) (manifests live in the
    # step-number namespace). The legacy single-file format keeps
    # restoring either way.
    sharded_checkpoint: bool = False
    # checkpoint retention: keep only the newest N checkpoints per
    # namespace (epoch files; step files + sharded sets), GC'd after
    # each successful save — the newest VALID checkpoint is never
    # deleted. None = keep everything (legacy behavior).
    keep_last_checkpoints: Optional[int] = None
    # auto-recovery (tpuflow.train.recovery): turn a watchdog trip
    # (NaN / loss spike / stall) into rollback-to-last-good-checkpoint
    # with bounded retries instead of halt-and-dump. Requires
    # watchdog=True and checkpoint_dir; escalation ladder: after
    # recovery_lr_drop_after consecutive trips also drop the LR by
    # recovery_lr_drop_factor, after recovery_skip_batch_after also
    # skip the poisoned step's batch on replay, past
    # recovery_max_retries halt with the classic post-mortem.
    # recovery_backoff_s sleeps before each restore (doubling).
    recovery: bool = False
    recovery_max_retries: int = 3
    recovery_backoff_s: float = 0.0
    recovery_lr_drop_after: int = 2
    recovery_lr_drop_factor: float = 0.5
    recovery_skip_batch_after: int = 3
    seed: int = 0
    optimizer_kwargs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TuneConfig:
    max_evals: int = 20
    parallelism: int = 1
    seed: int = 0


@dataclass
class InferConfig:
    batch_size: int = 64
    result_type: str = "string"


@dataclass
class Config:
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    tune: TuneConfig = field(default_factory=TuneConfig)
    infer: InferConfig = field(default_factory=InferConfig)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Config":
        return cls(
            data=DataConfig(**d.get("data", {})),
            model=ModelConfig(**d.get("model", {})),
            train=TrainConfig(**d.get("train", {})),
            tune=TuneConfig(**d.get("tune", {})),
            infer=InferConfig(**d.get("infer", {})),
        )

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls.from_dict(json.loads(s))

    def flat_params(self) -> Dict[str, Any]:
        """Flatten to dotted keys for run-tracking param logging."""
        out: Dict[str, Any] = {}
        for section, value in self.to_dict().items():
            for k, v in value.items():
                out[f"{section}.{k}"] = v
        return out
