"""Version compatibility shims for the JAX API surface.

One place absorbs upstream API moves so a JAX upgrade (or downgrade)
breaks ONE import instead of every call site: ``shard_map`` graduated
from ``jax.experimental.shard_map`` to the top-level ``jax.shard_map``
namespace, and the repo targets both — newer JAX first, experimental
fallback for the 0.4.x line. Everything in tpuflow (and the tests /
examples / bench) imports ``shard_map`` from HERE, never from jax
directly; tests/test_import_health.py turns any future break of this
kind into one clear failure instead of a pile of opaque collection
errors.
"""

from __future__ import annotations

import jax

import inspect as _inspect

try:  # jax >= 0.5: public top-level API
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

if "axis_names" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(f, *, mesh, in_specs, out_specs,
                  axis_names=None, check_vma=None, **kw):
        """Accept the new-API kwargs on jax 0.4.x: ``axis_names`` (the
        manual axes) is the complement of the old ``auto`` set, and
        ``check_vma`` was called ``check_rep``."""
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

try:  # jax >= 0.5
    axis_size = jax.lax.axis_size
except AttributeError:  # jax 0.4.x: psum of a constant constant-folds
    # to the axis size at trace time (no collective is emitted)
    def axis_size(axis_name):
        return jax.lax.psum(1, axis_name)

try:  # jax >= 0.6: public aval-of API (carries .vma under shard_map)
    typeof = jax.typeof
except AttributeError:  # jax 0.4.x: the aval has no .vma — callers
    # already guard with getattr(..., "vma", frozenset())
    def typeof(x):
        return jax.core.get_aval(x)

try:
    _SDS_HAS_VMA = "vma" in _inspect.signature(
        jax.ShapeDtypeStruct.__init__
    ).parameters
except (ValueError, TypeError):  # C-level signature: probe directly
    try:
        jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
        _SDS_HAS_VMA = True
    except TypeError:
        _SDS_HAS_VMA = False


def shape_dtype_struct(shape, dtype, vma=None):
    """jax.ShapeDtypeStruct with the ``vma`` kwarg dropped on JAX
    versions that predate varying-manual-axes tracking (0.4.x uses
    check_rep instead, so the annotation is simply not needed)."""
    if vma and _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def tpu_compiler_params(**kwargs):
    """Pallas-TPU compiler params across the rename:
    ``pltpu.CompilerParams`` (new) vs ``pltpu.TPUCompilerParams``
    (jax 0.4.x). Imported lazily so CPU-only processes never pay for
    (or break on) the Pallas TPU import."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


__all__ = ["shard_map", "axis_size", "typeof", "shape_dtype_struct",
           "tpu_compiler_params"]
