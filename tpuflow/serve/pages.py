"""Paged KV memory management: allocator, prefix cache, COW forks.

The host-side policy half of the paged serve engine (ISSUE 6; the
device half — page-indexed gather/scatter attention and the paged
join/segment executables — lives in :mod:`tpuflow.infer.generate` and
:mod:`tpuflow.models.transformer`). Three pieces:

- :class:`PageAllocator` — a refcounted free-list over the physical
  pages of one :func:`~tpuflow.infer.generate.paged_kv_arrays` store.
  Page 0 is RESERVED as the write sink (masked device writes land
  there; it is never handed out), so ``pages - 1`` pages are usable.
  Freed-page events feed a sliding window so admission control can
  quote a Retry-After from the measured page FREE RATE instead of a
  queue-depth guess.

- :class:`PrefixCache` — a radix tree over page-sized token chunks
  mapping prompt prefixes to the page chains that already hold their
  KV. A request whose prompt shares a cached prefix SKIPS that part of
  its prefill entirely (the dominant pattern at scale: shared system
  prompts) and holds a refcount on the shared pages; the partial tail
  page of a match is reused COPY-ON-WRITE — the plan forks it onto a
  fresh page before the request's first divergent write, so the parent
  chain (and any request still decoding against it) is never touched.
  KV content at position j depends only on tokens [0..j] (positions
  are logical in the paged engine — no pads), which is exactly the
  property that makes token-prefix keyed sharing sound.

- :class:`PagedKV` — owns one device page store + allocator + prefix
  tree for one model, plans admissions (:meth:`PagedKV.plan` →
  :class:`PagePlan`), executes COW forks, and answers the memory
  accounting questions (bytes in use, bytes per live token) that
  ``tools/kv_memory_report.py`` and the ``serve.kv_*`` gauges quote.

Thread discipline: like the slot pools, ONE thread (the scheduler's)
may mutate the allocator/tree; read-only stat snapshots are safe from
other threads (single numpy/int reads).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: physical page id reserved as the masked-write sink — never allocated,
#: never mapped into a live row's table beyond padding slots.
SINK_PAGE = 0


def pages_needed(prompt_len: int, max_new: int, page_size: int) -> int:
    """Worst-case pages for one request (no sharing): its KV spans
    positions [0, p + max_new - 1) — the last generated token's KV is
    never written. THE single definition: the planner, the scheduler's
    never-servable check, and the default store sizing must agree."""
    return math.ceil((prompt_len + max_new - 1) / page_size)


def initial_pages_needed(prompt_len: int, max_new: int, advance: int,
                         page_size: int) -> int:
    """Pages the INCREMENTAL reserve covers (ISSUE 11): the prompt
    plus the first ``advance`` decode tokens, clamped to the budget —
    KV positions ``[0, min(p-1+advance, p+max_new-1))``. THE single
    definition: :meth:`PagedKV.plan`'s ``initial_new`` branch and the
    scheduler's Retry-After hints must quote the same number, or
    clients back off against capacity admission would grant."""
    cover = min(prompt_len - 1 + max(1, int(advance)),
                prompt_len + max_new - 1)
    return max(1, math.ceil(cover / page_size))


def chunk_keys(tokens, page_size: int) -> List[bytes]:
    """Chained digests of the FULL ``page_size``-token chunks of
    ``tokens`` — ``keys[j]`` identifies the token prefix
    ``tokens[:(j+1)*page_size]`` exactly as :class:`PrefixCache`
    chunks it (node key = chunk bytes under its parent chain), so the
    multi-replica router's affinity table and a replica's prefix tree
    agree on what can hit. Callers wanting the CACHEABLE prefix of a
    prompt pass ``prompt[:p-1]`` (position p-1 is written by the
    request's own first decode step — :meth:`PagedKV.plan`). Pure
    host math: tokens in, digests out."""
    import hashlib

    tokens = np.asarray(tokens, np.int32).reshape(-1)
    ps = int(page_size)
    h = hashlib.blake2b(digest_size=16)
    out: List[bytes] = []
    for j in range(tokens.size // ps):
        h.update(tokens[j * ps:(j + 1) * ps].tobytes())
        out.append(h.digest())
    return out


#: wire-format version tag (ISSUE 14): importers reject anything else.
PAGE_WIRE_VERSION = 1


class PageWireError(RuntimeError):
    """A page-chain wire payload failed validation — CRC mismatch,
    header/shape mismatch, a chain gap, or an allocator too dry to
    land it. The importer retains NOTHING from the failing chunk; the
    serving-tier contract is a clean fallback to LOCAL prefill (the
    request decodes correctly either way — the transfer is purely a
    work-placement optimization), never a truncated stream."""


def split_chain(wire: Dict[str, Any],
                chunk_pages: int) -> List[Dict[str, Any]]:
    """Split one :meth:`PagedKV.export_chain` wire into transferable
    chunks of at most ``chunk_pages`` pages each. Every chunk carries
    the token PREFIX through its own end (the radix path the importer
    needs) plus only its own page payloads (``first_page`` says where
    they sit in the chain), so chunks stream independently and land
    one scheduler boundary at a time — the transfer-overlap half of
    the disaggregation story."""
    n = int(wire["n_pages"])
    cp = max(1, int(chunk_pages))
    if n <= cp:
        return [wire] if n else []
    ps = int(wire["page_size"])
    out = []
    for s in range(0, n, cp):
        e = min(n, s + cp)
        ch = {k: wire[k] for k in ("version", "page_size", "quant",
                                   "leaves")}
        ch.update(
            n_pages=e - s, first_page=s,
            tokens=wire["tokens"][: e * ps],
            chunk_keys=wire["chunk_keys"][:e],
            payloads=wire["payloads"][s:e],
            crc32=wire["crc32"][s:e],
        )
        out.append(ch)
    return out


def wire_bytes(wire: Dict[str, Any]) -> int:
    """Payload bytes one wire (or chunk) ships — the unit of the
    ``serve.kv_transfer_bytes_total`` accounting."""
    return sum(len(p) for p in wire.get("payloads", ()))


def wire_to_json(wire: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-able form of a wire/chunk (payload bytes → base64) — what
    the HTTP replica transport ships."""
    import base64

    out = dict(wire)
    out["payloads"] = [base64.b64encode(p).decode("ascii")
                       for p in wire["payloads"]]
    return out


def wire_from_json(obj: Dict[str, Any]) -> Dict[str, Any]:
    import base64

    out = dict(obj)
    out["payloads"] = [base64.b64decode(p) for p in obj["payloads"]]
    return out


@dataclass(frozen=True)
class PagedKVSpec:
    """Shape of one paged KV store: ``pages`` physical pages of
    ``page_size`` token slots each; ``quant='int8'`` stores pages as
    int8 with per-page scale vectors (≈4× smaller than f32 KV, 2× than
    bf16 — capacity doubles again on top of paging). ``kernel``
    selects the fused paged-attention decode kernel
    (:func:`tpuflow.ops.attention.paged_flash_decode`): ``None`` =
    auto (TPU backend only — off-TPU the portable einsum path stays
    the bitwise-pinned production path), ``True`` forces it (Pallas
    interpret mode off-TPU — what the kernel parity tests run),
    ``False`` never. int8 stores always take the portable path."""

    pages: int
    page_size: int = 16
    quant: Optional[str] = None  # None | 'int8'
    kernel: Optional[bool] = None  # fused decode kernel (None = auto)

    def __post_init__(self):
        if self.pages < 2:
            raise ValueError(
                f"pages must be >= 2 (page 0 is the reserved write "
                f"sink), got {self.pages}"
            )
        if self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}"
            )
        if self.quant not in (None, "int8"):
            raise ValueError(
                f"quant must be None or 'int8', got {self.quant!r}"
            )


class PageAllocator:
    """Refcounted free-list over ``pages`` physical pages (page 0
    reserved). ``alloc`` is all-or-nothing; ``release`` returns pages
    to the free list when their refcount reaches zero and records the
    free event for :meth:`free_rate`."""

    def __init__(self, pages: int, clock: Callable[[], float] = time.time,
                 free_window_s: float = 10.0):
        if pages < 2:
            raise ValueError(f"pages must be >= 2, got {pages}")
        self.pages = int(pages)
        self.clock = clock
        self.free_window_s = float(free_window_s)
        # LIFO free list: recently freed pages are re-used first (their
        # contents are hottest in any cache hierarchy)
        self._free: List[int] = list(range(1, self.pages))
        self.refs = np.zeros(self.pages, np.int64)
        self.refs[SINK_PAGE] = 1  # pinned forever
        # freed-event window shared with foreign readers (the HTTP
        # frontend quotes Retry-After from free_rate()) — everything
        # else in the allocator is scheduler-thread-only
        self._freed: "deque[Tuple[float, int]]" = deque()
        self._rate_lock = threading.Lock()
        self.allocs = 0
        self.frees = 0
        self.alloc_failures = 0

    # ---- capacity ---------------------------------------------------
    @property
    def total(self) -> int:
        """Usable pages (the sink is not one)."""
        return self.pages - 1

    def free_count(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        return self.total - len(self._free)

    # ---- alloc / refcounts ------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages with refcount 1, or None (all-or-nothing)
        if the free list is short."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        if n > len(self._free):
            self.alloc_failures += 1
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self.refs[p] = 1
        self.allocs += n
        return out

    def retain(self, pages) -> None:
        for p in pages:
            if self.refs[p] <= 0:
                raise RuntimeError(
                    f"retain of unallocated page {p} (refcount "
                    f"{int(self.refs[p])}) — use-after-free"
                )
            self.refs[p] += 1

    def release(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list. Returns the number of pages actually freed."""
        freed = 0
        for p in pages:
            if p == SINK_PAGE:
                raise RuntimeError("the sink page is never released")
            if self.refs[p] <= 0:
                raise RuntimeError(
                    f"release of free page {p} — double free"
                )
            self.refs[p] -= 1
            if self.refs[p] == 0:
                self._free.append(p)
                freed += 1
        if freed:
            self.frees += freed
            now = self.clock()
            with self._rate_lock:
                self._freed.append((now, freed))
                self._trim(now)
        return freed

    # ---- windowed free-rate (Retry-After math) ----------------------
    def _trim(self, now: float) -> None:
        horizon = now - self.free_window_s
        while self._freed and self._freed[0][0] < horizon:
            self._freed.popleft()

    def free_rate(self, now: Optional[float] = None) -> float:
        """Pages freed per second over the sliding window — the
        denominator of the out-of-pages Retry-After estimate. Safe
        from any thread."""
        now = self.clock() if now is None else now
        with self._rate_lock:
            self._trim(now)
            total = sum(n for _, n in self._freed)
        return total / max(self.free_window_s, 1e-9)

    def stats(self) -> Dict[str, float]:
        return {
            "pages_total": self.total,
            "pages_in_use": self.in_use(),
            "pages_free": self.free_count(),
            "allocs": self.allocs,
            "frees": self.frees,
            "alloc_failures": self.alloc_failures,
            "free_rate_per_s": round(self.free_rate(), 4),
        }


class _Node:
    __slots__ = ("tokens", "key", "page", "children", "parent",
                 "last_used")

    def __init__(self, tokens: np.ndarray, key: bytes, page: int,
                 parent: Optional["_Node"], last_used: float):
        self.tokens = tokens
        self.key = key
        self.page = page
        self.children: Dict[bytes, "_Node"] = {}
        self.parent = parent
        self.last_used = last_used


class PrefixCache:
    """Radix tree over page-sized token chunks → physical pages.

    Every node is one FULL page of prompt KV, keyed by that page's
    token chunk under its parent chain (so the path root→node spells
    the token prefix the page's KV was computed from). The tree holds
    one refcount per node page; requests matching a prefix add their
    own. Eviction is leaf-LRU, only of pages nobody else references —
    called when the allocator runs dry, never on the hot path."""

    def __init__(self, page_size: int, allocator: PageAllocator,
                 clock: Callable[[], float] = time.time):
        self.ps = int(page_size)
        self.alloc = allocator
        self.clock = clock
        self.root: Dict[bytes, _Node] = {}
        self.nodes = 0
        self.inserts = 0
        self.evictions = 0
        # guards tree-STRUCTURE mutation vs foreign-thread stats():
        # the flight recorder dumps kv_snapshot from its own thread at
        # trip/SIGTERM time, possibly mid-insert on the scheduler
        # thread — an unguarded dict walk would raise 'dictionary
        # changed size during iteration' exactly when the post-mortem
        # matters. match() stays lock-free (scheduler-thread-only).
        self._mutate_lock = threading.Lock()

    # ---- lookup -----------------------------------------------------
    def match(self, tokens: np.ndarray):
        """Longest cached prefix of ``tokens``. Returns ``(full_pages,
        matched_tokens, partial)``: the chain of fully matched pages,
        the token count they cover, and — when the next page's first
        ``q > 0`` tokens also match — ``(page, q)``, the COPY-ON-WRITE
        fork candidate (the caller duplicates that page and appends
        into its own copy; the shared parent is never written)."""
        tokens = np.asarray(tokens, np.int32)
        level = self.root
        pages: List[int] = []
        i = 0
        now = self.clock()
        while i + self.ps <= tokens.size:
            nd = level.get(tokens[i:i + self.ps].tobytes())
            if nd is None:
                break
            nd.last_used = now
            pages.append(nd.page)
            i += self.ps
            level = nd.children
        partial = None
        rem = tokens[i:]
        if rem.size:
            best_q, best_nd = 0, None
            for nd in level.values():
                n = min(rem.size, nd.tokens.size)
                neq = np.nonzero(nd.tokens[:n] != rem[:n])[0]
                q = int(neq[0]) if neq.size else n
                if q > best_q:
                    best_q, best_nd = q, nd
            if best_nd is not None:
                best_nd.last_used = now
                partial = (best_nd.page, best_q)
        return pages, i, partial

    # ---- insert -----------------------------------------------------
    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Register ``pages[j]`` as holding the KV of token chunk
        ``tokens[j*ps:(j+1)*ps]`` (under the preceding chunks). Chunks
        already present keep their EXISTING page (the caller's
        duplicate page stays private and dies with its request); new
        nodes retain their page on behalf of the tree. Returns the
        number of new nodes."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.size < len(pages) * self.ps:
            raise ValueError(
                f"{len(pages)} pages need {len(pages) * self.ps} "
                f"tokens, got {tokens.size}"
            )
        level = self.root
        parent = None
        new = 0
        now = self.clock()
        with self._mutate_lock:
            for j, pg in enumerate(pages):
                chunk = tokens[j * self.ps:(j + 1) * self.ps]
                key = chunk.tobytes()
                nd = level.get(key)
                if nd is None:
                    nd = _Node(chunk.copy(), key, int(pg), parent, now)
                    level[key] = nd
                    self.alloc.retain([int(pg)])
                    self.nodes += 1
                    new += 1
                else:
                    nd.last_used = now
                parent = nd
                level = nd.children
            self.inserts += new
        return new

    # ---- eviction ---------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out: List[_Node] = []
        stack = list(self.root.values())
        while stack:
            nd = stack.pop()
            if nd.children:
                stack.extend(nd.children.values())
            else:
                out.append(nd)
        return out

    def _drop(self, nd: _Node) -> None:
        # callers hold _mutate_lock
        siblings = nd.parent.children if nd.parent else self.root
        del siblings[nd.key]
        self.nodes -= 1
        self.evictions += 1
        self.alloc.release([nd.page])

    def evict_lru(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping least-recently-used
        LEAF nodes whose page only the tree references (refcount 1 —
        no live request shares it). Dropping a leaf can expose its
        parent as the next candidate."""
        freed = 0
        with self._mutate_lock:
            while freed < n_pages:
                # one tree walk + one sort per ROUND (a round drains
                # every current candidate; dropping leaves can expose
                # parents, which the next round picks up) — not one
                # full walk per page freed
                cands = sorted(
                    (nd for nd in self._leaves()
                     if self.alloc.refs[nd.page] == 1),
                    key=lambda x: x.last_used,
                )
                if not cands:
                    break
                for nd in cands:
                    self._drop(nd)
                    freed += 1
                    if freed >= n_pages:
                        break
        return freed

    def clear(self) -> int:
        """Release every tree reference (deepest first). Pages shared
        with live requests survive until those requests release them."""
        freed = 0
        # leaves-first teardown keeps the parent links consistent
        with self._mutate_lock:
            while self.root:
                for nd in self._leaves():
                    if self.alloc.refs[nd.page] == 1:
                        freed += 1
                    self._drop(nd)
                    self.evictions -= 1  # clear() is not an eviction
        return freed

    def stats(self) -> Dict[str, float]:
        """Safe from any thread (the flight recorder calls this on its
        own thread at trip/SIGTERM time)."""
        with self._mutate_lock:
            depth = 0
            stack = [(nd, 1) for nd in self.root.values()]
            while stack:
                nd, d = stack.pop()
                depth = max(depth, d)
                stack.extend((c, d + 1) for c in nd.children.values())
            return {
                "nodes": self.nodes,
                "max_depth": depth,
                "inserts": self.inserts,
                "evictions": self.evictions,
            }


@dataclass
class PagePlan:
    """One admission's page assignment (built by :meth:`PagedKV.plan`,
    grown by :meth:`PagedKV.extend`, consumed by
    ``PagedSlotPool.join``). Under incremental allocation (ISSUE 11)
    ``table`` starts at prompt + first-segment coverage and grows at
    segment boundaries; ``budget_pages`` records the worst-case need
    (what admission used to reserve up front) so the held-vs-budget
    accounting can show what incrementality saves."""

    table: List[int]  # page chain, position-ordered (shared + fresh)
    owned: List[int] = field(default_factory=list)  # refs THIS request holds
    start: int = 0  # m — KV positions already cached (prefill skips them)
    width: int = 0  # p - m suffix tokens still to write (incl. last)
    forks: List[Tuple[int, int]] = field(default_factory=list)  # (src, dst)
    n_full: int = 0  # leading pages that will hold a full prompt chunk
    matched_tokens: int = 0
    hit: bool = False
    budget_pages: int = 0  # worst-case pages_needed (the old reserve)
    # worst case at the POOL's max_new_cap — what a contiguous slab (or
    # the old reserve at cap) provisions per slot; set by the scheduler
    cap_budget_pages: int = 0
    held_sum: int = 0  # Σ len(table) over decode boundaries…
    held_n: int = 0  # …and the boundary count (mean held = sum/n)


class PagedKV:
    """One model's paged KV universe: device page store + allocator +
    prefix tree + the admission planner. Shared by every
    ``PagedSlotPool`` (all buckets) of one scheduler — that sharing is
    the point: admission asks THIS object for pages, not a per-bucket
    pool for a slot-shaped slab."""

    def __init__(self, model, spec: PagedKVSpec, *,
                 prefix_cache: bool = True,
                 clock: Callable[[], float] = time.time,
                 draft_model=None):
        from tpuflow.infer.generate import paged_kv_arrays, paged_page_bytes

        self.model = model
        self.spec = spec
        self.cache = paged_kv_arrays(model, spec)  # device pytree
        self.page_bytes = paged_page_bytes(self.cache)
        # speculative decoding (ISSUE 9): the draft model's KV lives in
        # a SECOND page store indexed by the SAME page tables — one
        # allocation covers both models' KV for a position, so plans,
        # refcounts, COW forks and releases need no draft-side twin.
        # Ledger component: kv_draft.
        self.draft_model = draft_model
        self.draft_cache = None
        self.draft_page_bytes = 0
        if draft_model is not None:
            self.draft_cache = paged_kv_arrays(draft_model, spec,
                                               component="kv_draft")
            self.draft_page_bytes = paged_page_bytes(self.draft_cache)
        self.allocator = PageAllocator(spec.pages, clock=clock)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(spec.page_size, self.allocator, clock=clock)
            if prefix_cache else None
        )
        # incremental-allocation accounting (ISSUE 11): per-segment
        # extend events, and the mean held-vs-budget ratio over
        # released plans — the number that says what incrementality
        # saves vs the old worst-case reserve (bench acceptance < 0.6)
        self.extends = 0
        # wire-transport counts (ISSUE 14): chains serialized out of /
        # landed into this store (per-call; pages/bytes ride the serve
        # metrics plane)
        self.exports = 0
        self.imports = 0
        self._held_ratio_sum = 0.0
        self._held_ratio_n = 0
        self._held_cap_sum = 0.0
        self._held_cap_n = 0

    # ---- admission planning -----------------------------------------
    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        return pages_needed(prompt_len, max_new, self.spec.page_size)

    def plan(self, prompt: np.ndarray, max_new: int,
             initial_new: Optional[int] = None,
             use_prefix: bool = True) -> Optional[PagePlan]:
        """Match the prefix cache, fork the partial tail COW, allocate
        the fresh remainder — or return None when the allocator cannot
        cover it even after LRU-evicting unreferenced tree pages (the
        caller keeps the request QUEUED; nothing is retained on
        failure).

        ``initial_new`` (ISSUE 11, incremental allocation): reserve
        pages covering only the prompt plus the first ``initial_new``
        decode tokens instead of the full ``max_new`` budget — the
        scheduler passes its segment advance and grows the plan at
        later boundaries via :meth:`extend`, so a request holds pages
        proportional to tokens GENERATED. ``None`` keeps the original
        worst-case reserve (offline callers, warm-up).

        ``use_prefix=False`` skips the prefix-cache match (every page
        fresh and row-exclusive) — for callers that want wholesale
        private page chains (the ring landing path itself only ever
        writes a plan's private pages, so the serve scheduler plans
        ring admissions WITH the prefix and rings only the uncached
        suffix; this flag stays for direct callers)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = int(prompt.size)
        ps = self.spec.page_size
        # only positions [0, p-1) are reusable: position p-1 is written
        # by the request's own first decode step (which also produces
        # the logits its first sample needs)
        full_pages: List[int] = []
        m_full = 0
        partial = None
        if use_prefix and self.prefix is not None and p > 1:
            full_pages, m_tok, partial = self.prefix.match(prompt[:p - 1])
            m_full = m_tok // ps
        need_total = self.pages_needed(p, max_new)
        if initial_new is None:
            need_init = need_total
        else:
            # KV positions that must be writable before the first
            # extend opportunity: the join prefill writes [m, p-1) and
            # the first segment writes [p-1, p-1+initial_new), clamped
            # to the row's budget limit p + max_new - 1
            need_init = initial_pages_needed(p, max_new,
                                             int(initial_new), ps)
        n_fresh = need_init - len(full_pages)
        # retain the matched chain BEFORE any eviction/allocation: a
        # nearly-dry allocator may otherwise LRU-evict the very pages
        # we just matched (tree-only refcount 1) and hand them back as
        # this plan's FRESH pages — the same physical page would then
        # sit in the table as shared prefix AND prefill target
        self.allocator.retain(full_pages)
        fresh = self.allocator.alloc(n_fresh)
        if fresh is None and self.prefix is not None:
            short = n_fresh - self.allocator.free_count()
            self.prefix.evict_lru(short)
            fresh = self.allocator.alloc(n_fresh)
        if fresh is None:
            self.allocator.release(full_pages)
            return None
        m = m_full * ps
        forks: List[Tuple[int, int]] = []
        if partial is not None and partial[1] > 0:
            # COW: duplicate the partially matching page; the request
            # appends into ITS copy from offset q — the shared parent
            # (possibly mid-decode in another slot) is never written
            src, q = partial
            forks.append((int(src), int(fresh[0])))
            m += int(q)
        plan = PagePlan(
            table=full_pages + fresh,
            owned=full_pages + fresh,
            start=m,
            width=p - m,
            forks=forks,
            n_full=(p - 1) // ps,
            matched_tokens=m,
            hit=m > 0,
            budget_pages=need_total,
        )
        return plan

    def extend(self, plan: PagePlan, n: int) -> Optional[List[int]]:
        """Grow ``plan`` by ``n`` fresh pages at a segment boundary
        (incremental allocation, ISSUE 11) — LRU-evicting unreferenced
        prefix-tree pages under pressure exactly like :meth:`plan`.
        Returns the new pages (appended to the plan's table/owned), or
        None with NOTHING retained when the store is genuinely dry —
        the caller's cue to evict a row back to the queue instead of
        letting the pool deadlock."""
        if n < 1:
            return []
        fresh = self.allocator.alloc(n)
        if fresh is None and self.prefix is not None:
            short = n - self.allocator.free_count()
            self.prefix.evict_lru(short)
            fresh = self.allocator.alloc(n)
        if fresh is None:
            return None
        plan.table.extend(fresh)
        plan.owned.extend(fresh)
        self.extends += 1
        return fresh

    def execute_forks(self, plan: PagePlan) -> None:
        if plan.forks:
            from tpuflow.infer.generate import paged_copy
            from tpuflow.obs import memory as _mem

            src = [s for s, _ in plan.forks]
            dst = [d for _, d in plan.forks]
            self.cache = paged_copy(self.cache, src, dst)
            _mem.tag("kv_pages", self.cache)  # COW replaced the store
            if self.draft_cache is not None:
                # the draft store forks the SAME page ids: the shared
                # page table must stay valid for both models' KV
                self.draft_cache = paged_copy(self.draft_cache, src, dst)
                _mem.tag("kv_draft", self.draft_cache)

    def land_ring(self, plan: PagePlan, harvest, n_row_pages: int,
                  prompt_len: int) -> None:
        """Ring-prefill landing path (ISSUE 13): scatter a sequence-
        parallel prefill's per-layer K/V (the ``ring_kv`` collection
        from :func:`tpuflow.infer.generate.ring_prefill_kv`, logical
        token order) into this plan's PRIVATE pages — positions
        ``[matched_tokens//ps * ps, p-1)``; the plan's fully-matched
        shared prefix pages are never written (their slots redirect to
        the sink), a partially-matched tail page is the plan's own
        fresh page and the landing rewrites it wholesale (so the COW
        copy is unnecessary — the caller clears ``plan.forks``), and
        position p-1 is left to the row's first decode step as
        always. Page slots past the landed chain point at the write
        sink, and the tail page's slots beyond p-1 hold pad-token
        garbage every decode step overwrites before any read can see
        it (causal mask + write-before-read). Fixed shapes per pool:
        ONE compiled scatter regardless of prompt length."""
        from tpuflow.infer.generate import paged_land
        from tpuflow.obs import memory as _mem

        if self.spec.quant is not None:
            raise ValueError(
                "ring prefill does not combine with int8 pages yet — "
                "the harvest lands unquantized KV")
        ps = self.spec.page_size
        n_land = max(0, math.ceil((prompt_len - 1) / ps))
        start_page = int(plan.matched_tokens) // ps
        if n_land > len(plan.table):  # pragma: no cover - defensive
            raise RuntimeError(
                f"plan covers {len(plan.table)} pages < the "
                f"{n_land} the harvest lands")
        pages = np.zeros((int(n_row_pages),), np.int32)
        pages[start_page:n_land] = plan.table[start_page:n_land]
        self.cache = paged_land(self.cache, harvest, pages)
        _mem.tag("kv_pages", self.cache)

    # ---- wire format (ISSUE 14, prefill/decode disaggregation) ------
    def wire_header(self) -> Dict[str, Any]:
        """Self-describing store header: what an importer checks a
        wire against before touching its allocator — two stores
        inter-operate iff their page geometry, quantization and leaf
        shapes/dtypes agree (same model family, same spec)."""
        import jax

        leaves = jax.tree_util.tree_leaves(self.cache)
        return {
            "version": PAGE_WIRE_VERSION,
            "page_size": int(self.spec.page_size),
            "quant": self.spec.quant or "none",
            "leaves": [[list(leaf.shape[1:]), str(leaf.dtype)]
                       for leaf in leaves],
        }

    def export_chain(self, tokens, pages) -> Dict[str, Any]:
        """Serialize a page chain to the WIRE FORMAT: ``pages[j]``
        holds the KV of token chunk ``tokens[j*ps:(j+1)*ps]`` (the
        prefix-tree granularity — callers export FULL prompt pages,
        ``plan.table[:plan.n_full]``). Each page's payload is the
        concatenated bytes of its slice of every store leaf, guarded
        by a CRC32 (zlib — the same checksum the ckpt footer uses), so
        a decode replica verifies before landing a single byte.
        Chained ``chunk_keys`` ride along: they ARE the router's
        affinity keys, so the wire and the prefix tree agree on what
        can hit."""
        import zlib

        import jax

        from tpuflow.infer.generate import paged_gather

        tokens = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.spec.page_size
        n = len(pages)
        if tokens.size != n * ps:
            raise ValueError(
                f"{n} pages need exactly {n * ps} tokens, got "
                f"{tokens.size}")
        wire = self.wire_header()
        payloads: List[bytes] = []
        crcs: List[int] = []
        if n:
            host = paged_gather(self.cache, [int(p) for p in pages])
            leaves = jax.tree_util.tree_leaves(host)
            for j in range(n):
                buf = b"".join(np.ascontiguousarray(leaf[j]).tobytes()
                               for leaf in leaves)
                payloads.append(buf)
                crcs.append(zlib.crc32(buf) & 0xFFFFFFFF)
        wire.update(
            n_pages=n, first_page=0,
            tokens=tokens.tolist(),
            chunk_keys=[k.hex() for k in chunk_keys(tokens, ps)],
            payloads=payloads, crc32=crcs,
        )
        self.exports += 1
        return wire

    def _check_header(self, wire: Dict[str, Any]) -> None:
        mine = self.wire_header()
        for key in ("version", "page_size", "quant", "leaves"):
            theirs = wire.get(key)
            if key == "leaves":
                theirs = [[list(s), str(d)] for s, d in (theirs or ())]
            if theirs != mine[key]:
                raise PageWireError(
                    f"wire {key} mismatch: got {theirs!r}, this store "
                    f"has {mine[key]!r} — exporter and importer must "
                    f"run the same model/spec")

    def import_chain(self, wire: Dict[str, Any]) -> int:
        """Verify and land one wire (or :func:`split_chain` chunk)
        into THIS store: every payload CRC is checked FIRST (nothing
        retained on any failure — the :class:`PageWireError` contract),
        chunks the prefix tree already holds are skipped (transfer
        dedup — the exporter shipped them because the router could not
        know), fresh pages are allocated (LRU-evicting unreferenced
        tree pages under pressure, exactly like :meth:`plan`), the
        payloads scatter in place (donated store), and the landed
        chain publishes into the prefix tree holding TREE-ONLY
        references — imported pages are LRU-evictable like any cached
        prefix, and the next admission matching the prompt completes
        as a narrow (width-1 at best) join. Returns pages landed."""
        import jax

        import zlib

        if self.prefix is None:
            raise PageWireError(
                "importer has no prefix cache — imported pages would "
                "be unreachable")
        self._check_header(wire)
        ps = self.spec.page_size
        tokens = np.asarray(wire["tokens"], np.int32).reshape(-1)
        first = int(wire.get("first_page", 0))
        n = int(wire["n_pages"])
        payloads = wire["payloads"]
        crcs = wire["crc32"]
        if len(payloads) != n or len(crcs) != n:
            raise PageWireError(
                f"wire carries {len(payloads)} payloads / {len(crcs)} "
                f"crcs for n_pages={n}")
        if tokens.size != (first + n) * ps:
            raise PageWireError(
                f"wire tokens cover {tokens.size} positions, chain "
                f"end needs {(first + n) * ps}")
        leaves, treedef = jax.tree_util.tree_flatten(self.cache)
        specs = [(tuple(leaf.shape[1:]), np.dtype(str(leaf.dtype)))
                 for leaf in leaves]
        page_nbytes = sum(int(np.prod(s)) * d.itemsize for s, d in specs)
        for j, (buf, crc) in enumerate(zip(payloads, crcs)):
            if len(buf) != page_nbytes:
                raise PageWireError(
                    f"page {first + j} payload is {len(buf)} bytes, "
                    f"store pages are {page_nbytes}")
            if zlib.crc32(buf) & 0xFFFFFFFF != int(crc):
                raise PageWireError(
                    f"page {first + j} payload failed its CRC — "
                    f"corrupt in transit")
        # dedup against what this store already caches: the match is
        # the same radix walk an admission would do
        full_pages, m_tok, _ = self.prefix.match(tokens)
        m_full = m_tok // ps
        if m_full < first:
            raise PageWireError(
                f"chain gap: this store holds {m_full} full pages of "
                f"the prefix but the chunk starts at page {first} — "
                f"an earlier chunk is missing or failed")
        start = max(first, m_full)
        end = first + n
        if start >= end:
            return 0  # everything already cached here
        n_new = end - start
        fresh = self.allocator.alloc(n_new)
        if fresh is None:
            short = n_new - self.allocator.free_count()
            self.prefix.evict_lru(short)
            fresh = self.allocator.alloc(n_new)
        if fresh is None:
            raise PageWireError(
                f"allocator dry: {n_new} pages short even after LRU "
                f"pressure — falling back to local prefill")
        # payload bytes -> per-leaf host arrays (k pages each)
        arrays = []
        for shape, dtype in specs:
            arrays.append(np.empty((n_new,) + shape, dtype))
        for i in range(n_new):
            buf = payloads[start - first + i]
            ofs = 0
            for li, (shape, dtype) in enumerate(specs):
                nb = int(np.prod(shape)) * dtype.itemsize
                arrays[li][i] = np.frombuffer(
                    buf, dtype, count=int(np.prod(shape)),
                    offset=ofs).reshape(shape)
                ofs += nb
        from tpuflow.infer.generate import paged_store_pages
        from tpuflow.obs import memory as _mem

        payload_tree = jax.tree_util.tree_unflatten(treedef, arrays)
        self.cache = paged_store_pages(self.cache, fresh, payload_tree)
        _mem.tag("kv_pages", self.cache)
        # publish: existing chain + fresh pages spell the full path;
        # the tree retains the fresh pages itself, so releasing OUR
        # allocation reference leaves them tree-only (LRU-evictable) —
        # and frees outright any page whose chunk was already present
        self.prefix.insert(tokens[: end * ps],
                           (full_pages + fresh)[:end])
        self.allocator.release(fresh)
        self.imports += 1
        return n_new

    def insert_prompt(self, prompt: np.ndarray, plan: PagePlan) -> int:
        """After the join prefill: publish the request's full prompt
        pages into the prefix tree (content for pages fully inside
        [0, p-1) is complete the moment the join dispatch lands)."""
        if self.prefix is None or plan.n_full == 0:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.spec.page_size
        return self.prefix.insert(prompt[:plan.n_full * ps],
                                  plan.table[:plan.n_full])

    def release(self, plan_or_pages) -> int:
        if isinstance(plan_or_pages, PagePlan):
            plan = plan_or_pages
            if plan.held_n and plan.budget_pages:
                # held-vs-budget sample: mean pages this request held
                # across its decode boundaries over its worst-case need
                mean_held = plan.held_sum / plan.held_n
                self._held_ratio_sum += mean_held / plan.budget_pages
                self._held_ratio_n += 1
                if plan.cap_budget_pages:
                    self._held_cap_sum += (mean_held
                                           / plan.cap_budget_pages)
                    self._held_cap_n += 1
            pages = plan.owned
        else:
            pages = plan_or_pages
        return self.allocator.release(pages)

    def held_vs_budget_mean(self) -> Optional[float]:
        """Mean over released plans of (mean pages held / worst-case
        budget) — < 1 is what incremental allocation buys; None before
        any decoded request released."""
        if not self._held_ratio_n:
            return None
        return self._held_ratio_sum / self._held_ratio_n

    def held_vs_cap_mean(self) -> Optional[float]:
        """Same numerator over the POOL-CAP worst case
        (``pages_needed(p, max_new_cap)`` — the per-slot provisioning
        a contiguous slab, or cap-budget reserve, must make). The
        capacity-planning view of the same saving."""
        if not self._held_cap_n:
            return None
        return self._held_cap_sum / self._held_cap_n

    # ---- accounting -------------------------------------------------
    def bytes_in_use(self) -> int:
        """Device bytes the allocated pages pin — the draft store's
        share included when speculation is on (a page costs both
        models' KV)."""
        return self.allocator.in_use() * (self.page_bytes
                                          + self.draft_page_bytes)

    def bytes_total(self) -> int:
        return self.allocator.total * (self.page_bytes
                                       + self.draft_page_bytes)

    def snapshot(self) -> Dict[str, Any]:
        hb = self.held_vs_budget_mean()
        hc = self.held_vs_cap_mean()
        out = {"page_size": self.spec.page_size,
               "quant": self.spec.quant or "none",
               "page_bytes": self.page_bytes,
               "kv_bytes_in_use": self.bytes_in_use(),
               "kv_bytes_total": self.bytes_total(),
               "page_extends": self.extends,
               "chain_exports": self.exports,
               "chain_imports": self.imports,
               "held_vs_budget_mean": (
                   None if hb is None else round(hb, 4)),
               "held_vs_cap_mean": (
                   None if hc is None else round(hc, 4))}
        if self.draft_cache is not None:
            out["draft_page_bytes"] = self.draft_page_bytes
        out.update(self.allocator.stats())
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out
