"""Paged KV memory management: allocator, prefix cache, COW forks.

The host-side policy half of the paged serve engine (ISSUE 6; the
device half — page-indexed gather/scatter attention and the paged
join/segment executables — lives in :mod:`tpuflow.infer.generate` and
:mod:`tpuflow.models.transformer`). Three pieces:

- :class:`PageAllocator` — a refcounted free-list over the physical
  pages of one :func:`~tpuflow.infer.generate.paged_kv_arrays` store.
  Page 0 is RESERVED as the write sink (masked device writes land
  there; it is never handed out), so ``pages - 1`` pages are usable.
  Freed-page events feed a sliding window so admission control can
  quote a Retry-After from the measured page FREE RATE instead of a
  queue-depth guess.

- :class:`PrefixCache` — a radix tree over page-sized token chunks
  mapping prompt prefixes to the page chains that already hold their
  KV. A request whose prompt shares a cached prefix SKIPS that part of
  its prefill entirely (the dominant pattern at scale: shared system
  prompts) and holds a refcount on the shared pages; the partial tail
  page of a match is reused COPY-ON-WRITE — the plan forks it onto a
  fresh page before the request's first divergent write, so the parent
  chain (and any request still decoding against it) is never touched.
  KV content at position j depends only on tokens [0..j] (positions
  are logical in the paged engine — no pads), which is exactly the
  property that makes token-prefix keyed sharing sound.

- :class:`PagedKV` — owns one device page store + allocator + prefix
  tree for one model, plans admissions (:meth:`PagedKV.plan` →
  :class:`PagePlan`), executes COW forks, and answers the memory
  accounting questions (bytes in use, bytes per live token) that
  ``tools/kv_memory_report.py`` and the ``serve.kv_*`` gauges quote.

Thread discipline: like the slot pools, ONE thread (the scheduler's)
may mutate the allocator/tree; read-only stat snapshots are safe from
other threads (single numpy/int reads).
"""

from __future__ import annotations

import math
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: physical page id reserved as the masked-write sink — never allocated,
#: never mapped into a live row's table beyond padding slots.
SINK_PAGE = 0


def pages_needed(prompt_len: int, max_new: int, page_size: int) -> int:
    """Worst-case pages for one request (no sharing): its KV spans
    positions [0, p + max_new - 1) — the last generated token's KV is
    never written. THE single definition: the planner, the scheduler's
    never-servable check, and the default store sizing must agree."""
    return math.ceil((prompt_len + max_new - 1) / page_size)


def initial_pages_needed(prompt_len: int, max_new: int, advance: int,
                         page_size: int) -> int:
    """Pages the INCREMENTAL reserve covers (ISSUE 11): the prompt
    plus the first ``advance`` decode tokens, clamped to the budget —
    KV positions ``[0, min(p-1+advance, p+max_new-1))``. THE single
    definition: :meth:`PagedKV.plan`'s ``initial_new`` branch and the
    scheduler's Retry-After hints must quote the same number, or
    clients back off against capacity admission would grant."""
    cover = min(prompt_len - 1 + max(1, int(advance)),
                prompt_len + max_new - 1)
    return max(1, math.ceil(cover / page_size))


def chunk_keys(tokens, page_size: int) -> List[bytes]:
    """Chained digests of the FULL ``page_size``-token chunks of
    ``tokens`` — ``keys[j]`` identifies the token prefix
    ``tokens[:(j+1)*page_size]`` exactly as :class:`PrefixCache`
    chunks it (node key = chunk bytes under its parent chain), so the
    multi-replica router's affinity table and a replica's prefix tree
    agree on what can hit. Callers wanting the CACHEABLE prefix of a
    prompt pass ``prompt[:p-1]`` (position p-1 is written by the
    request's own first decode step — :meth:`PagedKV.plan`). Pure
    host math: tokens in, digests out."""
    import hashlib

    tokens = np.asarray(tokens, np.int32).reshape(-1)
    ps = int(page_size)
    h = hashlib.blake2b(digest_size=16)
    out: List[bytes] = []
    for j in range(tokens.size // ps):
        h.update(tokens[j * ps:(j + 1) * ps].tobytes())
        out.append(h.digest())
    return out


#: wire-format version tag (ISSUE 14): importers reject anything else.
PAGE_WIRE_VERSION = 1


class PageWireError(RuntimeError):
    """A page-chain wire payload failed validation — CRC mismatch,
    header/shape mismatch, a chain gap, or an allocator too dry to
    land it. The importer retains NOTHING from the failing chunk; the
    serving-tier contract is a clean fallback to LOCAL prefill (the
    request decodes correctly either way — the transfer is purely a
    work-placement optimization), never a truncated stream."""


class PageStoreDry(PageWireError):
    """The importer's allocator could not cover a VALID wire even
    after LRU pressure. Split out from the corruption cases (ISSUE 16)
    so the promote path can tell "drop this spilled chain, it is bad"
    from "the store is merely full right now — keep the chain"."""


def split_chain(wire: Dict[str, Any], chunk_pages: int,
                trace_ctx: Optional[Dict[str, Any]] = None,
                ) -> List[Dict[str, Any]]:
    """Split one :meth:`PagedKV.export_chain` wire into transferable
    chunks of at most ``chunk_pages`` pages each. Every chunk carries
    the token PREFIX through its own end (the radix path the importer
    needs) plus only its own page payloads (``first_page`` says where
    they sit in the chain), so chunks stream independently and land
    one scheduler boundary at a time — the transfer-overlap half of
    the disaggregation story.

    ``trace_ctx`` (ISSUE 19) stamps distributed-trace metadata
    (``{"trace_id": ..., "parent_span": ...}``) onto every chunk under
    the ``trace`` key: the importer's landing spans join the sender's
    trace. The key is ignored by header validation and rides the JSON
    codec unchanged — wires from older builds simply lack it."""
    n = int(wire["n_pages"])
    cp = max(1, int(chunk_pages))
    if n <= cp:
        if not n:
            return []
        if trace_ctx is not None:
            wire = dict(wire)
            wire["trace"] = dict(trace_ctx)
        return [wire]
    ps = int(wire["page_size"])
    out = []
    for s in range(0, n, cp):
        e = min(n, s + cp)
        ch = {k: wire[k] for k in ("version", "page_size", "quant",
                                   "leaves")}
        ch.update(
            n_pages=e - s, first_page=s,
            tokens=wire["tokens"][: e * ps],
            chunk_keys=wire["chunk_keys"][:e],
            payloads=wire["payloads"][s:e],
            crc32=wire["crc32"][s:e],
        )
        if trace_ctx is not None:
            ch["trace"] = dict(trace_ctx)
        out.append(ch)
    return out


def wire_bytes(wire: Dict[str, Any]) -> int:
    """Payload bytes one wire (or chunk) ships — the unit of the
    ``serve.kv_transfer_bytes_total`` accounting."""
    return sum(len(p) for p in wire.get("payloads", ()))


def wire_to_json(wire: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-able form of a wire/chunk (payload bytes → base64) — what
    the HTTP replica transport ships."""
    import base64

    out = dict(wire)
    out["payloads"] = [base64.b64encode(p).decode("ascii")
                       for p in wire["payloads"]]
    return out


def wire_from_json(obj: Dict[str, Any]) -> Dict[str, Any]:
    import base64

    out = dict(obj)
    out["payloads"] = [base64.b64decode(p) for p in obj["payloads"]]
    return out


class TieredChainPool:
    """Host-RAM (and optional disk) spill tiers under one
    :class:`PagedKV` (ISSUE 16). Entries are whole page chains in the
    PR 14 WIRE FORMAT — the same self-describing, CRC-guarded unit the
    disaggregation transfers ship — keyed by the chain's deepest chunk
    key; an index from EVERY covered chunk key to its chain lets a
    lookup match any prefix depth (the wire truncates cleanly at page
    granularity). LRU within the pool under a byte budget: host
    overflow spills to ``disk_path`` when set (payloads land in one
    blob read back through ``mmap``), else the oldest chain drops.

    Thread discipline: demote (:meth:`PrefixCache.evict_lru` →
    ``on_evict``), promote (:meth:`PagedKV.plan`) and chain fetches
    all run on the scheduler thread; a lock still guards every mutation
    so foreign-thread :meth:`stats`/:meth:`report` reads (flight
    recorder, router directory sweep) are safe."""

    #: spill-file magic — a reader rejects anything else before parsing
    DISK_MAGIC = b"TPKV1\n"

    def __init__(self, host_bytes: int, *,
                 disk_path: Optional[str] = None,
                 disk_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        if host_bytes <= 0 and not disk_path:
            raise ValueError(
                "tiered pool needs a host byte budget > 0 and/or a "
                "disk path")
        self.host_bytes = int(max(0, host_bytes))
        self.disk_path = disk_path
        self.disk_bytes = None if disk_bytes is None else int(disk_bytes)
        self.clock = clock
        if disk_path:
            import os

            os.makedirs(disk_path, exist_ok=True)
        self._lock = threading.Lock()
        # head hex key -> entry; OrderedDict order IS the LRU order
        # (host and disk entries share one recency stream: a disk hit
        # is warmth too)
        self._entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        # chunk hex key -> (head hex, page index within that chain)
        self._index: Dict[str, Tuple[str, int]] = {}
        self._host_used = 0
        self._disk_used = 0
        # counters (cumulative; the serve metrics plane mirrors them)
        self.demotes = 0
        self.promotes = 0
        self.demoted_pages = 0
        self.promoted_pages = 0
        self.disk_spills = 0
        self.disk_loads = 0
        self.drops = 0  # chains evicted out of the hierarchy entirely
        self.corrupt_drops = 0

    # ---- internal helpers (callers hold _lock) ----------------------
    def _unindex(self, head: str) -> None:
        ent = self._entries.pop(head)
        for k in ent["keys"]:
            if self._index.get(k, (None,))[0] == head:
                del self._index[k]
        if ent["tier"] == "host":
            self._host_used -= ent["bytes"]
        else:
            self._disk_used -= ent["bytes"]
            if ent.get("path"):
                import os

                try:
                    os.unlink(ent["path"])
                except OSError:
                    pass

    def _spill_to_disk(self, head: str, ent: Dict[str, Any]) -> bool:
        """Host → disk: payloads into one blob behind a JSON header,
        written atomically (tmp + rename). Returns False (and the
        entry drops) on any write failure."""
        import json
        import os

        wire = ent["wire"]
        header = {k: v for k, v in wire.items() if k != "payloads"}
        header["payload_lens"] = [len(p) for p in wire["payloads"]]
        path = os.path.join(self.disk_path, f"{head}.kvchain")
        try:
            hb = json.dumps(header).encode("utf-8")
            with open(path + ".tmp", "wb") as f:
                f.write(self.DISK_MAGIC)
                f.write(len(hb).to_bytes(8, "big"))
                f.write(hb)
                for p in wire["payloads"]:
                    f.write(p)
            os.replace(path + ".tmp", path)
        except OSError:
            try:
                os.unlink(path + ".tmp")
            except OSError:
                pass
            return False
        ent["wire"] = None
        ent["path"] = path
        ent["tier"] = "disk"
        self._host_used -= ent["bytes"]
        self._disk_used += ent["bytes"]
        self.disk_spills += 1
        return True

    def _load_from_disk(self, ent: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Read one spilled chain back (payload blob through mmap).
        Returns None — and the caller drops the entry — when the file
        is missing/corrupt; payload CRCs are still verified later by
        :meth:`PagedKV.import_chain` (the end-to-end guard)."""
        import json
        import mmap
        import os

        try:
            with open(ent["path"], "rb") as f:
                with mmap.mmap(f.fileno(), 0,
                               access=mmap.ACCESS_READ) as mm:
                    if mm[: len(self.DISK_MAGIC)] != self.DISK_MAGIC:
                        return None
                    o = len(self.DISK_MAGIC)
                    hlen = int.from_bytes(mm[o:o + 8], "big")
                    o += 8
                    header = json.loads(mm[o:o + hlen].decode("utf-8"))
                    o += hlen
                    lens = header.pop("payload_lens")
                    payloads = []
                    for n in lens:
                        payloads.append(bytes(mm[o:o + n]))
                        o += n
        except (OSError, ValueError, KeyError):
            return None
        wire = dict(header)
        wire["payloads"] = payloads
        self.disk_loads += 1
        return wire

    def _enforce_budgets(self) -> None:
        while self._host_used > self.host_bytes:
            head = next((h for h, e in self._entries.items()
                         if e["tier"] == "host"), None)
            if head is None:
                break
            ent = self._entries[head]
            if not (self.disk_path and self._spill_to_disk(head, ent)):
                self._unindex(head)
                self.drops += 1
        if self.disk_bytes is not None:
            while self._disk_used > self.disk_bytes:
                head = next((h for h, e in self._entries.items()
                             if e["tier"] == "disk"), None)
                if head is None:
                    break
                self._unindex(head)
                self.drops += 1

    # ---- write side (demote) ----------------------------------------
    def covers(self, head_hex: str) -> bool:
        """Whether a chain ending at this chunk key is already held —
        the pre-export dedup check (skip the device gather)."""
        with self._lock:
            return head_hex in self._index

    def put(self, wire: Dict[str, Any]) -> bool:
        """Demote one exported chain into the host tier. A chain whose
        head chunk is already covered only refreshes LRU recency (a
        shallower chain is a prefix of a stored one — dedup)."""
        keys = list(wire.get("chunk_keys") or ())
        if not keys or not wire.get("n_pages"):
            return False
        head = keys[-1]
        nbytes = wire_bytes(wire)
        with self._lock:
            hit = self._index.get(head)
            if hit is not None:
                self._entries[hit[0]]["last_used"] = self.clock()
                self._entries.move_to_end(hit[0])
                return False
            ent = {"keys": keys, "wire": wire, "path": None,
                   "bytes": nbytes, "tier": "host",
                   "last_used": self.clock()}
            self._entries[head] = ent
            self._host_used += nbytes
            for j, k in enumerate(keys):
                # deeper chains win the index (a lookup through any of
                # their keys must reach the deepest coverage)
                self._index[k] = (head, j)
            self.demotes += 1
            self.demoted_pages += int(wire["n_pages"])
            self._enforce_budgets()
        return True

    # ---- read side (promote / fetch) --------------------------------
    def match(self, keys: List[bytes],
              min_pages: int = 1) -> Optional[Dict[str, Any]]:
        """Deepest stored coverage of a chunk-key chain, as an
        importable wire truncated to the matched depth — or None when
        nothing covers at least ``min_pages`` pages. A corrupt/missing
        disk entry drops silently (the caller recomputes — the
        PageWireError contract one level down)."""
        with self._lock:
            # index j covers j+1 pages, so the shallowest acceptable
            # index is min_pages - 1
            for j in range(len(keys) - 1, max(1, int(min_pages)) - 2, -1):
                hit = self._index.get(keys[j].hex())
                if hit is None:
                    continue
                head, idx = hit
                ent = self._entries.get(head)
                if ent is None:  # stale index row
                    del self._index[keys[j].hex()]
                    continue
                wire = ent["wire"]
                if wire is None:
                    wire = self._load_from_disk(ent)
                    if wire is None:
                        self._unindex(head)
                        self.corrupt_drops += 1
                        continue
                ent["last_used"] = self.clock()
                self._entries.move_to_end(head)
                n = idx + 1
                ps = int(wire["page_size"])
                out = {k: wire[k] for k in ("version", "page_size",
                                            "quant", "leaves")}
                out.update(
                    n_pages=n, first_page=0,
                    tokens=list(wire["tokens"][: n * ps]),
                    chunk_keys=list(wire["chunk_keys"][:n]),
                    payloads=list(wire["payloads"][:n]),
                    crc32=list(wire["crc32"][:n]),
                )
                return out
        return None

    def drop(self, head_hex: str, corrupt: bool = False) -> bool:
        """Remove one chain (the post-import-failure path: a CRC-bad
        spill must not be retried forever)."""
        with self._lock:
            hit = self._index.get(head_hex)
            if hit is None:
                return False
            self._unindex(hit[0])
            self.drops += 1
            if corrupt:
                self.corrupt_drops += 1
            return True

    def clear(self) -> int:
        """Drop every chain (disk files included) — the weight-swap
        invalidation path: spilled KV under NEW weights is garbage."""
        with self._lock:
            n = len(self._entries)
            for head in list(self._entries):
                self._unindex(head)
        return n

    # ---- read-only views --------------------------------------------
    def report(self) -> List[Dict[str, Any]]:
        """Per-chain ``{'keys': [hex...], 'tier': ...}`` rows — what a
        replica publishes to the router's tier-global directory."""
        with self._lock:
            return [{"keys": list(e["keys"]), "tier": e["tier"]}
                    for e in self._entries.values()]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            host_chains = sum(1 for e in self._entries.values()
                              if e["tier"] == "host")
            return {
                "host_bytes_budget": self.host_bytes,
                "host_bytes_used": self._host_used,
                "host_chains": host_chains,
                "disk_bytes_used": self._disk_used,
                "disk_chains": len(self._entries) - host_chains,
                "demotes": self.demotes,
                "promotes": self.promotes,
                "demoted_pages": self.demoted_pages,
                "promoted_pages": self.promoted_pages,
                "disk_spills": self.disk_spills,
                "disk_loads": self.disk_loads,
                "drops": self.drops,
                "corrupt_drops": self.corrupt_drops,
            }


@dataclass(frozen=True)
class PagedKVSpec:
    """Shape of one paged KV store: ``pages`` physical pages of
    ``page_size`` token slots each; ``quant='int8'`` stores pages as
    int8 with per-page scale vectors (≈4× smaller than f32 KV, 2× than
    bf16 — capacity doubles again on top of paging). ``kernel``
    selects the fused paged-attention decode kernel
    (:func:`tpuflow.ops.attention.paged_flash_decode`): ``None`` =
    auto (TPU backend only — off-TPU the portable einsum path stays
    the bitwise-pinned production path), ``True`` forces it (Pallas
    interpret mode off-TPU — what the kernel parity tests run),
    ``False`` never. int8 stores always take the portable path."""

    pages: int
    page_size: int = 16
    quant: Optional[str] = None  # None | 'int8'
    kernel: Optional[bool] = None  # fused decode kernel (None = auto)

    def __post_init__(self):
        if self.pages < 2:
            raise ValueError(
                f"pages must be >= 2 (page 0 is the reserved write "
                f"sink), got {self.pages}"
            )
        if self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}"
            )
        if self.quant not in (None, "int8"):
            raise ValueError(
                f"quant must be None or 'int8', got {self.quant!r}"
            )


class PageAllocator:
    """Refcounted free-list over ``pages`` physical pages (page 0
    reserved). ``alloc`` is all-or-nothing; ``release`` returns pages
    to the free list when their refcount reaches zero and records the
    free event for :meth:`free_rate`."""

    def __init__(self, pages: int, clock: Callable[[], float] = time.time,
                 free_window_s: float = 10.0):
        if pages < 2:
            raise ValueError(f"pages must be >= 2, got {pages}")
        self.pages = int(pages)
        self.clock = clock
        self.free_window_s = float(free_window_s)
        # LIFO free list: recently freed pages are re-used first (their
        # contents are hottest in any cache hierarchy)
        self._free: List[int] = list(range(1, self.pages))
        self.refs = np.zeros(self.pages, np.int64)
        self.refs[SINK_PAGE] = 1  # pinned forever
        # freed-event window shared with foreign readers (the HTTP
        # frontend quotes Retry-After from free_rate()) — everything
        # else in the allocator is scheduler-thread-only
        self._freed: "deque[Tuple[float, int]]" = deque()
        self._rate_lock = threading.Lock()
        self.allocs = 0
        self.frees = 0
        self.alloc_failures = 0

    # ---- capacity ---------------------------------------------------
    @property
    def total(self) -> int:
        """Usable pages (the sink is not one)."""
        return self.pages - 1

    def free_count(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        return self.total - len(self._free)

    # ---- alloc / refcounts ------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages with refcount 1, or None (all-or-nothing)
        if the free list is short."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        if n > len(self._free):
            self.alloc_failures += 1
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self.refs[p] = 1
        self.allocs += n
        return out

    def retain(self, pages) -> None:
        for p in pages:
            if self.refs[p] <= 0:
                raise RuntimeError(
                    f"retain of unallocated page {p} (refcount "
                    f"{int(self.refs[p])}) — use-after-free"
                )
            self.refs[p] += 1

    def release(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list. Returns the number of pages actually freed."""
        freed = 0
        for p in pages:
            if p == SINK_PAGE:
                raise RuntimeError("the sink page is never released")
            if self.refs[p] <= 0:
                raise RuntimeError(
                    f"release of free page {p} — double free"
                )
            self.refs[p] -= 1
            if self.refs[p] == 0:
                self._free.append(p)
                freed += 1
        if freed:
            self.frees += freed
            now = self.clock()
            with self._rate_lock:
                self._freed.append((now, freed))
                self._trim(now)
        return freed

    # ---- windowed free-rate (Retry-After math) ----------------------
    def _trim(self, now: float) -> None:
        horizon = now - self.free_window_s
        while self._freed and self._freed[0][0] < horizon:
            self._freed.popleft()

    def free_rate(self, now: Optional[float] = None) -> float:
        """Pages freed per second over the sliding window — the
        denominator of the out-of-pages Retry-After estimate. Safe
        from any thread."""
        now = self.clock() if now is None else now
        with self._rate_lock:
            self._trim(now)
            total = sum(n for _, n in self._freed)
        return total / max(self.free_window_s, 1e-9)

    def stats(self) -> Dict[str, float]:
        return {
            "pages_total": self.total,
            "pages_in_use": self.in_use(),
            "pages_free": self.free_count(),
            "allocs": self.allocs,
            "frees": self.frees,
            "alloc_failures": self.alloc_failures,
            "free_rate_per_s": round(self.free_rate(), 4),
        }


class _Node:
    __slots__ = ("tokens", "key", "page", "children", "parent",
                 "last_used")

    def __init__(self, tokens: np.ndarray, key: bytes, page: int,
                 parent: Optional["_Node"], last_used: float):
        self.tokens = tokens
        self.key = key
        self.page = page
        self.children: Dict[bytes, "_Node"] = {}
        self.parent = parent
        self.last_used = last_used


class PrefixCache:
    """Radix tree over page-sized token chunks → physical pages.

    Every node is one FULL page of prompt KV, keyed by that page's
    token chunk under its parent chain (so the path root→node spells
    the token prefix the page's KV was computed from). The tree holds
    one refcount per node page; requests matching a prefix add their
    own. Eviction is leaf-LRU, only of pages nobody else references —
    called when the allocator runs dry, never on the hot path."""

    def __init__(self, page_size: int, allocator: PageAllocator,
                 clock: Callable[[], float] = time.time):
        self.ps = int(page_size)
        self.alloc = allocator
        self.clock = clock
        self.root: Dict[bytes, _Node] = {}
        self.nodes = 0
        self.inserts = 0
        self.evictions = 0
        # demote hook (ISSUE 16): called as ``on_evict(tokens, pages,
        # last_used)`` with the ROOT→LEAF chain a leaf terminates,
        # just before :meth:`evict_lru` drops it — the spill tier's
        # entry point. NOT called from :meth:`clear` (invalidation
        # must discard, a weight swap makes the KV garbage).
        self.on_evict: Optional[Callable[
            [np.ndarray, List[int], float], None]] = None
        # guards tree-STRUCTURE mutation vs foreign-thread stats():
        # the flight recorder dumps kv_snapshot from its own thread at
        # trip/SIGTERM time, possibly mid-insert on the scheduler
        # thread — an unguarded dict walk would raise 'dictionary
        # changed size during iteration' exactly when the post-mortem
        # matters. match() stays lock-free (scheduler-thread-only).
        self._mutate_lock = threading.Lock()

    # ---- lookup -----------------------------------------------------
    def match(self, tokens: np.ndarray):
        """Longest cached prefix of ``tokens``. Returns ``(full_pages,
        matched_tokens, partial)``: the chain of fully matched pages,
        the token count they cover, and — when the next page's first
        ``q > 0`` tokens also match — ``(page, q)``, the COPY-ON-WRITE
        fork candidate (the caller duplicates that page and appends
        into its own copy; the shared parent is never written)."""
        tokens = np.asarray(tokens, np.int32)
        level = self.root
        pages: List[int] = []
        i = 0
        now = self.clock()
        while i + self.ps <= tokens.size:
            nd = level.get(tokens[i:i + self.ps].tobytes())
            if nd is None:
                break
            nd.last_used = now
            pages.append(nd.page)
            i += self.ps
            level = nd.children
        partial = None
        rem = tokens[i:]
        if rem.size:
            best_q, best_nd = 0, None
            for nd in level.values():
                n = min(rem.size, nd.tokens.size)
                neq = np.nonzero(nd.tokens[:n] != rem[:n])[0]
                q = int(neq[0]) if neq.size else n
                if q > best_q:
                    best_q, best_nd = q, nd
            if best_nd is not None:
                best_nd.last_used = now
                partial = (best_nd.page, best_q)
        return pages, i, partial

    # ---- insert -----------------------------------------------------
    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Register ``pages[j]`` as holding the KV of token chunk
        ``tokens[j*ps:(j+1)*ps]`` (under the preceding chunks). Chunks
        already present keep their EXISTING page (the caller's
        duplicate page stays private and dies with its request); new
        nodes retain their page on behalf of the tree. Returns the
        number of new nodes."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.size < len(pages) * self.ps:
            raise ValueError(
                f"{len(pages)} pages need {len(pages) * self.ps} "
                f"tokens, got {tokens.size}"
            )
        level = self.root
        parent = None
        new = 0
        now = self.clock()
        with self._mutate_lock:
            for j, pg in enumerate(pages):
                chunk = tokens[j * self.ps:(j + 1) * self.ps]
                key = chunk.tobytes()
                nd = level.get(key)
                if nd is None:
                    nd = _Node(chunk.copy(), key, int(pg), parent, now)
                    level[key] = nd
                    self.alloc.retain([int(pg)])
                    self.nodes += 1
                    new += 1
                else:
                    nd.last_used = now
                parent = nd
                level = nd.children
            self.inserts += new
        return new

    # ---- eviction ---------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out: List[_Node] = []
        stack = list(self.root.values())
        while stack:
            nd = stack.pop()
            if nd.children:
                stack.extend(nd.children.values())
            else:
                out.append(nd)
        return out

    def _drop(self, nd: _Node) -> None:
        # callers hold _mutate_lock
        siblings = nd.parent.children if nd.parent else self.root
        del siblings[nd.key]
        self.nodes -= 1
        self.evictions += 1
        self.alloc.release([nd.page])

    def evict_lru(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping least-recently-used
        LEAF nodes whose page only the tree references (refcount 1 —
        no live request shares it). Dropping a leaf can expose its
        parent as the next candidate."""
        freed = 0
        with self._mutate_lock:
            while freed < n_pages:
                # one tree walk + one sort per ROUND (a round drains
                # every current candidate; dropping leaves can expose
                # parents, which the next round picks up) — not one
                # full walk per page freed
                cands = sorted(
                    (nd for nd in self._leaves()
                     if self.alloc.refs[nd.page] == 1),
                    key=lambda x: x.last_used,
                )
                if not cands:
                    break
                for nd in cands:
                    if self.on_evict is not None:
                        self._offer_evicted(nd)
                    self._drop(nd)
                    freed += 1
                    if freed >= n_pages:
                        break
        return freed

    def _offer_evicted(self, nd: _Node) -> None:
        # caller holds _mutate_lock; spell out the root→leaf chain the
        # doomed leaf terminates — the spill tier needs a whole
        # importable unit, not one orphan page. Best-effort: eviction
        # must free pages even when the demote path fails.
        chain: List[_Node] = []
        cur: Optional[_Node] = nd
        while cur is not None:
            chain.append(cur)
            cur = cur.parent
        chain.reverse()
        try:
            self.on_evict(
                np.concatenate([c.tokens for c in chain]),
                [c.page for c in chain], nd.last_used)
        except Exception:
            pass

    def clear(self) -> int:
        """Release every tree reference (deepest first). Pages shared
        with live requests survive until those requests release them."""
        freed = 0
        # leaves-first teardown keeps the parent links consistent
        with self._mutate_lock:
            while self.root:
                for nd in self._leaves():
                    if self.alloc.refs[nd.page] == 1:
                        freed += 1
                    self._drop(nd)
                    self.evictions -= 1  # clear() is not an eviction
        return freed

    def stats(self) -> Dict[str, float]:
        """Safe from any thread (the flight recorder calls this on its
        own thread at trip/SIGTERM time)."""
        with self._mutate_lock:
            depth = 0
            stack = [(nd, 1) for nd in self.root.values()]
            while stack:
                nd, d = stack.pop()
                depth = max(depth, d)
                stack.extend((c, d + 1) for c in nd.children.values())
            return {
                "nodes": self.nodes,
                "max_depth": depth,
                "inserts": self.inserts,
                "evictions": self.evictions,
            }


@dataclass
class PagePlan:
    """One admission's page assignment (built by :meth:`PagedKV.plan`,
    grown by :meth:`PagedKV.extend`, consumed by
    ``PagedSlotPool.join``). Under incremental allocation (ISSUE 11)
    ``table`` starts at prompt + first-segment coverage and grows at
    segment boundaries; ``budget_pages`` records the worst-case need
    (what admission used to reserve up front) so the held-vs-budget
    accounting can show what incrementality saves."""

    table: List[int]  # page chain, position-ordered (shared + fresh)
    owned: List[int] = field(default_factory=list)  # refs THIS request holds
    start: int = 0  # m — KV positions already cached (prefill skips them)
    width: int = 0  # p - m suffix tokens still to write (incl. last)
    forks: List[Tuple[int, int]] = field(default_factory=list)  # (src, dst)
    n_full: int = 0  # leading pages that will hold a full prompt chunk
    matched_tokens: int = 0
    hit: bool = False
    budget_pages: int = 0  # worst-case pages_needed (the old reserve)
    # worst case at the POOL's max_new_cap — what a contiguous slab (or
    # the old reserve at cap) provisions per slot; set by the scheduler
    cap_budget_pages: int = 0
    held_sum: int = 0  # Σ len(table) over decode boundaries…
    held_n: int = 0  # …and the boundary count (mean held = sum/n)


class PagedKV:
    """One model's paged KV universe: device page store + allocator +
    prefix tree + the admission planner. Shared by every
    ``PagedSlotPool`` (all buckets) of one scheduler — that sharing is
    the point: admission asks THIS object for pages, not a per-bucket
    pool for a slot-shaped slab."""

    def __init__(self, model, spec: PagedKVSpec, *,
                 prefix_cache: bool = True,
                 clock: Callable[[], float] = time.time,
                 draft_model=None,
                 host_bytes: int = 0,
                 disk_path: Optional[str] = None,
                 disk_bytes: Optional[int] = None,
                 spill_min_pages: int = 2,
                 spill_max_idle_s: Optional[float] = None,
                 promote_min_pages: int = 2):
        from tpuflow.infer.generate import paged_kv_arrays, paged_page_bytes

        self.model = model
        self.spec = spec
        self.cache = paged_kv_arrays(model, spec)  # device pytree
        self.page_bytes = paged_page_bytes(self.cache)
        # speculative decoding (ISSUE 9): the draft model's KV lives in
        # a SECOND page store indexed by the SAME page tables — one
        # allocation covers both models' KV for a position, so plans,
        # refcounts, COW forks and releases need no draft-side twin.
        # Ledger component: kv_draft.
        self.draft_model = draft_model
        self.draft_cache = None
        self.draft_page_bytes = 0
        if draft_model is not None:
            self.draft_cache = paged_kv_arrays(draft_model, spec,
                                               component="kv_draft")
            self.draft_page_bytes = paged_page_bytes(self.draft_cache)
        self.allocator = PageAllocator(spec.pages, clock=clock)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(spec.page_size, self.allocator, clock=clock)
            if prefix_cache else None
        )
        # incremental-allocation accounting (ISSUE 11): per-segment
        # extend events, and the mean held-vs-budget ratio over
        # released plans — the number that says what incrementality
        # saves vs the old worst-case reserve (bench acceptance < 0.6)
        self.extends = 0
        # wire-transport counts (ISSUE 14): chains serialized out of /
        # landed into this store (per-call; pages/bytes ride the serve
        # metrics plane)
        self.exports = 0
        self.imports = 0
        # tiered hierarchy (ISSUE 16): host-RAM / disk spill pools
        # under this store. Demote rides the eviction hook (a chain
        # evict_lru would discard exports into the pool instead);
        # promote rides plan() (a spilled frontier deeper than the
        # resident match imports before prefill falls back). Off by
        # default — a budget of 0 and no disk path means no pool.
        self.clock = clock
        self.tier: Optional[TieredChainPool] = None
        self.spill_min_pages = max(1, int(spill_min_pages))
        self.spill_max_idle_s = spill_max_idle_s
        self.promote_min_pages = max(1, int(promote_min_pages))
        if host_bytes or disk_path:
            if self.prefix is None:
                raise ValueError(
                    "the tiered KV hierarchy spills/refills the prefix "
                    "tree — it needs prefix_cache=True")
            self.tier = TieredChainPool(
                int(host_bytes), disk_path=disk_path,
                disk_bytes=disk_bytes, clock=clock)
            self.prefix.on_evict = self._demote
        self._held_ratio_sum = 0.0
        self._held_ratio_n = 0
        self._held_cap_sum = 0.0
        self._held_cap_n = 0

    # ---- admission planning -----------------------------------------
    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        return pages_needed(prompt_len, max_new, self.spec.page_size)

    def plan(self, prompt: np.ndarray, max_new: int,
             initial_new: Optional[int] = None,
             use_prefix: bool = True) -> Optional[PagePlan]:
        """Match the prefix cache, fork the partial tail COW, allocate
        the fresh remainder — or return None when the allocator cannot
        cover it even after LRU-evicting unreferenced tree pages (the
        caller keeps the request QUEUED; nothing is retained on
        failure).

        ``initial_new`` (ISSUE 11, incremental allocation): reserve
        pages covering only the prompt plus the first ``initial_new``
        decode tokens instead of the full ``max_new`` budget — the
        scheduler passes its segment advance and grows the plan at
        later boundaries via :meth:`extend`, so a request holds pages
        proportional to tokens GENERATED. ``None`` keeps the original
        worst-case reserve (offline callers, warm-up).

        ``use_prefix=False`` skips the prefix-cache match (every page
        fresh and row-exclusive) — for callers that want wholesale
        private page chains (the ring landing path itself only ever
        writes a plan's private pages, so the serve scheduler plans
        ring admissions WITH the prefix and rings only the uncached
        suffix; this flag stays for direct callers)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = int(prompt.size)
        ps = self.spec.page_size
        # only positions [0, p-1) are reusable: position p-1 is written
        # by the request's own first decode step (which also produces
        # the logits its first sample needs)
        full_pages: List[int] = []
        m_full = 0
        partial = None
        if use_prefix and self.prefix is not None and p > 1:
            full_pages, m_tok, partial = self.prefix.match(prompt[:p - 1])
            m_full = m_tok // ps
            if self.tier is not None and self._promote(prompt[:p - 1],
                                                       m_full):
                # a spilled frontier landed (plan() only runs at a
                # scheduler boundary, so the promote lands like any
                # transfer — decode never stalls on it); re-match to
                # pick the deeper chain up
                full_pages, m_tok, partial = self.prefix.match(
                    prompt[:p - 1])
                m_full = m_tok // ps
        need_total = self.pages_needed(p, max_new)
        if initial_new is None:
            need_init = need_total
        else:
            # KV positions that must be writable before the first
            # extend opportunity: the join prefill writes [m, p-1) and
            # the first segment writes [p-1, p-1+initial_new), clamped
            # to the row's budget limit p + max_new - 1
            need_init = initial_pages_needed(p, max_new,
                                             int(initial_new), ps)
        n_fresh = need_init - len(full_pages)
        # retain the matched chain BEFORE any eviction/allocation: a
        # nearly-dry allocator may otherwise LRU-evict the very pages
        # we just matched (tree-only refcount 1) and hand them back as
        # this plan's FRESH pages — the same physical page would then
        # sit in the table as shared prefix AND prefill target
        self.allocator.retain(full_pages)
        fresh = self.allocator.alloc(n_fresh)
        if fresh is None and self.prefix is not None:
            short = n_fresh - self.allocator.free_count()
            self.prefix.evict_lru(short)
            fresh = self.allocator.alloc(n_fresh)
        if fresh is None:
            self.allocator.release(full_pages)
            return None
        m = m_full * ps
        forks: List[Tuple[int, int]] = []
        if partial is not None and partial[1] > 0:
            # COW: duplicate the partially matching page; the request
            # appends into ITS copy from offset q — the shared parent
            # (possibly mid-decode in another slot) is never written
            src, q = partial
            forks.append((int(src), int(fresh[0])))
            m += int(q)
        plan = PagePlan(
            table=full_pages + fresh,
            owned=full_pages + fresh,
            start=m,
            width=p - m,
            forks=forks,
            n_full=(p - 1) // ps,
            matched_tokens=m,
            hit=m > 0,
            budget_pages=need_total,
        )
        return plan

    def extend(self, plan: PagePlan, n: int) -> Optional[List[int]]:
        """Grow ``plan`` by ``n`` fresh pages at a segment boundary
        (incremental allocation, ISSUE 11) — LRU-evicting unreferenced
        prefix-tree pages under pressure exactly like :meth:`plan`.
        Returns the new pages (appended to the plan's table/owned), or
        None with NOTHING retained when the store is genuinely dry —
        the caller's cue to evict a row back to the queue instead of
        letting the pool deadlock."""
        if n < 1:
            return []
        fresh = self.allocator.alloc(n)
        if fresh is None and self.prefix is not None:
            short = n - self.allocator.free_count()
            self.prefix.evict_lru(short)
            fresh = self.allocator.alloc(n)
        if fresh is None:
            return None
        plan.table.extend(fresh)
        plan.owned.extend(fresh)
        self.extends += 1
        return fresh

    def execute_forks(self, plan: PagePlan) -> None:
        if plan.forks:
            from tpuflow.infer.generate import paged_copy
            from tpuflow.obs import memory as _mem

            src = [s for s, _ in plan.forks]
            dst = [d for _, d in plan.forks]
            self.cache = paged_copy(self.cache, src, dst)
            _mem.tag("kv_pages", self.cache)  # COW replaced the store
            if self.draft_cache is not None:
                # the draft store forks the SAME page ids: the shared
                # page table must stay valid for both models' KV
                self.draft_cache = paged_copy(self.draft_cache, src, dst)
                _mem.tag("kv_draft", self.draft_cache)

    def land_ring(self, plan: PagePlan, harvest, n_row_pages: int,
                  prompt_len: int) -> None:
        """Ring-prefill landing path (ISSUE 13): scatter a sequence-
        parallel prefill's per-layer K/V (the ``ring_kv`` collection
        from :func:`tpuflow.infer.generate.ring_prefill_kv`, logical
        token order) into this plan's PRIVATE pages — positions
        ``[matched_tokens//ps * ps, p-1)``; the plan's fully-matched
        shared prefix pages are never written (their slots redirect to
        the sink), a partially-matched tail page is the plan's own
        fresh page and the landing rewrites it wholesale (so the COW
        copy is unnecessary — the caller clears ``plan.forks``), and
        position p-1 is left to the row's first decode step as
        always. Page slots past the landed chain point at the write
        sink, and the tail page's slots beyond p-1 hold pad-token
        garbage every decode step overwrites before any read can see
        it (causal mask + write-before-read). Fixed shapes per pool:
        ONE compiled scatter regardless of prompt length."""
        from tpuflow.infer.generate import paged_land
        from tpuflow.obs import memory as _mem

        if self.spec.quant is not None:
            raise ValueError(
                "ring prefill does not combine with int8 pages yet — "
                "the harvest lands unquantized KV")
        ps = self.spec.page_size
        n_land = max(0, math.ceil((prompt_len - 1) / ps))
        start_page = int(plan.matched_tokens) // ps
        if n_land > len(plan.table):  # pragma: no cover - defensive
            raise RuntimeError(
                f"plan covers {len(plan.table)} pages < the "
                f"{n_land} the harvest lands")
        pages = np.zeros((int(n_row_pages),), np.int32)
        pages[start_page:n_land] = plan.table[start_page:n_land]
        self.cache = paged_land(self.cache, harvest, pages)
        _mem.tag("kv_pages", self.cache)

    # ---- wire format (ISSUE 14, prefill/decode disaggregation) ------
    def wire_header(self) -> Dict[str, Any]:
        """Self-describing store header: what an importer checks a
        wire against before touching its allocator — two stores
        inter-operate iff their page geometry, quantization and leaf
        shapes/dtypes agree (same model family, same spec)."""
        import jax

        leaves = jax.tree_util.tree_leaves(self.cache)
        return {
            "version": PAGE_WIRE_VERSION,
            "page_size": int(self.spec.page_size),
            "quant": self.spec.quant or "none",
            "leaves": [[list(leaf.shape[1:]), str(leaf.dtype)]
                       for leaf in leaves],
        }

    def export_chain(self, tokens, pages) -> Dict[str, Any]:
        """Serialize a page chain to the WIRE FORMAT: ``pages[j]``
        holds the KV of token chunk ``tokens[j*ps:(j+1)*ps]`` (the
        prefix-tree granularity — callers export FULL prompt pages,
        ``plan.table[:plan.n_full]``). Each page's payload is the
        concatenated bytes of its slice of every store leaf, guarded
        by a CRC32 (zlib — the same checksum the ckpt footer uses), so
        a decode replica verifies before landing a single byte.
        Chained ``chunk_keys`` ride along: they ARE the router's
        affinity keys, so the wire and the prefix tree agree on what
        can hit."""
        import zlib

        import jax

        from tpuflow.infer.generate import paged_gather

        tokens = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.spec.page_size
        n = len(pages)
        if tokens.size != n * ps:
            raise ValueError(
                f"{n} pages need exactly {n * ps} tokens, got "
                f"{tokens.size}")
        wire = self.wire_header()
        payloads: List[bytes] = []
        crcs: List[int] = []
        if n:
            host = paged_gather(self.cache, [int(p) for p in pages])
            leaves = jax.tree_util.tree_leaves(host)
            for j in range(n):
                buf = b"".join(np.ascontiguousarray(leaf[j]).tobytes()
                               for leaf in leaves)
                payloads.append(buf)
                crcs.append(zlib.crc32(buf) & 0xFFFFFFFF)
        wire.update(
            n_pages=n, first_page=0,
            tokens=tokens.tolist(),
            chunk_keys=[k.hex() for k in chunk_keys(tokens, ps)],
            payloads=payloads, crc32=crcs,
        )
        self.exports += 1
        return wire

    def _check_header(self, wire: Dict[str, Any]) -> None:
        mine = self.wire_header()
        for key in ("version", "page_size", "quant", "leaves"):
            theirs = wire.get(key)
            if key == "leaves":
                theirs = [[list(s), str(d)] for s, d in (theirs or ())]
            if theirs != mine[key]:
                raise PageWireError(
                    f"wire {key} mismatch: got {theirs!r}, this store "
                    f"has {mine[key]!r} — exporter and importer must "
                    f"run the same model/spec")

    def import_chain(self, wire: Dict[str, Any]) -> int:
        """Verify and land one wire (or :func:`split_chain` chunk)
        into THIS store: every payload CRC is checked FIRST (nothing
        retained on any failure — the :class:`PageWireError` contract),
        chunks the prefix tree already holds are skipped (transfer
        dedup — the exporter shipped them because the router could not
        know), fresh pages are allocated (LRU-evicting unreferenced
        tree pages under pressure, exactly like :meth:`plan`), the
        payloads scatter in place (donated store), and the landed
        chain publishes into the prefix tree holding TREE-ONLY
        references — imported pages are LRU-evictable like any cached
        prefix, and the next admission matching the prompt completes
        as a narrow (width-1 at best) join. Returns pages landed."""
        import jax

        import zlib

        if self.prefix is None:
            raise PageWireError(
                "importer has no prefix cache — imported pages would "
                "be unreachable")
        self._check_header(wire)
        ps = self.spec.page_size
        tokens = np.asarray(wire["tokens"], np.int32).reshape(-1)
        first = int(wire.get("first_page", 0))
        n = int(wire["n_pages"])
        payloads = wire["payloads"]
        crcs = wire["crc32"]
        if len(payloads) != n or len(crcs) != n:
            raise PageWireError(
                f"wire carries {len(payloads)} payloads / {len(crcs)} "
                f"crcs for n_pages={n}")
        if tokens.size != (first + n) * ps:
            raise PageWireError(
                f"wire tokens cover {tokens.size} positions, chain "
                f"end needs {(first + n) * ps}")
        leaves, treedef = jax.tree_util.tree_flatten(self.cache)
        specs = [(tuple(leaf.shape[1:]), np.dtype(str(leaf.dtype)))
                 for leaf in leaves]
        page_nbytes = sum(int(np.prod(s)) * d.itemsize for s, d in specs)
        for j, (buf, crc) in enumerate(zip(payloads, crcs)):
            if len(buf) != page_nbytes:
                raise PageWireError(
                    f"page {first + j} payload is {len(buf)} bytes, "
                    f"store pages are {page_nbytes}")
            if zlib.crc32(buf) & 0xFFFFFFFF != int(crc):
                raise PageWireError(
                    f"page {first + j} payload failed its CRC — "
                    f"corrupt in transit")
        # dedup against what this store already caches: the match is
        # the same radix walk an admission would do
        full_pages, m_tok, _ = self.prefix.match(tokens)
        m_full = m_tok // ps
        if m_full < first:
            raise PageWireError(
                f"chain gap: this store holds {m_full} full pages of "
                f"the prefix but the chunk starts at page {first} — "
                f"an earlier chunk is missing or failed")
        start = max(first, m_full)
        end = first + n
        if start >= end:
            return 0  # everything already cached here
        n_new = end - start
        fresh = self.allocator.alloc(n_new)
        if fresh is None:
            short = n_new - self.allocator.free_count()
            self.prefix.evict_lru(short)
            fresh = self.allocator.alloc(n_new)
        if fresh is None:
            raise PageStoreDry(
                f"allocator dry: {n_new} pages short even after LRU "
                f"pressure — falling back to local prefill")
        # payload bytes -> per-leaf host arrays (k pages each)
        arrays = []
        for shape, dtype in specs:
            arrays.append(np.empty((n_new,) + shape, dtype))
        for i in range(n_new):
            buf = payloads[start - first + i]
            ofs = 0
            for li, (shape, dtype) in enumerate(specs):
                nb = int(np.prod(shape)) * dtype.itemsize
                arrays[li][i] = np.frombuffer(
                    buf, dtype, count=int(np.prod(shape)),
                    offset=ofs).reshape(shape)
                ofs += nb
        from tpuflow.infer.generate import paged_store_pages
        from tpuflow.obs import memory as _mem

        payload_tree = jax.tree_util.tree_unflatten(treedef, arrays)
        self.cache = paged_store_pages(self.cache, fresh, payload_tree)
        _mem.tag("kv_pages", self.cache)
        # publish: existing chain + fresh pages spell the full path;
        # the tree retains the fresh pages itself, so releasing OUR
        # allocation reference leaves them tree-only (LRU-evictable) —
        # and frees outright any page whose chunk was already present
        self.prefix.insert(tokens[: end * ps],
                           (full_pages + fresh)[:end])
        self.allocator.release(fresh)
        self.imports += 1
        return n_new

    # ---- tiered hierarchy (ISSUE 16) --------------------------------
    def _demote(self, tokens: np.ndarray, pages: List[int],
                last_used: float) -> None:
        """Eviction hook: export a doomed tree chain into the spill
        pool instead of discarding its warmth. Gated by the warmth
        threshold — short chains (< ``spill_min_pages``) and chains
        idle past ``spill_max_idle_s`` are not worth the gather; a
        chain whose head the pool already covers is deduped BEFORE the
        device read. Runs on the scheduler thread under the tree's
        mutate lock (export reads device pages, never the tree)."""
        if self.tier is None or len(pages) < self.spill_min_pages:
            return
        if (self.spill_max_idle_s is not None
                and self.clock() - last_used > self.spill_max_idle_s):
            return
        ps = self.spec.page_size
        keys = chunk_keys(tokens, ps)
        if not keys or self.tier.covers(keys[-1].hex()):
            return
        self.tier.put(self.export_chain(tokens, pages))

    def _promote(self, prompt: np.ndarray, m_full: int) -> bool:
        """Prefix-miss path of :meth:`plan`: consult the spill pool
        for coverage deeper than the resident match and import the
        frontier before prefill falls back. Gated by the cost-table
        crossover ``promote_min_pages`` (the bench measures import vs
        recompute; 1-page promotes don't pay). A corrupt spill drops
        from the pool and the plan proceeds as a plain miss — nothing
        retained (the :class:`PageWireError` contract); a merely-dry
        store keeps the chain for a later attempt. Returns whether
        anything landed (the caller re-matches)."""
        ps = self.spec.page_size
        usable = (int(prompt.size) // ps) * ps
        if usable // ps - m_full < self.promote_min_pages:
            return False
        keys = chunk_keys(prompt[:usable], ps)
        hit = self.tier.match(
            keys, min_pages=m_full + self.promote_min_pages)
        if hit is None:
            return False
        try:
            landed = self.import_chain(hit)
        except PageStoreDry:
            return False
        except PageWireError:
            self.tier.drop(hit["chunk_keys"][-1], corrupt=True)
            return False
        self.tier.promotes += 1
        self.tier.promoted_pages += landed
        return landed > 0

    def chain_for(self, tokens) -> Optional[Dict[str, Any]]:
        """Deepest exportable coverage of a token prefix as ONE wire —
        the resident tree (re-exported) or a spilled chain, whichever
        reaches further. The donor side of a directory-routed
        cross-replica pull; scheduler thread only (device gather +
        radix walk). None when nothing covers a single full page."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.spec.page_size
        full_pages: List[int] = []
        m_full = 0
        if self.prefix is not None and tokens.size >= ps:
            full_pages, m_tok, _ = self.prefix.match(tokens)
            m_full = m_tok // ps
        if self.tier is not None and tokens.size >= ps:
            hit = self.tier.match(chunk_keys(tokens, ps),
                                  min_pages=m_full + 1)
            if hit is not None:
                return hit
        if m_full:
            return self.export_chain(tokens[:m_full * ps],
                                     full_pages[:m_full])
        return None

    def insert_prompt(self, prompt: np.ndarray, plan: PagePlan) -> int:
        """After the join prefill: publish the request's full prompt
        pages into the prefix tree (content for pages fully inside
        [0, p-1) is complete the moment the join dispatch lands)."""
        if self.prefix is None or plan.n_full == 0:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.spec.page_size
        return self.prefix.insert(prompt[:plan.n_full * ps],
                                  plan.table[:plan.n_full])

    def release(self, plan_or_pages) -> int:
        if isinstance(plan_or_pages, PagePlan):
            plan = plan_or_pages
            if plan.held_n and plan.budget_pages:
                # held-vs-budget sample: mean pages this request held
                # across its decode boundaries over its worst-case need
                mean_held = plan.held_sum / plan.held_n
                self._held_ratio_sum += mean_held / plan.budget_pages
                self._held_ratio_n += 1
                if plan.cap_budget_pages:
                    self._held_cap_sum += (mean_held
                                           / plan.cap_budget_pages)
                    self._held_cap_n += 1
            pages = plan.owned
        else:
            pages = plan_or_pages
        return self.allocator.release(pages)

    def held_vs_budget_mean(self) -> Optional[float]:
        """Mean over released plans of (mean pages held / worst-case
        budget) — < 1 is what incremental allocation buys; None before
        any decoded request released."""
        if not self._held_ratio_n:
            return None
        return self._held_ratio_sum / self._held_ratio_n

    def held_vs_cap_mean(self) -> Optional[float]:
        """Same numerator over the POOL-CAP worst case
        (``pages_needed(p, max_new_cap)`` — the per-slot provisioning
        a contiguous slab, or cap-budget reserve, must make). The
        capacity-planning view of the same saving."""
        if not self._held_cap_n:
            return None
        return self._held_cap_sum / self._held_cap_n

    # ---- accounting -------------------------------------------------
    def bytes_in_use(self) -> int:
        """Device bytes the allocated pages pin — the draft store's
        share included when speculation is on (a page costs both
        models' KV)."""
        return self.allocator.in_use() * (self.page_bytes
                                          + self.draft_page_bytes)

    def bytes_total(self) -> int:
        return self.allocator.total * (self.page_bytes
                                       + self.draft_page_bytes)

    def snapshot(self) -> Dict[str, Any]:
        hb = self.held_vs_budget_mean()
        hc = self.held_vs_cap_mean()
        out = {"page_size": self.spec.page_size,
               "quant": self.spec.quant or "none",
               "page_bytes": self.page_bytes,
               "kv_bytes_in_use": self.bytes_in_use(),
               "kv_bytes_total": self.bytes_total(),
               "page_extends": self.extends,
               "chain_exports": self.exports,
               "chain_imports": self.imports,
               "held_vs_budget_mean": (
                   None if hb is None else round(hb, 4)),
               "held_vs_cap_mean": (
                   None if hc is None else round(hc, 4))}
        if self.draft_cache is not None:
            out["draft_page_bytes"] = self.draft_page_bytes
        out.update(self.allocator.stats())
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        if self.tier is not None:
            out["tier"] = self.tier.stats()
        return out
