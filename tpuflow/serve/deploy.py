"""Zero-downtime continuous deployment (ISSUE 15): live weight
hot-swap through the router.

ROADMAP item 3 closes the train → checkpoint → serve loop with no
human and no downtime. Every ingredient already existed as a seam —
this module composes them:

- PR 10's sharded checkpoints publish ATOMICALLY (manifest-last, CRC
  verified), so a manifest's existence IS the promotion signal: the
  :class:`ModelWatcher` polls a checkpoint namespace and a newly
  published, verified manifest triggers a rollout — no registry
  service, no promote button;
- PR 10's re-slice pivot (``assemble_leaves`` → place under the
  template's own shardings) restores the new weights into a STANDBY
  replica's device buffers without recompiling anything: same config ⇒
  same shapes ⇒ same executables — the swap is a buffer refresh, and
  :func:`load_host_params` validates exactly that (every template
  leaf present with identical shape AND dtype) before a single byte
  moves, raising :class:`SwapMismatchError` loudly when the published
  config drifted from the loaded model (the full re-init path is a
  process restart — deliberately NOT automated here: a config change
  is a deployment decision, not a weight push);
- PR 8's drain machinery makes the traffic shift truncation-free: the
  :class:`DeploymentManager` blue/greens — activate the freshly
  swapped standby, drain ONE old-version replica (it finishes its
  admitted backlog; new submits route to the new version), recycle it
  as the next standby, repeat until the whole tier serves the new
  version. A version bump INVALIDATES cached KV (new weights ⇒ the
  old pages are garbage for the new model), so prefix warmth is
  rebuilt by REPLAYING the tier's hottest chain heads as prefill-only
  requests on the incoming replica (PR 14's ``submit_prefill``) —
  re-prefilled, never transferred;
- every replica carries a ``model_version`` ({step, digest, label}
  from the manifest) surfaced in ``load_snapshot()`` / ``/v1/metrics``
  / Prometheus / flight bundles, and ``Router.submit(pin_version=)``
  pins a request to a version for token-identical A/B during a
  rollout (the pinned stream id plus identical weights make outputs
  bitwise-reproducible per version).

Draft models (PR 9) ride the same machinery: ``target='draft'``
pushes a freshly distilled draft through the rotation so speculative
acceptance rises live without touching target weights.

The gc race (satellite): retention must never delete a manifest the
watcher has seen but not finished restoring — the watcher PINS the
manifest (:func:`tpuflow.ckpt.checkpoint.pin_checkpoint`) for the
whole rollout and ``gc_checkpoints`` skips pinned sets.

Everything here is pure host policy except the device placement
inside ``ServeScheduler.swap_weights`` — which runs only on quiescent
(standby / drained) replicas, preserving the device-thread discipline.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tpuflow.obs.gauges import Histogram, inc_counter, register_histogram

__all__ = [
    "DeployError",
    "SwapMismatchError",
    "manifest_version",
    "version_label",
    "load_host_params",
    "place_like",
    "ModelWatcher",
    "DeploymentManager",
]


class DeployError(RuntimeError):
    """A rollout step failed (replica died mid-roll, drain timed out).
    The tier is left SERVING — on whatever version mix it reached —
    and the failure is counted/annotated; a deploy must degrade to
    'not yet rolled', never to an outage."""


class SwapMismatchError(ValueError):
    """The published manifest's config does not match the loaded
    model (missing leaves, shape or dtype drift): the swap is refused
    LOUDLY before any buffer moves. A ValueError so the worker HTTP
    endpoint maps it to 400 — a config mismatch is a bad request, not
    a server fault; the fallback is a full re-init (process restart
    with the new config), which is a deployment decision."""


#: deploy-plane wall-clock histogram (one per process, all tiers):
#: begin() → finished, in ms — the number the README's standby-cost
#: sizing note quotes
deploy_ms = register_histogram("serve.deploy_ms", Histogram())


# ---- versions --------------------------------------------------------


def manifest_version(mpath: str) -> Dict[str, Any]:
    """``{step, digest, label}`` of a sharded-checkpoint manifest —
    the model version a replica carries after restoring it. The digest
    is content-derived (CRC32 over the manifest bytes, which already
    notarize every shard file's CRC), so a re-publish of identical
    weights at the same step is the SAME version (idempotence) while
    any weight change at the same step is a different one."""
    import os

    from tpuflow.ckpt.sharded import _crc32_file, manifest_step

    step = manifest_step(os.path.basename(mpath))
    if step is None:
        raise ValueError(f"{mpath}: not a sharded-checkpoint manifest")
    digest = f"{_crc32_file(mpath):08x}"
    return {"step": int(step), "digest": digest,
            "label": f"step{step}-{digest}"}


def version_label(version: "Optional[Dict[str, Any] | str]") -> Optional[str]:
    """The comparable string of a version in any of its spellings
    (dict / bare label / None) — what ``pin_version=`` matches on."""
    if version is None:
        return None
    if isinstance(version, str):
        return version
    return version.get("label")


def normalize_version(version: "Optional[Dict[str, Any] | str]"
                      ) -> Optional[Dict[str, Any]]:
    """Version in canonical dict form ({step, digest, label}); bare
    strings become ``{"label": s}``."""
    if version is None or isinstance(version, dict):
        return version
    return {"step": None, "digest": None, "label": str(version)}


# ---- manifest → placed params ---------------------------------------


def _flat_template(template_params: Any) -> Dict[str, Any]:
    from flax import serialization

    from tpuflow.ckpt.checkpoint import _unkey
    from tpuflow.ckpt.sharded import _flatten

    return _flatten(serialization.to_state_dict(_unkey(template_params)))


def load_host_params(mpath: str, template_params: Any) -> Dict[str, np.ndarray]:
    """Assemble the manifest leaves matching ``template_params`` as
    full host arrays, validating config compatibility LOUDLY first:
    every template leaf must exist in the manifest (bare, or under the
    ``params/`` prefix a TrainState checkpoint writes) with the exact
    shape and dtype the loaded model compiled against. Raises
    :class:`SwapMismatchError` listing the drift; on success the
    result keys match the template's flat keys."""
    from tpuflow.ckpt.sharded import assemble_leaves, load_manifest

    flat = _flat_template(template_params)
    man = load_manifest(mpath)
    leaves = man.get("leaves", {})
    prefix = None
    for cand in ("", "params/"):
        if all((cand + k) in leaves for k in flat):
            prefix = cand
            break
    if prefix is None:
        missing = [k for k in flat
                   if k not in leaves and ("params/" + k) not in leaves]
        raise SwapMismatchError(
            f"{mpath}: manifest is missing {len(missing)} model "
            f"leaves (config mismatch — a swap cannot reshape the "
            f"compiled model): {missing[:4]}"
            f"{'...' if len(missing) > 4 else ''}")
    drift = []
    for key, leaf in flat.items():
        meta = leaves[prefix + key]
        want_shape = tuple(int(d) for d in np.shape(leaf))
        want_dtype = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        got_shape = tuple(int(d) for d in meta.get("shape", ()))
        got_dtype = str(meta.get("dtype"))
        if got_shape != want_shape or got_dtype != want_dtype:
            drift.append(f"{key}: manifest {got_shape}/{got_dtype} "
                         f"vs loaded {want_shape}/{want_dtype}")
    if drift:
        raise SwapMismatchError(
            f"{mpath}: {len(drift)} leaves mismatch the loaded model "
            f"(shape/dtype drift — refuse the swap, re-init with the "
            f"new config instead): {drift[:4]}"
            f"{'...' if len(drift) > 4 else ''}")
    host = assemble_leaves(mpath, want=[prefix + k for k in flat])
    return {k: host[prefix + k] for k in flat}


def place_like(host: Dict[str, np.ndarray], template_params: Any) -> Any:
    """Flat host arrays → a params pytree shaped and DEVICE-PLACED
    like ``template_params`` (same tree, same shardings — the
    restore half of the swap; no recompile because nothing about the
    shapes changed)."""
    import jax
    from flax import serialization

    from tpuflow.ckpt.checkpoint import _rekey, _unkey
    from tpuflow.ckpt.sharded import _apply_flat
    from tpuflow.parallel.mesh import put_replicated

    template_sd = serialization.to_state_dict(_unkey(template_params))
    restored = serialization.from_state_dict(
        _unkey(template_params), _apply_flat(template_sd, dict(host)))
    restored = _rekey(template_params, restored)
    return jax.tree.map(
        lambda v, t: put_replicated(np.asarray(v), t.sharding)
        if hasattr(t, "sharding") else v,
        restored,
        template_params,
    )


def check_tree_compatible(template: Any, new: Any, what: str = "model") -> None:
    """Structure + shape + dtype equality of two params pytrees —
    the in-memory twin of :func:`load_host_params`'s manifest check
    (``swap_weights(params=...)`` callers hit this one)."""
    a, b = _flat_template(template), _flat_template(new)
    if set(a) != set(b):
        missing = sorted(set(a) - set(b))
        extra = sorted(set(b) - set(a))
        raise SwapMismatchError(
            f"{what} swap refused: leaf sets differ "
            f"(missing {missing[:3]}, unexpected {extra[:3]})")
    drift = [
        k for k in a
        if tuple(np.shape(a[k])) != tuple(np.shape(b[k]))
        or str(getattr(a[k], "dtype", np.asarray(a[k]).dtype))
        != str(getattr(b[k], "dtype", np.asarray(b[k]).dtype))
    ]
    if drift:
        raise SwapMismatchError(
            f"{what} swap refused: {len(drift)} leaves changed "
            f"shape/dtype: {drift[:4]}")


# ---- the watcher -----------------------------------------------------


class ModelWatcher:
    """Poll a checkpoint namespace for newly published sharded
    manifests and hand each verified one to ``on_manifest(mpath,
    version)`` — the promotion signal with no promoter.

    Discipline (unit-pinned, deterministically driven via
    :meth:`poll_once`):

    - only manifests with step STRICTLY above the last deployed step
      fire (a re-publish at the same step is idempotent — same step,
      nothing to do);
    - a manifest that fails :func:`verify_sharded` (corrupt manifest,
      missing/bit-flipped shard, PARTIAL set still landing) is
      SKIPPED this poll and re-checked next poll — a slow publisher
      finishes eventually, a genuinely corrupt set never fires;
    - the manifest is PINNED (:func:`tpuflow.ckpt.checkpoint.
      pin_checkpoint`) for the whole callback — and the
      DeploymentManager re-pins for the whole multi-rotation rollout
      — so retention (``gc_checkpoints``) can never delete a set
      mid-restore: the gc-vs-watcher race, closed;
    - a raising callback does NOT advance the deployed step (the
      next poll retries with a fresh verify); tier-side failures
      (rollout still active, wedged drain, replica death) retry
      indefinitely — they say nothing about the checkpoint;
    - after ``bad_after`` consecutive MANIFEST-shaped failures —
      verify failures or :class:`SwapMismatchError` (config drift) —
      against an UNCHANGED set, the step is remembered as BAD and no
      longer retried (counted on ``serve.deploy_bad_manifests_total``):
      a config-drifted or bit-flipped publish must not re-CRC the
      whole shard set and re-fail a rollout every poll forever. The
      failure count resets whenever the set's on-disk fingerprint
      (file sizes/mtimes) changes — and a blacklisted step whose set
      later changes is UN-blacklisted and retried — so a SLOW
      non-atomic publisher (rsync-style sync where the manifest lands
      before the shards finish) keeps being re-checked for as long as
      it keeps making progress: it finishes eventually and deploys.

    Drive it online (:meth:`start` — a daemon poll thread) or
    deterministically (:meth:`poll_once`)."""

    def __init__(
        self,
        checkpoint_dir: str,
        on_manifest: Callable[[str, Dict[str, Any]], Any],
        *,
        poll_s: float = 2.0,
        min_step: int = -1,
        bad_after: int = 8,
    ):
        self.checkpoint_dir = str(checkpoint_dir)
        self.on_manifest = on_manifest
        self.poll_s = float(poll_s)
        self.deployed_step = int(min_step)
        self.bad_after = int(bad_after)
        # per-step: (consecutive failures, set fingerprint the count
        # applies to) — a changed fingerprint resets the count.
        # _bad_steps maps step -> fingerprint AT blacklist time: a
        # later change to the set (the stalled publisher resumed,
        # someone re-published the step) un-blacklists it.
        self._step_fails: Dict[int, tuple] = {}
        self._bad_steps: Dict[int, tuple] = {}
        self.polls = 0
        self.fired = 0
        self.skipped_invalid = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _candidates(self) -> List[str]:
        from tpuflow.ckpt.sharded import (
            list_sharded_checkpoints,
            manifest_step,
        )
        import os

        out = []
        for mp in list_sharded_checkpoints(self.checkpoint_dir):
            step = manifest_step(os.path.basename(mp))
            if step is None or step <= self.deployed_step:
                continue
            bad_fp = self._bad_steps.get(step)
            if bad_fp is not None:
                # blacklisted — but a CHANGED set (stalled publisher
                # resumed, step re-published) earns a fresh start:
                # permanence would skip a valid checkpoint forever
                if self._set_fingerprint(step) == bad_fp:
                    continue
                del self._bad_steps[step]
                self._step_fails.pop(step, None)
            out.append(mp)
        return out

    def poll_once(self) -> Optional[str]:
        """One sweep: deploy the NEWEST verified undeployed manifest
        (skipping invalid sets); returns the manifest path deployed,
        or None. Never raises — a failing rollout is counted and
        retried next poll."""
        import os

        from tpuflow.ckpt.checkpoint import pin_checkpoint, unpin_checkpoint
        from tpuflow.ckpt.sharded import manifest_step, verify_sharded

        self.polls += 1
        for mpath in reversed(self._candidates()):  # newest first
            step = manifest_step(os.path.basename(mpath))
            # pin BEFORE verify: a retention sweep between verify and
            # restore is exactly the race this guard exists to close
            pin_checkpoint(mpath)
            try:
                if not verify_sharded(mpath):
                    # corrupt OR still landing: skip this poll
                    self.skipped_invalid += 1
                    self._record_step_failure(step)
                    continue
                version = manifest_version(mpath)
                try:
                    self.on_manifest(mpath, version)
                except Exception as e:
                    # the DeploymentManager already counted its own
                    # deploy_failures_total; this one counts callback
                    # breakage generally and keeps the step
                    # undeployed (the next poll retries). Only
                    # MANIFEST-shaped failures (config drift) count
                    # toward the static-set blacklist — tier-side
                    # failures (rollout still active, wedged drain,
                    # replica death) say nothing about the
                    # checkpoint and must keep being retried
                    inc_counter("serve.deploy_watch_errors_total")
                    if isinstance(e, SwapMismatchError):
                        self._record_step_failure(step)
                    return None
                self.deployed_step = step
                self.fired += 1
                self._step_fails.pop(step, None)
                return mpath
            finally:
                unpin_checkpoint(mpath)
        return None

    def _set_fingerprint(self, step: int):
        """Cheap progress signal for one step's file set (sizes +
        mtimes of everything named for the step): a slow non-atomic
        publisher keeps changing it, a corrupt/drifted static set
        does not."""
        import os

        out = []
        try:
            for fn in sorted(os.listdir(self.checkpoint_dir)):
                # our OWN pin sidecar is rewritten every poll — it is
                # observer machinery, not publisher progress, and
                # including it would defeat unchanged-set detection
                if f"step-{step}." in fn and ".pin-" not in fn:
                    try:
                        st = os.stat(os.path.join(self.checkpoint_dir,
                                                  fn))
                        out.append((fn, st.st_size, st.st_mtime_ns))
                    except OSError:
                        out.append((fn, -1, -1))
        except OSError:
            pass
        return tuple(out)

    def _record_step_failure(self, step: int) -> None:
        fp = self._set_fingerprint(step)
        n, prev_fp = self._step_fails.get(step, (0, None))
        # progress since the last failure (files grew/landed/were
        # re-published): start the count over — only an UNCHANGED set
        # that keeps failing is genuinely bad
        n = n + 1 if fp == prev_fp else 1
        self._step_fails[step] = (n, fp)
        if n >= self.bad_after:
            self._bad_steps[step] = fp
            inc_counter("serve.deploy_bad_manifests_total")

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:
                    pass  # the poll must never die
                self._stop.wait(self.poll_s)

        self._thread = threading.Thread(
            target=loop, name="tpuflow-model-watcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---- the rollout -----------------------------------------------------


class DeploymentManager:
    """Router-driven blue/green rollout over one standby replica.

    The tier runs N active replicas plus one STANDBY (registered with
    the router but excluded from placement). A weight push rotates:

    1. **swap** — restore the manifest into the standby's device
       buffers (``replica.swap_from_manifest``: config validated,
       same executables, prefix cache cleared — a version bump
       invalidates cached KV);
    2. **warm** — replay the router's hottest chain heads onto the
       standby as prefill-only requests (PR 14's ``submit_prefill``),
       re-prefilling — not transferring — so the first real requests
       land on a warm tree;
    3. **shift** — activate the standby (placement now prefers it as
       least-loaded) and mark ONE old-version replica draining: its
       admitted backlog finishes (zero truncated streams), new
       submits see only live replicas (drain 503s are the router's
       normal shed surface, nothing new);
    4. **recycle** — once the drained replica idles, it becomes the
       next standby; repeat from 1 until every active replica serves
       the new version, then finish (counters, ``deploy_ms``, flight
       note).

    The rollout is a STATE MACHINE advanced by :meth:`tick` — wire it
    into the router's maintenance cadence (online) or interleave it
    with replica steps (offline tests); :meth:`deploy` is the
    blocking convenience for scripts. ``target='draft'`` pushes draft
    weights through the same rotation (speculative acceptance rises
    live; target weights untouched).

    With a ``canary`` policy (ISSUE 20) the FIRST rotation becomes a
    judged canary window: after the new version activates, the old
    replica is NOT retired yet — both serve traffic while a
    :class:`~tpuflow.serve.canary.CanaryScorer` compares their
    per-version metric cuts window by window. ``retire_old`` proceeds
    with the normal rotation (later rotations skip re-scoring — the
    version is proven); ``retire_new`` ROLLS BACK: the new replica
    drains through the same zero-truncation machinery and recycles as
    standby, the rollout finishes degraded (the watcher sees a failed
    push, never a deployed version), and the tier keeps serving old
    throughout."""

    def __init__(self, router, *, replay_hot: int = 8,
                 drain_timeout_s: float = 300.0,
                 canary=None,
                 clock: Callable[[], float] = time.time):
        self.router = router
        self.replay_hot = int(replay_hot)
        self.drain_timeout_s = float(drain_timeout_s)
        self.canary = canary  # Optional[tpuflow.serve.canary.CanaryPolicy]
        self.clock = clock
        self._lock = threading.Lock()
        # serializes tick() bodies: the router's maintenance thread
        # and a blocking deploy() may both pump the state machine
        self._tick_lock = threading.Lock()
        self._state: Optional[Dict[str, Any]] = None
        self.history: List[Dict[str, Any]] = []

    # -- introspection -------------------------------------------------
    @property
    def active(self) -> bool:
        with self._lock:
            return self._state is not None

    def state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return None if self._state is None else dict(
                self._state, mpath=self._state["mpath"])

    # -- rollout -------------------------------------------------------
    def _standby_idx(self) -> int:
        sb = self.router.standby_indices()
        if not sb:
            raise DeployError(
                "no standby replica: construct the Router with "
                "standby=(i,) (or set_standby) to enable rollouts")
        return sb[0]

    def _old_version_actives(self, label: str, target: str) -> List[int]:
        out = []
        for i in self.router.active_indices():
            if version_label(self.router.replica_version(
                    i, target=target)) != label:
                out.append(i)
        return out

    def _swap_and_activate(self, st: Dict[str, Any]) -> None:
        """One rotation's swap+warm+shift: standby gets the new
        weights, replays hot heads, goes active; one old-version
        replica starts draining. When NO active replica is on an old
        version (operator retry of an already-live push), the standby
        is swapped but stays PARKED — activating it would consume the
        tier's only standby on a no-op and leave nothing for the next
        real push."""
        old = self._old_version_actives(st["label"], st["target"])
        idx = self._standby_idx()
        rep = self.router.replicas[idx]
        rep.swap_from_manifest(st["mpath"], draft=(st["target"] == "draft"))
        if not old:
            st["old_idx"] = None
            return
        # the standby may be a recycled (drained → closed) replica:
        # reopen + restart its loop before traffic shifts to it
        try:
            rep.reopen()
        except Exception:
            pass
        if st["online"]:
            rep.start()
        # warm: replay the hottest chain heads as prefill-only
        # requests — re-prefill (the version bump invalidated any
        # cached KV), never transfer. Best-effort: a replica without
        # the prefill surface (contiguous KV, speculation) just
        # starts cold.
        replayed = 0
        for toks in self.router.hot_heads(self.replay_hot):
            try:
                rep.submit_prefill(np.asarray(toks, np.int32))
                replayed += 1
            except Exception:
                break
        st["replayed"] += replayed
        self.router.activate(idx)
        st["activated"].append(idx)
        st["old_idx"] = old[0]
        if self.canary is not None and not st.get("canary_done"):
            # canary window (ISSUE 20): hold the retirement — old and
            # new both serve while the scorer compares their version
            # cuts; _tick acts on the verdict
            from tpuflow.serve.canary import CanaryScorer

            old_version = self.router.replica_version(
                old[0], target=st["target"])
            st["canary"] = CanaryScorer(
                self.router, old_label=version_label(old_version),
                new_label=st["label"], policy=self.canary,
                clock=self.clock)
            st["canary"].begin()
            st["new_idx"] = idx
            st["drain_t0"] = None
            return
        st["drain_t0"] = self.clock()
        self.router.begin_retire(old[0])

    def begin(self, mpath: str, *, target: str = "model",
              online: Optional[bool] = None) -> Dict[str, Any]:
        """Start a rollout of ``mpath``. Raises
        :class:`SwapMismatchError` (config drift — counted, tier
        untouched) or :class:`DeployError` (rollout already active /
        no standby). Returns the version dict."""
        if target not in ("model", "draft"):
            raise ValueError(f"target must be 'model' or 'draft', "
                             f"got {target!r}")
        version = manifest_version(mpath)
        # pin for the WHOLE rollout, not just this call: rotations
        # 2..N re-read the manifest from tick() long after the
        # watcher's own pin released — retention must stay off the
        # set until _finish (which unpins on every path)
        from tpuflow.ckpt.checkpoint import pin_checkpoint

        with self._lock:
            if self._state is not None:
                raise DeployError(
                    f"rollout of {self._state['label']} still active")
            pin_checkpoint(mpath)
            self._state = st = {
                "mpath": str(mpath), "target": target,
                "version": version, "label": version["label"],
                "t0": self.clock(), "wall_t0": time.perf_counter(),
                "old_idx": None, "drain_t0": None,
                "activated": [], "recycled": [], "replayed": 0,
                "online": (self.router.is_online()
                           if online is None else bool(online)),
            }
        try:
            self._swap_and_activate(st)
        except Exception as e:
            self._finish(st, error=f"{type(e).__name__}: {e}")
            raise
        self.router.metrics.event("-deploy-", "deploy_begin",
                                  version=version["label"],
                                  target=target)
        if st["old_idx"] is None:
            self._finish(st)
        return version

    def tick(self) -> bool:
        """Advance the state machine one step (cheap; call from the
        maintenance cadence). Returns True while a rollout is
        active. Concurrent tickers (maintenance thread + a blocking
        :meth:`deploy`) serialize; the loser skips the beat."""
        if not self._tick_lock.acquire(blocking=False):
            return self.active
        try:
            return self._tick()
        finally:
            self._tick_lock.release()

    def _tick(self) -> bool:
        with self._lock:
            st = self._state
        if st is None:
            return False
        scorer = st.get("canary")
        if scorer is not None and not st.get("canary_done"):
            verdict = scorer.tick()
            if verdict is None:
                return True  # window still open — keep serving both
            st["canary_done"] = True
            st["canary_summary"] = scorer.summary()
            self.router.metrics.event(
                "-deploy-", "canary_verdict", version=st["label"],
                verdict=verdict,
                reasons=scorer.reasons()[:4] or None)
            if verdict == "retire_new":
                # ROLLBACK: drain the NEW replica through the same
                # zero-truncation machinery a rotation uses on old
                # ones; the old replica was never retired and keeps
                # serving — the tier never rotates past the canary
                st["rolled_back"] = True
                st["old_idx"] = st["new_idx"]
                st["drain_t0"] = self.clock()
                self.router.begin_retire(st["new_idx"])
            else:
                st["drain_t0"] = self.clock()
                self.router.begin_retire(st["old_idx"])
            return True
        old = st["old_idx"]
        if old is None:
            return False
        rep = self.router.replicas[old]
        try:
            drained = rep.idle()
        except Exception:
            drained = True  # a dead replica has nothing left to drain
        timed_out = (st["drain_t0"] is not None
                     and self.clock() - st["drain_t0"]
                     > self.drain_timeout_s)
        if not drained and not timed_out:
            return True
        if timed_out and not drained:
            # the old replica is wedged mid-drain: leave it retired
            # (not recycled) and finish on the replicas we did move —
            # a deploy degrades, never hangs the tier
            self.router.retire(old)
            self._finish(st, error=f"drain of replica {old} timed out "
                                   f"after {self.drain_timeout_s:g}s")
            return False
        self.router.recycle_as_standby(old)
        st["recycled"].append(old)
        st["old_idx"] = None
        if st.get("rolled_back"):
            # the drained replica was the NEW one: rollback complete —
            # finish degraded so deploy()/the watcher see a FAILED
            # push, never a deployed version
            reasons = (st.get("canary_summary") or {}).get("reasons", [])
            why = "; ".join(reasons[:3]) or "canary breach"
            self._finish(st, error=f"canary retired new version: {why}")
            return False
        remaining = self._old_version_actives(st["label"], st["target"])
        if remaining:
            try:
                self._swap_and_activate(st)
            except Exception as e:
                self._finish(st, error=f"{type(e).__name__}: {e}")
                raise
            return True
        self._finish(st)
        return False

    def deploy(self, mpath: str, *, target: str = "model",
               timeout_s: float = 600.0, poll_s: float = 0.05,
               drive: Optional[Callable[[], Any]] = None) -> Dict[str, Any]:
        """Blocking convenience: :meth:`begin` + :meth:`tick` until
        the rollout finishes (``drive`` pumps an offline tier between
        ticks). Returns the version dict on a CLEAN finish; a rollout
        that finished degraded (wedged drain → retire, mid-roll
        replica death) raises :class:`DeployError` — callers like the
        watcher must see a partial roll as a failure to retry, never
        as a deployed version."""
        version = self.begin(mpath, target=target,
                             online=(drive is None) or None)
        deadline = time.monotonic() + timeout_s
        while self.active:
            if drive is not None:
                drive()
            self.tick()
            if not self.active:
                break
            if time.monotonic() > deadline:
                raise DeployError(
                    f"rollout of {version['label']} still active "
                    f"after {timeout_s:g}s")
            if drive is None:
                time.sleep(poll_s)
        err = self.history[-1]["error"] if self.history else None
        if err is not None:
            raise DeployError(
                f"rollout of {version['label']} finished degraded: "
                f"{err}")
        return version

    def abort(self, reason: str = "aborted") -> None:
        """Drop an active rollout's bookkeeping (the tier keeps
        whatever mix it reached; a retired-but-undrained replica is
        recycled as standby so the NEXT rollout still has one)."""
        with self._lock:
            st = self._state
        if st is None:
            return
        # only a replica whose RETIREMENT began needs recycling; in a
        # canary scoring window old_idx is still an ACTIVE replica
        # (drain_t0 None) and must keep serving
        if st["old_idx"] is not None and st["drain_t0"] is not None:
            try:
                self.router.recycle_as_standby(st["old_idx"])
            except Exception:
                pass
        self._finish(st, error=reason)

    # -- bookkeeping ---------------------------------------------------
    def _finish(self, st: Dict[str, Any], error: Optional[str] = None) -> None:
        from tpuflow.ckpt.checkpoint import unpin_checkpoint
        from tpuflow.obs import flight

        with self._lock:
            if self._state is not st:
                return
            self._state = None
        unpin_checkpoint(st["mpath"])
        ms = (time.perf_counter() - st["wall_t0"]) * 1e3
        noop = error is None and not st["activated"]
        rec = {
            "version": st["label"],
            "target": st["target"],
            "ts": st["t0"],
            "deploy_ms": round(ms, 3),
            "activated": list(st["activated"]),
            "recycled": list(st["recycled"]),
            "replayed_heads": st["replayed"],
            "noop": noop,
            "error": error,
        }
        if st.get("canary_summary") is not None:
            rec["canary"] = st["canary_summary"]
            rec["rolled_back"] = bool(st.get("rolled_back"))
        self.history.append(rec)
        del self.history[:-16]
        if st.get("rolled_back"):
            # a rollback is a PROTECTIVE failure: counted apart from
            # mechanical deploy failures so a dashboard can tell "the
            # canary saved us" from "the swap machinery broke"
            inc_counter("serve.deploy_rollbacks_total")
        if error is not None:
            inc_counter("serve.deploy_failures_total")
        elif noop:
            # the version was already live: no traffic moved — a
            # distinct counter, and NO deploy_ms sample (near-zero
            # no-op walls would skew the rollout-duration histogram)
            inc_counter("serve.deploys_noop_total")
        else:
            inc_counter("serve.deploys_total")
            deploy_ms.observe(ms)
        # post-mortems must show WHICH version was live (and when it
        # became so): a bounded history note on every future bundle
        flight.append_note("deploy", rec)
        self.router.metrics.event(
            "-deploy-", "deploy_finish" if error is None
            else "deploy_failed", version=st["label"],
            target=st["target"], deploy_ms=round(ms, 3), error=error)
