"""Slot-level continuous-batching scheduler — the serving control loop.

Replaces wave-drain granularity (packaging.lm.generate_text's
``serve_slots`` waves: a finished wave frees ALL its slots at once)
with slot granularity: a fixed pool of decode slots per prompt-length
bucket, where each finished row frees its slot at the next decode-
SEGMENT boundary and the head of the queue prefills into it mid-flight
(Orca-style iteration-level scheduling, expressed through the bucketed
pad_lens machinery that keeps every shape compile-stable — see
tpuflow.infer.generate's serve engine).

One scheduler owns:

- the **admission queue** — bounded; :meth:`submit` raises
  :class:`~tpuflow.serve.request.QueueFull` with a retry-after hint
  when it is at capacity (backpressure, mapped to HTTP 429 upstream);
- **per-bucket slot pools** (created lazily) and the boundary loop:
  sweep deadlines/cancellations → admit into freed slots → run one
  decode segment → stream new tokens → harvest finished rows;
- the **request lifecycle**: deadline expiry in queue AND mid-decode,
  cancellation that frees the slot for immediate reuse, streaming
  callbacks at segment boundaries, terminal events that unblock
  ``Request.result()``.

Determinism contract: a request's sampling stream id is its per-bucket
admission index mod ``slots`` — exactly the physical row index the
wave-drained path would have given it — and its logical RNG steps are
pad-free, so the scheduler's outputs are TOKEN-IDENTICAL to
``generate_text(..., serve_slots=slots, scheduler='wave')`` under
pinned seeds (tests/test_serve.py pins this; greedy and sampled).

Drive it either offline (``run_until_idle()`` on the calling thread —
what ``generate_text(scheduler='slot')`` does) or online (``start()``
spawns the scheduler thread; ``submit`` is thread-safe; the HTTP
frontend in tpuflow.serve.http sits on top).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence

import numpy as np

from tpuflow.obs import trace
from tpuflow.obs import health as _health
from tpuflow.serve.metrics import ServeMetrics
from tpuflow.serve.pages import PagedKV, PagedKVSpec, pages_needed
from tpuflow.serve.request import (
    QueueFull,
    Request,
    RequestState,
    SchedulerClosed,
)
from tpuflow.serve.slots import PagedSlotPool, SlotPool


class ServeScheduler:
    """Online serving runtime over one model's decode slot pools.

    Gauges publish process-wide under ``serve.*`` by default; a process
    running SEVERAL schedulers (multi-model serving) should give each
    its own namespace — ``metrics=ServeMetrics(gauge_prefix="serve.b")``
    — or their occupancy/queue gauges overwrite each other last-writer-
    wins in the shared obs registry."""

    def __init__(
        self,
        model,
        params,
        tokenizer=None,
        *,
        slots: int = 4,
        seg: int = 8,
        rounds: int = 3,
        max_new_cap: int = 64,
        max_queue: int = 64,
        max_bucket: int = 1024,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
        metrics: Optional[ServeMetrics] = None,
        clock: Callable[[], float] = time.time,
        kv: str = "contiguous",
        kv_pages: Optional[int] = None,
        kv_page_size: int = 16,
        kv_quant: Optional[str] = None,
        kv_kernel: Optional[bool] = None,
        kv_prefix_cache: bool = True,
        kv_prefix_insert_generated: bool = True,
        kv_host_bytes: int = 0,
        kv_disk_path: Optional[str] = None,
        kv_spill_min_pages: int = 2,
        kv_promote_min_pages: int = 2,
        speculate_k: int = 0,
        draft_model=None,
        draft_params=None,
        moe_capacity_factor: float = 1.25,
        moe_overflow: str = "queue",
        prefill_budget_tokens: Optional[int] = None,
        ring_prefill: Optional[int] = None,
        ring_prefill_min_tokens: int = 512,
        replica_class: str = "mixed",
        watchdog=None,
        transfer_wait_s: float = 30.0,
        model_version=None,
    ):
        """``kv='paged'`` switches the KV memory model (ISSUE 6): one
        process-wide store of ``kv_pages`` fixed-size pages
        (``kv_page_size`` tokens each) shared by EVERY bucket's slot
        pool through per-row page tables — KV bytes scale with live
        tokens, not ``buckets × slots × horizon`` — with copy-on-write
        prefix sharing (``kv_prefix_cache``: requests with a cached
        prompt prefix skip that prefill) and opt-in
        ``kv_quant='int8'`` pages. Admission asks the page ALLOCATOR:
        when it runs dry the head request stays QUEUED (Retry-After
        quoted from the windowed page free-rate) instead of being
        bucket-pool rejected; cancel/expiry frees a request's pages
        the same boundary. ``kv_pages=None`` sizes the store for about
        4×``slots`` concurrent worst-case requests.

        ``prefill_budget_tokens`` (ISSUE 13, the chunked-prefill SLO
        knob, CLI ``--prefill-slo``): a join whose uncached prompt
        suffix exceeds this many tokens is admitted as a CHUNKED
        prefill — at most one chunk of at most this many KV positions
        is dispatched per scheduler boundary, interleaved with the
        other rows' decode segments, so one long prompt stops stalling
        every in-flight row's inter-token latency (``serve.itl_ms``
        now measures it). Smaller budget = flatter concurrent ITL,
        longer TTFT for the long prompt; ``None`` keeps atomic joins.
        Chunked outputs are token-identical to unchunked (same
        executables, same KV, position by position); partially
        prefilled prompts publish completed page chunks into the
        prefix tree at every chunk boundary. Requires ``kv='paged'``.

        ``ring_prefill`` (ISSUE 13, the offload half): prompts whose
        UNCACHED suffix (after the prefix-cache match) is at least
        ``ring_prefill_min_tokens`` tokens prefill SEQUENCE-PARALLEL
        over this many devices (causal ring attention, striped
        layout) with the harvested K/V landed directly into the
        plan's private pages — per-device prefill residency drops to
        O(p/n), so prompts beyond one device's budget become
        servable, and decode afterwards is plain single-device paged
        decode, token-identical to a single-device prefill of the
        same prompt. Duplicates and multi-turn follow-ups hit the
        prefix tree like any other request (a full hit never rings).
        Requires ``kv='paged'``, no int8 pages, no speculation.

        ``replica_class`` (ISSUE 14, prefill/decode disaggregation):
        an advisory class label — ``'prefill'`` replicas run prompt
        passes and EXPORT the resulting KV page chains over the wire
        (:meth:`submit_prefill`), ``'decode'`` replicas IMPORT chains
        (:meth:`offer_chain`) and own the decode slots, ``'mixed'``
        (default) does both locally. The multi-replica router reads
        the class for two-phase placement; the scheduler itself only
        validates the config (non-mixed classes require ``kv='paged'``
        and no speculation — the draft store has no wire harvest) and
        reports the class in ``load_snapshot()``.

        ``watchdog`` (ISSUE 14 satellite, the PR 8 isolation note):
        a dedicated :class:`tpuflow.obs.health.Watchdog` for THIS
        scheduler — ``readiness()``/``health()`` consult it instead of
        the process default, and a scheduler-loop step failure trips
        it, so one in-process replica's fault fails over ONLY that
        replica instead of the whole tier. ``None`` keeps the process
        default (single-scheduler servers; out-of-process replicas are
        isolated by their process boundary anyway).

        ``speculate_k`` (ISSUE 9) turns on draft-model speculative
        decoding: a small ``draft_model``/``draft_params``
        TransformerLM (same vocabulary; see
        :func:`tpuflow.models.draft_lm_config`) proposes ``k`` tokens
        per round and the target verifies all k+1 positions in ONE
        blockwise pass with ORACLE-PARITY acceptance — outputs are
        token-identical to the non-speculative scheduler (greedy
        bitwise; sampled seeded-identical), so speculation is purely a
        throughput knob. Requires ``kv='paged'`` (rollback rides the
        per-row write positions); draft KV shares the target's page
        tables. Per-request opt-out: ``submit(..., speculate=False)``
        rows run plain decode inside the same batch.

        MoE serving (ISSUE 18): an MoE model (``n_experts > 0``) must
        be built with ``moe_no_drop=True`` and serves through
        ``kv='paged'`` — the paged segment fn harvests per-expert
        routed-token loads every segment (the ``serve.moe_expert_load``
        gauges). ``moe_capacity_factor`` is the HOST-side capacity
        knob: when the hottest expert's last-segment load exceeds
        ``factor × balanced_share`` (balanced share = slots × seg ×
        top_k × n_moe_blocks / n_experts), NEW admissions hold at the
        boundary (``moe_overflow='queue'``, counted as
        ``moe_capacity_waits``) until routing cools — in-flight rows
        always run, so a hot expert degrades admission latency, never
        wedges the batch. ``moe_overflow='off'`` disables the gate
        (gauges only). Dropless routing means this gate is a LOAD
        shaper, not a correctness surface — outputs stay
        token-identical either way."""
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if kv not in ("contiguous", "paged"):
            raise ValueError(
                f"kv must be 'contiguous' or 'paged', got {kv!r}"
            )
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.slots = int(slots)
        self.seg = int(seg)
        self.rounds = int(rounds)
        self.max_new_cap = int(max_new_cap)
        self.max_queue = int(max_queue)
        self.max_bucket = int(max_bucket)
        self.sampling = dict(temperature=float(temperature), top_k=top_k,
                             top_p=top_p, eos_id=eos_id, seed=int(seed))
        self.metrics = metrics or ServeMetrics()
        self.clock = clock
        self.kv = kv
        if kv == "paged":
            ps = int(kv_page_size)
            if kv_pages is None:
                # default sizing: ~4×slots concurrent typical requests
                # (cap-sized prompt + cap decode each), floored at ONE
                # maximum-legal request (max_bucket prompt + cap) — any
                # prompt the bucket config admits must be SERVABLE
                # under default sizing (worst case: alone, with the
                # rest queued), never a submit-time ValueError. A
                # starting point, not a law; size deliberately for
                # real traffic.
                per_req = pages_needed(int(max_new_cap),
                                       int(max_new_cap), ps)
                per_max = pages_needed(int(max_bucket),
                                       int(max_new_cap), ps)
                kv_pages = 1 + max(4 * int(slots) * max(1, per_req),
                                   per_max)
            self.kv_spec: Optional[PagedKVSpec] = PagedKVSpec(
                pages=int(kv_pages), page_size=ps, quant=kv_quant,
                kernel=kv_kernel)
            self.kv_prefix_cache = bool(kv_prefix_cache)
            # ISSUE 8 satellite (the PR 6 known-limits follow-on):
            # also publish a finished request's GENERATED pages into
            # the prefix tree, so a multi-turn follow-up whose prompt
            # is prompt+completion(+user turn) hits past the original
            # prompt. ON by default since ISSUE 13: the r11 wider A/B
            # recorded verdict enable_by_default
            # (BENCH_LOCAL_r11_serve_paged.json insert_generated) —
            # completion pages retained in the tree stay LRU-evictable
            # under pressure; opt out per scheduler (CLI
            # --no-kv-prefix-insert-generated).
            self.kv_insert_generated = bool(
                kv_prefix_insert_generated) and self.kv_prefix_cache
            # tiered hierarchy (ISSUE 16): host/disk spill pools under
            # the page store — evicted chains demote instead of drop,
            # plan() promotes spilled frontiers back before prefill
            if (kv_host_bytes or kv_disk_path) and not self.kv_prefix_cache:
                raise ValueError(
                    "the tiered KV hierarchy (kv_host_bytes/"
                    "kv_disk_path) spills and refills the prefix tree "
                    "— it requires kv_prefix_cache=True")
        else:
            self.kv_spec = None
            self.kv_prefix_cache = False
            self.kv_insert_generated = False
            if kv_host_bytes or kv_disk_path:
                raise ValueError(
                    "the tiered KV hierarchy requires kv='paged' — "
                    "page chains are its spill unit")
        self.kv_host_bytes = int(kv_host_bytes)
        self.kv_disk_path = kv_disk_path
        self.kv_spill_min_pages = int(kv_spill_min_pages)
        self.kv_promote_min_pages = int(kv_promote_min_pages)
        self.prefill_budget_tokens = (
            None if prefill_budget_tokens is None
            else int(prefill_budget_tokens))
        if self.prefill_budget_tokens is not None:
            if kv != "paged":
                raise ValueError(
                    "prefill_budget_tokens (chunked prefill) requires "
                    "kv='paged' — chunks ride the width-bucketed "
                    "suffix-join menu")
            if self.prefill_budget_tokens < 1:
                raise ValueError(
                    f"prefill_budget_tokens must be >= 1 (None = "
                    f"atomic joins), got {prefill_budget_tokens}")
        self.ring_prefill = None if not ring_prefill else int(ring_prefill)
        self.ring_prefill_min_tokens = int(ring_prefill_min_tokens)
        if self.ring_prefill is not None:
            if kv != "paged":
                raise ValueError(
                    "ring_prefill requires kv='paged' — the harvest "
                    "lands into KV pages")
            if kv_quant is not None:
                raise ValueError(
                    "ring_prefill does not combine with int8 pages "
                    "yet — the harvest lands unquantized KV")
            if speculate_k:
                raise ValueError(
                    "ring_prefill does not combine with speculate_k — "
                    "the draft store has no ring harvest, so drafted "
                    "rows would attend to garbage prompt KV")
            n = self.ring_prefill
            if n < 2 or n & (n - 1) or n > 8:
                raise ValueError(
                    f"ring_prefill must be a power of two in [2, 8] "
                    f"(it must divide every pow2 prompt bucket, min "
                    f"8), got {ring_prefill}")
            import jax as _jax

            if n > len(_jax.devices()):
                raise ValueError(
                    f"ring_prefill={n} > {len(_jax.devices())} "
                    f"available devices")
        if replica_class not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"replica_class must be 'mixed', 'prefill' or "
                f"'decode', got {replica_class!r}")
        if replica_class != "mixed":
            if kv != "paged":
                raise ValueError(
                    f"replica_class={replica_class!r} requires "
                    f"kv='paged' — KV pages are the wire format")
            if speculate_k:
                raise ValueError(
                    "prefill/decode replica classes do not combine "
                    "with speculate_k — the draft store has no wire "
                    "harvest, so imported chains would leave drafted "
                    "rows attending to garbage")
            if replica_class == "decode" and not kv_prefix_cache:
                raise ValueError(
                    "replica_class='decode' requires the prefix cache "
                    "— imported page chains land in it")
        self.replica_class = replica_class
        self._watchdog = watchdog
        self.transfer_wait_s = float(transfer_wait_s)
        # zero-downtime deployment (ISSUE 15): the model version this
        # replica serves ({step, digest, label} — a bare string
        # normalizes), surfaced in load_snapshot()/metrics/Prometheus/
        # flight and advanced by swap_weights/swap_from_manifest
        from tpuflow.serve.deploy import normalize_version

        self.model_version: Optional[Dict[str, Any]] = (
            normalize_version(model_version))
        self.draft_version: Optional[Dict[str, Any]] = None
        if self.model_version is not None:
            self.metrics.on_model_version(self.model_version)
        # inbound page-chain transfers (ISSUE 14): chunks queue here
        # from any thread; the scheduler thread lands them at boundary
        # start (device scatter stays on the one device-owning thread)
        self._chain_inbox: "Deque[tuple]" = deque()
        self._transfers: Dict[str, Dict[str, Any]] = {}
        self._transfer_seq = 0
        # outbound chain fetches (ISSUE 16): the donor side of a
        # directory-routed cross-replica pull. Requests queue here from
        # any thread; the scheduler thread answers them at boundary
        # start (prefix-tree walk + device gather stay on the one
        # device-owning thread, and a fetch never blocks decode
        # mid-segment)
        self._fetch_inbox: "Deque[tuple]" = deque()
        self.speculate_k = int(speculate_k)
        self.draft_model = draft_model
        self.draft_params = draft_params
        if self.speculate_k:
            if self.speculate_k < 1:
                raise ValueError(
                    f"speculate_k must be >= 1 (0 = off), got "
                    f"{speculate_k}")
            if kv != "paged":
                raise ValueError(
                    "speculate_k requires kv='paged' — speculative "
                    "rollback rides the paged engine's per-row write "
                    "positions")
            if draft_model is None or draft_params is None:
                raise ValueError(
                    "speculate_k needs draft_model AND draft_params "
                    "(a small TransformerLM over the same vocabulary; "
                    "see tpuflow.models.draft_lm_config)")
            dv = getattr(draft_model, "vocab_size", None)
            tv = getattr(model, "vocab_size", None)
            if dv is not None and tv is not None and int(dv) != int(tv):
                raise ValueError(
                    f"draft vocab_size {dv} != target vocab_size {tv} "
                    f"— draft and target must share one tokenizer")
            div = int(getattr(draft_model, "image_vocab", 0) or 0)
            tiv = int(getattr(model, "image_vocab", 0) or 0)
            if div != tiv:
                raise ValueError(
                    f"draft image_vocab {div} != target image_vocab "
                    f"{tiv} — a VLM target's draft must embed the same "
                    f"image-prefix ids (draft_lm_config inherits them) "
                    f"or drafted rows read garbage prompt positions")
            from tpuflow.obs import memory as _mem

            _mem.tag("draft_params", draft_params)  # ledger (ISSUE 7)
        # ---- multi-workload validation (ISSUE 18) -------------------
        # MoE serving: dropless routing + paged KV + the host-side
        # capacity admission gate; VLM: the extended-vocab id range.
        # Every misconfiguration fails HERE with a pointed error, not
        # deep in a compiled dispatch (the --kv-* validated-combo
        # style).
        self.moe_experts = int(getattr(model, "n_experts", 0) or 0)
        self.moe_top_k = int(getattr(model, "moe_top_k", 2) or 2)
        moe_every = int(getattr(model, "moe_every", 2) or 2)
        depth = int(getattr(model, "depth", 0) or 0)
        self.moe_blocks = sum(
            1 for i in range(depth)
            if self.moe_experts > 0 and i % moe_every == moe_every - 1)
        self.moe_capacity_factor = float(moe_capacity_factor)
        self.moe_overflow = moe_overflow
        if self.moe_experts:
            if not getattr(model, "moe_no_drop", False):
                raise ValueError(
                    "serving an MoE model requires moe_no_drop=True "
                    "(build_transformer_lm(..., moe_no_drop=True)) — "
                    "capacity-dropped routing makes a token's output "
                    "depend on its batch neighbors, so serve outputs "
                    "could not stay token-identical to the single-"
                    "request oracle; dropless decode routing moves the "
                    "capacity trade to this scheduler's admission gate "
                    "(moe_capacity_factor)")
            if kv != "paged":
                raise ValueError(
                    "MoE serving requires kv='paged' — the per-expert "
                    "load harvest and the capacity admission gate ride "
                    "the paged segment fn")
            if speculate_k:
                raise ValueError(
                    "speculate_k does not combine with MoE targets yet "
                    "— the draft/verify fns have no expert-load "
                    "harvest, so the capacity admission gate would fly "
                    "blind; serve the MoE target without speculation")
            if self.moe_blocks == 0:
                raise ValueError(
                    f"n_experts={self.moe_experts} but moe_every="
                    f"{moe_every} places no MoE block in depth={depth} "
                    f"— blocks i with i % moe_every == moe_every - 1 "
                    f"are MoE; use moe_every=1 for every-block MoE")
            if not self.moe_capacity_factor > 0:
                raise ValueError(
                    f"moe_capacity_factor must be > 0 (the hot-expert "
                    f"admission threshold as a multiple of the "
                    f"balanced per-expert share), got "
                    f"{moe_capacity_factor}")
            if moe_overflow not in ("queue", "off"):
                raise ValueError(
                    f"moe_overflow must be 'queue' (hold new "
                    f"admissions while an expert runs hot) or 'off' "
                    f"(gauges only), got {moe_overflow!r}")
        self.image_vocab = int(getattr(model, "image_vocab", 0) or 0)
        if self.image_vocab < 0:
            raise ValueError(
                f"image_vocab must be >= 0, got {self.image_vocab}")
        # latest per-expert segment harvest (numpy (n_experts,)); None
        # until the first MoE segment runs
        self._moe_load: Optional[np.ndarray] = None
        self.kv_state: Optional[PagedKV] = None  # built with first pool
        self.pools: Dict[int, SlotPool] = {}
        self._queues: Dict[int, Deque[Request]] = {}
        self._admit_counts: Dict[int, int] = {}  # per-bucket stream-id source
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        self._draining = False
        # readiness threshold: a decode segment (or idle loop pass)
        # older than this while work is pending marks the scheduler
        # NOT READY (see readiness()); generous default — a segment is
        # seg device steps, normally milliseconds-to-seconds
        self.stall_after_s = 30.0
        # post-mortem capture: the flight recorder snapshots in-flight
        # request states through this provider (one per gauge prefix,
        # so multi-model schedulers don't clobber each other). Weakly
        # bound: the provider registry is process-global and must not
        # pin a dead scheduler's pools (and their KV device buffers)
        import weakref

        from tpuflow.obs import flight as _flight

        ref = weakref.ref(self)

        def _provider():
            s = ref()
            return s._requests_snapshot() if s is not None else None

        _flight.add_provider(f"{self.metrics.prefix}_requests",
                             _provider)
        if kv == "paged":
            def _kv_provider():
                s = ref()
                return s.kv_snapshot() if s is not None else None

            _flight.add_provider(f"{self.metrics.prefix}_kv",
                                 _kv_provider)
        if self.speculate_k:
            # post-mortems must show acceptance collapse: the bundle's
            # <prefix>_spec.json carries cumulative + windowed rates
            def _spec_provider():
                s = ref()
                return s.spec_snapshot() if s is not None else None

            _flight.add_provider(f"{self.metrics.prefix}_spec",
                                 _spec_provider)

    @classmethod
    def from_packaged(cls, lm, **kwargs) -> "ServeScheduler":
        """Build from a :class:`tpuflow.packaging.lm.PackagedLM` (or a
        path/URI to one): model, params, bundled tokenizer, and the
        packaged ``generate_defaults`` sampling knobs (explicit kwargs
        win)."""
        from tpuflow.packaging.lm import PackagedLM, load_packaged_lm

        if isinstance(lm, str):
            lm = load_packaged_lm(lm)
        if not isinstance(lm, PackagedLM):
            raise TypeError(
                f"from_packaged needs a PackagedLM or path/URI, got "
                f"{type(lm).__name__}"
            )
        defaults = dict(lm.generate_defaults)
        defaults.pop("max_new_tokens", None)
        for k in ("temperature", "top_k", "top_p", "eos_id", "seed"):
            if k in defaults and k not in kwargs:
                kwargs[k] = defaults[k]
        return cls(lm.model, lm.params, tokenizer=lm.tokenizer, **kwargs)

    # ---- admission (any thread) -------------------------------------
    def _encode(self, prompt) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts need a tokenizer; submit token ids "
                    "or construct the scheduler with one"
                )
            return np.asarray(self.tokenizer.encode(prompt), np.int32)
        return np.asarray(prompt, np.int32).reshape(-1)

    @staticmethod
    def _retry_hint(depth: int) -> float:
        """Backpressure hint: a segment's worth of work per queued
        request ahead, floored — deliberately rough (the client just
        needs a sane backoff, not a promise). THE single definition:
        QueueFull and the public surface must never diverge."""
        return max(0.1, 0.05 * depth)

    def _initial_pages_needed(self, prompt_len: int, max_new: int) -> int:
        """The pages ADMISSION actually gates on under incremental
        allocation (ISSUE 11): prompt + first-segment coverage — THE
        same helper ``PagedKV.plan(initial_new=segment_advance())``
        reserves through. Retry-After hints must quote this, not the
        worst case the request may never grow into."""
        from tpuflow.serve.pages import initial_pages_needed

        adv = (self.speculate_k + 1) if self.speculate_k else self.seg
        return initial_pages_needed(prompt_len, max_new, adv,
                                    self.kv_spec.page_size)

    def _page_retry_from(self, need: int) -> Optional[float]:
        """Out-of-pages Retry-After: pages still short of ``need`` over
        the windowed page FREE-RATE (pages/s actually released lately)
        — a measured drain estimate, not a queue-depth guess. None when
        pages are not the constraint."""
        kvs = self.kv_state
        if kvs is None:
            return None
        short = need - kvs.allocator.free_count()
        if short <= 0:
            return None
        rate = kvs.allocator.free_rate(now=self.clock())
        if rate <= 0.0:
            return 1.0  # nothing freed in the whole window: flat backoff
        return min(30.0, max(0.1, short / rate))

    def retry_after_s(self) -> float:
        head = None
        with self._lock:
            depth = sum(len(q) for q in self._queues.values())
            for q in self._queues.values():
                if q and (head is None
                          or q[0].ts_arrival < head.ts_arrival):
                    head = q[0]
        hint = self._retry_hint(depth)
        if head is not None and self.kv_state is not None:
            ph = self._page_retry_from(self._initial_pages_needed(
                head.effective_len(), head.remaining_new()))
            if ph is not None:
                hint = max(hint, ph)
        return hint

    def _moe_capacity_tokens(self, pool) -> float:
        """Hot-expert admission threshold in routed tokens per
        segment: ``moe_capacity_factor`` × the balanced per-expert
        share of one full segment's routing mass (slots rows × seg
        steps × top_k choices × n_moe_blocks sows / n_experts)."""
        balanced = (pool.slots * pool.seg * self.moe_top_k
                    * self.moe_blocks) / max(1, self.moe_experts)
        return self.moe_capacity_factor * balanced

    def _moe_admission_hot(self, pool) -> bool:
        """True while the hot-expert admission gate should hold NEW
        admissions: MoE model, gate on, a live batch, and the last
        harvested segment's hottest expert at/over the capacity
        threshold. Never true for an idle pool — stale loads cannot
        starve an empty batch."""
        if (not self.moe_experts or self.moe_overflow != "queue"
                or self._moe_load is None or not pool.decode_live()):
            return False
        return (float(self._moe_load.max())
                >= self._moe_capacity_tokens(pool))

    def moe_hot_expert_frac(self) -> float:
        """Hottest expert's share of the last segment's routed-token
        mass (0.0 before any MoE segment, or for dense models) — the
        router's expert-affinity placement signal."""
        load = self._moe_load
        if load is None:
            return 0.0
        total = float(load.sum())
        return float(load.max()) / total if total > 0 else 0.0

    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        *,
        deadline_s: Optional[float] = None,
        stream_cb: Optional[Callable[[Request, List[int], bool], None]] = None,
        request_id: Optional[str] = None,
        stream_id: Optional[int] = None,
        speculate: bool = True,
        await_transfer: Optional[str] = None,
        prefill_only: bool = False,
        trace_ctx: Optional[Dict[str, Any]] = None,
    ) -> Request:
        """Queue one request. Raises :class:`QueueFull` when the
        admission queue is at capacity (backpressure),
        :class:`SchedulerClosed` once :meth:`drain`/:meth:`stop` ran
        (→ HTTP 503), and ``ValueError`` for requests that can never
        be served (prompt longer than the largest bucket, budget
        beyond the pool horizon).

        ``stream_id`` pins the request's sampling stream explicitly
        (taken mod ``slots``) instead of drawing it from this
        scheduler's per-bucket admission counter — the multi-replica
        router's determinism hook: a tier that assigns stream ids from
        ONE global per-bucket counter reproduces a single scheduler's
        sampled outputs no matter which replica serves (or, after
        failover, re-serves) the request.

        ``speculate=False`` (speculating schedulers only) pins THIS
        request to plain one-token-per-round decode while it shares
        the continuous batch with speculative rows — tokens are
        identical either way (oracle-parity acceptance); a no-op when
        ``speculate_k`` is off.

        ``await_transfer`` (ISSUE 14) names an inbound page-chain
        transfer (:meth:`offer_chain`): the request stays QUEUED until
        that transfer completes (its admission then hits the imported
        prefix — cross-process cache routing) or fails/times out
        (``transfer_wait_s``), when it admits with a LOCAL prefill —
        tokens are identical either way. ``prefill_only`` admits a
        prompt-pass-only request that exports its page chain
        (:meth:`submit_prefill` is the public spelling).

        ``trace_ctx`` (ISSUE 19) adopts an inbound distributed-trace
        context — ``{"trace_id": ..., "parent_span": ...}``, the
        router's stamp on the worker RPC — so this scheduler's
        lifecycle spans join the tier-level trace instead of starting
        a fresh one (``trace_id`` defaults to the request id, the
        ISSUE 4 correlation contract; ``parent_span`` parents the
        ``serve.request`` root under the router's span)."""
        from tpuflow.packaging.lm import _bucket_len

        if (await_transfer or prefill_only) and self.kv_spec is None:
            raise ValueError(
                "await_transfer/prefill_only require kv='paged' — KV "
                "pages are the wire format")
        if (await_transfer or prefill_only) and self.speculate_k:
            raise ValueError(
                "await_transfer/prefill_only do not combine with "
                "speculate_k (no draft-side wire harvest)")
        ids = self._encode(prompt)
        if ids.size:
            # multi-workload id-range check (ISSUE 18): text ids live
            # in [0, vocab); image-prefix ids in [vocab, vocab +
            # image_vocab). Out-of-range ids would gather garbage
            # embeddings — fail at submit, not in a compiled dispatch.
            vocab = int(getattr(self.model, "vocab_size", 0) or 0)
            if vocab:
                top = int(ids.max())
                if top >= vocab + self.image_vocab:
                    if self.image_vocab:
                        raise ValueError(
                            f"prompt id {top} >= vocab_size ({vocab}) "
                            f"+ image_vocab ({self.image_vocab}) — "
                            f"image-prefix ids come from models.vlm."
                            f"image_to_tokens against THIS model's "
                            f"vocab/image_vocab")
                    raise ValueError(
                        f"prompt id {top} >= vocab_size ({vocab}) — "
                        f"this model has no image vocabulary "
                        f"(image_vocab=0); build a VLM with "
                        f"models.vlm.build_vlm_lm to serve image-"
                        f"prefix prompts")
        if max_new_tokens is None:
            max_new_tokens = self.max_new_cap
        if not 1 <= int(max_new_tokens) <= self.max_new_cap:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} outside [1, "
                f"max_new_cap={self.max_new_cap}]"
            )
        bucket = _bucket_len(int(ids.size))
        if bucket > self.max_bucket:
            raise ValueError(
                f"prompt of {ids.size} tokens needs bucket {bucket} > "
                f"max_bucket {self.max_bucket}"
            )
        page_hint = None
        if self.kv_spec is not None:
            # never-servable check: a request whose WORST-CASE page
            # demand exceeds the whole store could queue forever —
            # that is a config error, not backpressure (incremental
            # growth must always be able to finish what it admits)
            need = pages_needed(int(ids.size), int(max_new_tokens),
                                self.kv_spec.page_size)
            if need > self.kv_spec.pages - 1:
                raise ValueError(
                    f"request needs {need} KV pages > the store's "
                    f"{self.kv_spec.pages - 1} usable pages; raise "
                    f"kv_pages (or shrink the prompt/budget)"
                )
            # …but the RETRY hint quotes what admission actually gates
            # on — the incremental first-segment reserve (ISSUE 11)
            page_hint = self._page_retry_from(self._initial_pages_needed(
                int(ids.size), int(max_new_tokens)))
        now = self.clock()
        req = Request(
            prompt_ids=ids, max_new_tokens=int(max_new_tokens),
            id=request_id or "",
            deadline_ts=None if deadline_s is None else now + deadline_s,
            stream_cb=stream_cb,
            speculate=bool(speculate),
            prefill_only=bool(prefill_only),
            await_transfer=await_transfer,
        )
        if await_transfer is not None:
            # placeholder so an unknown id reads as PENDING (the offer
            # may still be in flight over the wire) — bounded by the
            # transfer_wait_s fallback, never a hang
            with self._lock:
                self._transfers.setdefault(str(await_transfer), {
                    "offered": 0, "processed": 0, "pages": 0,
                    "done": False, "failed": None, "last_offered": False,
                    "ts": now,
                })
                self._prune_transfers_locked()
        req.ts_arrival = now
        req.bucket = bucket
        # request-lifecycle spans, TRACE ID = REQUEST ID — so the
        # /v1/metrics event log and /v1/trace/<id> spans correlate.
        # Created BEFORE the request enters the queue: the scheduler
        # thread may admit it the instant the lock drops, and the
        # admit path must find the queue span to end. begin() returns
        # None when the tracer is off and end(None) no-ops, so this
        # stays in production code. begin here (caller thread), end on
        # the scheduler thread: the cross-thread contract of
        # tpuflow.obs.trace.
        # an inbound trace context (the router's RPC stamp) overrides
        # the default trace id and parents the root span — every
        # process a request touches then shares ONE trace (ISSUE 19)
        t_id = req.id
        t_parent = None
        if trace_ctx:
            t_id = trace_ctx.get("trace_id") or req.id
            t_parent = trace_ctx.get("parent_span")
        req._trace_id = t_id
        # sampling: registers head-dropped traces for tail-keep; the
        # head decision is deterministic on the trace id, so the whole
        # tier votes identically without an extra wire field
        trace.begin_request(t_id)
        root = trace.begin("serve.request", trace_id=t_id,
                           parent_id=t_parent,
                           bucket=bucket,
                           prompt_tokens=int(ids.size),
                           max_new_tokens=int(max_new_tokens))
        parent = root.span if root is not None else None
        req._span_request = root
        req._span_queue = trace.begin("serve.queue", trace_id=t_id,
                                      parent_id=parent, phase="queue")
        req._span_ttft = trace.begin("serve.ttft", trace_id=t_id,
                                     parent_id=parent)
        with self._lock:
            if self._closed:
                trace.end(req._span_queue)
                trace.end(req._span_ttft)
                trace.end(root, state="rejected", error="stopped")
                raise SchedulerClosed(
                    "scheduler is stopped"
                    + (" (draining)" if self._draining else "")
                )
            depth = sum(len(q) for q in self._queues.values())
            if depth >= self.max_queue:
                retry = max(self._retry_hint(depth), page_hint or 0.0)
                self.metrics.on_reject(depth, retry)
                trace.end(req._span_queue)
                trace.end(req._span_ttft)
                trace.end(root, state="rejected", depth=depth)
                raise QueueFull(depth, retry)
            if stream_id is None:
                n = self._admit_counts.get(bucket, 0)
                self._admit_counts[bucket] = n + 1
                # the wave path's physical row index, reproduced:
                # stream ids are what make slot outputs == wave
                # outputs under sampling (see module docstring)
                req.stream_id = n % self.slots
            else:
                # router-pinned stream (see docstring): the local
                # counter is NOT advanced — replica-local admissions
                # and tier-pinned ones must not perturb each other
                req.stream_id = int(stream_id) % self.slots
            self._queues.setdefault(bucket, deque()).append(req)
            self.metrics.on_queue_depth(depth + 1)
            self._work.notify_all()
        self.metrics.on_submit(req)
        return req

    def cancel(self, request: "Request | str") -> bool:
        """Cancel by request or id. Queued requests finalize
        immediately; running ones are evicted (slot freed) at the next
        segment boundary. Returns False for unknown/already-terminal
        requests. Best-effort for RUNNING requests: True means the
        cancellation was REQUESTED — a request racing its final
        harvest may still complete DONE with full output (terminal
        transitions are deliberately taken outside the lock so client
        callbacks cannot deadlock the decode loop; check
        ``result()['state']`` for the outcome)."""
        with self._lock:
            req = None
            if isinstance(request, Request):
                req = request
            else:
                for q in self._queues.values():
                    for r in q:
                        if r.id == request:
                            req = r
                            break
                if req is None:
                    for pool in self.pools.values():
                        for r in pool.occupants:
                            if r is not None and r.id == request:
                                req = r
                                break
            if req is None or req.state not in (RequestState.QUEUED,
                                                RequestState.RUNNING):
                return False
            req.cancel_requested = True
            q = self._queues.get(req.bucket)
            was_queued = q is not None and req in q
            if was_queued:
                q.remove(req)
            else:
                self._work.notify_all()
        # finalize OUTSIDE the lock: _finalize fires the client's
        # stream_cb, and a callback that re-enters the scheduler
        # (submit/cancel/retry_after_s all take the lock) must not
        # deadlock the server — same discipline as step()
        if was_queued:
            self._finalize(req, RequestState.CANCELLED,
                           "cancelled while queued")
            return True
        self.metrics.event(req.id, "cancel_requested")
        return True

    # ---- prefill/decode disaggregation (ISSUE 14) -------------------
    def submit_prefill(
        self,
        prompt,
        *,
        deadline_s: Optional[float] = None,
        stream_cb: Optional[Callable] = None,
        request_id: Optional[str] = None,
        trace_ctx: Optional[Dict[str, Any]] = None,
    ) -> Request:
        """Queue a PREFILL-ONLY request: the scheduler admits it like
        any other (prefix-cache match, atomic / chunked / ring prompt
        pass — all three compose), then instead of decoding it exports
        the full prompt page chain to the wire format
        (``request.export``, see ``serve/pages.py``) and finalizes
        DONE with zero tokens. The exported chain is what a decode
        replica lands via :meth:`offer_chain`; the prefill replica's
        own prefix tree keeps the pages too, so repeated prefixes
        export without recomputing. Raises the :meth:`submit`
        taxonomy."""
        return self.submit(
            prompt, 1, deadline_s=deadline_s, stream_cb=stream_cb,
            request_id=request_id, speculate=False, prefill_only=True,
            trace_ctx=trace_ctx,
        )

    #: retained transfer records (a server must not grow without
    #: limit): beyond this, the oldest COMPLETED/FAILED entries are
    #: pruned — pending transfers are never dropped
    _TRANSFER_KEEP = 1024

    def _prune_transfers_locked(self) -> None:
        if len(self._transfers) <= self._TRANSFER_KEEP:
            return
        excess = len(self._transfers) - self._TRANSFER_KEEP
        drop = []
        for tid, st in self._transfers.items():
            if st["done"] or st["failed"]:
                drop.append(tid)
                if len(drop) >= excess:
                    break
        for tid in drop:
            del self._transfers[tid]

    def offer_chain(self, wire, *, transfer_id: Optional[str] = None,
                    last: bool = True,
                    trace_ctx: Optional[Dict[str, Any]] = None) -> str:
        """Queue one page-chain wire (or :func:`split_chain` chunk)
        for import at the next scheduler boundary — callable from any
        thread; the device scatter stays on the scheduler thread.
        Chunks sharing a ``transfer_id`` land in offer order,
        interleaved with decode segments (the transfer-overlap half:
        a long chain streams in while other rows keep decoding);
        ``last=True`` marks the transfer complete once every offered
        chunk landed, unblocking a request submitted with
        ``await_transfer=`` that id. A verify failure (CRC, header,
        gap, dry allocator) marks the transfer FAILED — the waiting
        request falls back to a local prefill, never a truncated
        stream. Returns the transfer id.

        ``trace_ctx`` (ISSUE 19) attaches a distributed-trace context
        to the TRANSFER (landing spans join the sender's trace even
        when individual wire chunks carry no ``trace`` metadata of
        their own)."""
        if self.kv_spec is None:
            raise ValueError(
                "offer_chain requires kv='paged' — KV pages are the "
                "wire format")
        if self.speculate_k:
            raise ValueError(
                "offer_chain does not combine with speculate_k — the "
                "draft store has no wire harvest")
        now = self.clock()
        with self._lock:
            if transfer_id is None:
                self._transfer_seq += 1
                transfer_id = f"tx-{self._transfer_seq}"
            tid = str(transfer_id)
            st = self._transfers.setdefault(tid, {
                "offered": 0, "processed": 0, "pages": 0,
                "done": False, "failed": None, "last_offered": False,
                "ts": now,
            })
            if st["done"]:
                raise ValueError(f"transfer {tid} already completed")
            if trace_ctx:
                st["trace"] = dict(trace_ctx)
            st["offered"] += 1
            if last:
                st["last_offered"] = True
            self._chain_inbox.append((tid, wire))
            self._prune_transfers_locked()
            self._work.notify_all()
        return tid

    def fail_transfer(self, transfer_id: str,
                      reason: str = "transfer failed") -> None:
        """Mark an inbound transfer FAILED from outside (the router's
        hook when the PREFILL side broke — rejected, dead replica,
        empty chain): a request submitted with ``await_transfer=`` on
        that id admits at its next boundary with a LOCAL prefill
        instead of waiting out ``transfer_wait_s``. Idempotent; a
        no-op on transfers that already completed."""
        with self._lock:
            st = self._transfers.setdefault(str(transfer_id), {
                "offered": 0, "processed": 0, "pages": 0,
                "done": False, "failed": None, "last_offered": False,
                "ts": self.clock(),
            })
            if st["done"] or st["failed"]:
                return
            st["failed"] = str(reason)
            st["ts_settled"] = self.clock()
            self._work.notify_all()
        self.metrics.on_kv_transfer_failure(str(transfer_id),
                                            str(reason), kind="abort")

    def _drain_chain_inbox(self) -> bool:
        """Land every queued transfer chunk (scheduler thread, one
        boundary): CRC-verify → allocate → donated scatter → publish.
        A failed chunk fails its whole transfer (later chunks of a
        failed transfer are dropped unlanded — they would only raise
        the same gap error)."""
        from tpuflow.serve.pages import PageWireError, wire_bytes

        from tpuflow.testing import faults

        progress = False
        while True:
            with self._lock:
                if not self._chain_inbox:
                    break
                tid, wire = self._chain_inbox.popleft()
                st = self._transfers[tid]
            progress = True
            nbytes = wire_bytes(wire)
            if st["failed"]:
                with self._lock:
                    st["processed"] += 1
                continue
            # landing span joins the SENDER's trace (ISSUE 19): the
            # chunk's own wire metadata wins, the transfer-level
            # context (offer_chain trace_ctx) is the fallback
            tctx = ((wire.get("trace") if isinstance(wire, dict)
                     else None) or st.get("trace") or {})
            sp = trace.begin("serve.transfer_land",
                             trace_id=tctx.get("trace_id") or tid,
                             parent_id=tctx.get("parent_span"),
                             transfer_id=tid)
            # injected-slow-transfer point: a "delay" fault here makes
            # the transfer phase dominate the TTFT breakdown — the
            # attribution demo bench.py --serve-trace pins
            faults.fire("serve.transfer.land")
            kvs = self._ensure_kv()
            t0 = self.clock()
            try:
                landed = kvs.import_chain(wire)
            except PageWireError as e:
                with self._lock:
                    st["processed"] += 1
                    st["failed"] = str(e)
                    st["ts_settled"] = self.clock()
                trace.end(sp, failed=str(e))
                self.metrics.on_kv_transfer_failure(tid, str(e))
                continue
            ms = (self.clock() - t0) * 1e3
            with self._lock:
                st["processed"] += 1
                st["pages"] += landed
                if (st["last_offered"]
                        and st["processed"] >= st["offered"]):
                    st["done"] = True
                    st["ts_settled"] = self.clock()
            trace.end(sp, pages=landed, bytes=nbytes)
            self.metrics.on_kv_import(tid, landed, nbytes, ms)
        return progress

    # ---- chain-fetch surface (ISSUE 16, directory pulls) ------------
    def request_chain(self, tokens, on_ready) -> None:
        """Ask this replica for its deepest coverage of a token prefix
        (resident tree re-export or spilled chain, whichever reaches
        further) — callable from any thread; the answer arrives via
        ``on_ready(wire_or_None)`` from the SCHEDULER thread at its
        next boundary (the gather never preempts a decode segment).
        The donor side of a router directory pull: the caller streams
        the wire to the puller via :meth:`offer_chain`. ``on_ready``
        gets None when nothing covers a full page (or the fetch
        failed) — the cue to ``fail_transfer`` the puller into a local
        prefill."""
        if self.kv_spec is None:
            raise ValueError(
                "request_chain requires kv='paged' — page chains are "
                "the wire format")
        with self._lock:
            self._fetch_inbox.append((np.asarray(tokens, np.int32)
                                      .reshape(-1), on_ready))
            self._work.notify_all()

    def fetch_chain(self, tokens,
                    timeout: float = 10.0) -> Optional[Dict[str, Any]]:
        """Blocking wrapper over :meth:`request_chain` for foreign
        threads (the HTTP worker surface). NEVER call from the
        scheduler thread — it would deadlock waiting on itself; use
        ``kv_state.chain_for`` there."""
        done = threading.Event()
        box: List[Optional[Dict[str, Any]]] = [None]

        def _cb(wire):
            box[0] = wire
            done.set()

        self.request_chain(tokens, _cb)
        done.wait(timeout)
        return box[0]

    def _drain_fetch_inbox(self) -> bool:
        """Answer every queued chain fetch (scheduler thread, boundary
        start). Runs even when closed/draining — a retiring replica
        keeps donating its warmth (pure reads) until the process
        exits."""
        progress = False
        while True:
            with self._lock:
                if not self._fetch_inbox:
                    break
                tokens, on_ready = self._fetch_inbox.popleft()
            progress = True
            wire = None
            try:
                if self.kv_state is not None:
                    wire = self.kv_state.chain_for(tokens)
            except Exception:  # defensive: a donor fault must not
                wire = None    # kill the decode loop
            try:
                on_ready(wire)
            except Exception:
                pass
        return progress

    def kv_chain_report(self) -> List[Dict[str, Any]]:
        """Per-chain ``{'keys': [hex...], 'tier': 'host'|'disk'}``
        rows for every SPILLED chain this replica holds — what the
        router's tier-global directory sweep merges (resident warmth
        it already learned from its own placements). Safe from any
        thread; empty without a tier pool."""
        kvs = self.kv_state
        if kvs is None or kvs.tier is None:
            return []
        return kvs.tier.report()

    def _transfer_blocked(self, req: Request, now: float) -> bool:
        """Whether an ``await_transfer`` request must stay queued:
        True only while its transfer is genuinely pending AND young —
        completed, failed and timed-out transfers all release the
        request to (local-prefill) admission."""
        tid = req.await_transfer
        if tid is None or self.kv_spec is None:
            return False
        # NOTE called from the admission loop, which already holds
        # self._lock (non-reentrant) — the reads here are plain dict /
        # scalar reads, safe against offer_chain's locked writes
        st = self._transfers.get(str(tid))
        if st is None:
            st = self._transfers.setdefault(str(tid), {
                "offered": 0, "processed": 0, "pages": 0,
                "done": False, "failed": None,
                "last_offered": False, "ts": req.ts_arrival})
        if st["done"] or st["failed"]:
            return False
        if now - min(st["ts"], req.ts_arrival) > self.transfer_wait_s:
            st["failed"] = "transfer timeout"
            st["ts_settled"] = now
            self.metrics.on_kv_transfer_failure(
                str(tid), "transfer timeout", kind="timeout")
            return False
        return True

    def _complete_prefill(self, pool, slot: int, req: Request) -> None:
        """A prefill-only row finished its prompt pass: export the
        full-page chain to the wire format, free the slot (the prefix
        tree keeps its own page references — the export survives the
        evict on the exporter too), finalize DONE."""
        plan = pool.plans[slot]
        kvs = self.kv_state
        ps = kvs.spec.page_size
        n_full = 0 if plan is None else int(plan.n_full)
        t0 = self.clock()
        err = None
        try:
            wire = kvs.export_chain(
                req.effective_prompt()[: n_full * ps],
                [] if plan is None else plan.table[:n_full])
        except Exception as e:  # defensive: an export must never
            # kill the decode loop
            wire, err = None, f"{type(e).__name__}: {e}"
        ms = (self.clock() - t0) * 1e3
        pool.evict(slot)
        if wire is None:
            self._finalize(req, RequestState.CANCELLED,
                           f"prefill export failed: {err}")
            return
        from tpuflow.serve.pages import wire_bytes

        req.export = wire
        if req.ts_prefill_done is None:
            req.ts_prefill_done = t0  # export began when prefill ended
        self.metrics.on_kv_export(req, n_full, wire_bytes(wire), ms)
        if req.ts_first_token is None:
            # the prompt pass IS this request's product: stamp TTFT at
            # export so prefill-class latency is observable
            req.ts_first_token = self.clock()
            self.metrics.on_first_token(req)
            trace.end(getattr(req, "_span_ttft", None))
        self._finalize(req, RequestState.DONE)
        self._stream(req, [], True)

    def _ensure_kv(self) -> PagedKV:
        """The scheduler-wide page universe, built on first need —
        pool construction and chain import share it."""
        if self.kv_state is None:
            self.kv_state = PagedKV(
                self.model, self.kv_spec,
                prefix_cache=self.kv_prefix_cache,
                clock=self.clock,
                draft_model=(self.draft_model
                             if self.speculate_k else None),
                host_bytes=self.kv_host_bytes,
                disk_path=self.kv_disk_path,
                spill_min_pages=self.kv_spill_min_pages,
                promote_min_pages=self.kv_promote_min_pages,
            )
        return self.kv_state

    # ---- live weight hot-swap (ISSUE 15) ----------------------------
    def swap_weights(self, params, *, version=None,
                     draft: bool = False) -> None:
        """Replace the served weights with ``params`` — SAME config,
        so the compiled join/segment executables are untouched: the
        swap is a reference flip onto freshly placed device buffers,
        validated (tree/shape/dtype) before anything moves and
        refused with :class:`~tpuflow.serve.deploy.SwapMismatchError`
        on drift.

        Quiescence contract: the scheduler must hold NO work (empty
        queues, no live rows) — the standby/drained state the
        blue/green rollout guarantees by construction. A busy replica
        raises instead of racing its own decode loop; the device
        placement happens BEFORE the lock, so admissions stall only
        for the reference flip itself.

        The prefix cache is CLEARED on a model swap: a version bump
        invalidates cached KV (old pages are garbage under new
        weights) — warmth is rebuilt by replaying hot chain heads
        (``DeploymentManager``), never by trusting stale pages.
        ``draft=True`` swaps the draft model's weights instead
        (speculative acceptance rises live; target weights, and
        therefore output tokens, untouched) — the draft store shares
        the target's page tables, so cached pages clear as well."""
        import jax

        from tpuflow.parallel.mesh import put_replicated
        from tpuflow.serve.deploy import (
            check_tree_compatible,
            normalize_version,
        )

        target = self.draft_params if draft else self.params
        if draft and target is None:
            raise ValueError(
                "draft swap on a non-speculating scheduler")
        check_tree_compatible(target, params,
                              what="draft" if draft else "model")
        t0 = self.clock()
        placed = jax.tree.map(
            lambda t, v: put_replicated(v, t.sharding)
            if hasattr(t, "sharding") else v,
            target, params)
        version = normalize_version(version)
        with self._lock:
            busy = any(self._queues.values())
            pools = list(self.pools.values())
            if not busy:
                busy = any(p.live_count() for p in pools)
            if busy:
                raise RuntimeError(
                    "swap_weights on a busy scheduler — swap the "
                    "standby (or drain first): the decode loop must "
                    "never race its own weights")
            if draft:
                self.draft_params = placed
                for pool in pools:
                    if getattr(pool, "draft_params", None) is not None:
                        pool.draft_params = placed
                self.draft_version = version
            else:
                self.params = placed
                for pool in pools:
                    pool.params = placed
                self.model_version = version
        if not draft:
            # new weights route differently: drop the stale per-expert
            # window so the admission gate / affinity signal restart
            # from the first post-swap segment (ISSUE 18)
            self._moe_load = None
        cleared = 0
        if self.kv_state is not None and self.kv_state.prefix is not None:
            cleared = self.kv_state.prefix.clear()
        if self.kv_state is not None and self.kv_state.tier is not None:
            # spilled chains are KV under the OLD weights — garbage
            # now, same invalidation rule as the resident tree
            self.kv_state.tier.clear()
        ms = (self.clock() - t0) * 1e3
        self.metrics.on_weight_swap(version, ms, draft=draft,
                                    cleared_pages=cleared)
        if not draft and version is not None:
            self.metrics.on_model_version(version)

    def swap_from_manifest(self, mpath: str, *,
                           draft: bool = False) -> Dict[str, Any]:
        """Restore a published sharded-checkpoint manifest (PR 10's
        atomic format) into this replica's device buffers — the
        checkpoint-namespace half of the hot swap: assemble the
        manifest's leaves on host (config validated against the
        loaded model FIRST — :class:`SwapMismatchError` on drift),
        place them under the current params' own shardings, flip.
        Returns the manifest's version dict ({step, digest,
        label})."""
        from tpuflow.serve.deploy import (
            load_host_params,
            manifest_version,
            place_like,
        )

        target = self.draft_params if draft else self.params
        if draft and target is None:
            raise ValueError(
                "draft swap on a non-speculating scheduler")
        version = manifest_version(mpath)
        host = load_host_params(mpath, target)
        placed = place_like(host, target)
        self.swap_weights(placed, version=version, draft=draft)
        return version

    def reopen(self) -> None:
        """Re-admit after a drain — the recycle half of blue/green:
        a drained-out old-version replica becomes the next standby,
        gets the NEXT version swapped in, and reopens. Refused while
        the admitted backlog is still in flight (reopening mid-drain
        would un-503 a replica the router already routed around)."""
        with self._lock:
            if any(self._queues.values()) or any(
                    p.live_count() for p in self.pools.values()):
                raise RuntimeError(
                    "reopen() before the drain finished — the "
                    "admitted backlog is still in flight")
            self._closed = False
            self._draining = False
        from tpuflow.obs.gauges import set_gauge

        set_gauge(f"{self.metrics.prefix}.draining", 0.0)
        self.metrics.event("-scheduler-", "reopen")

    # ---- health (per-replica isolation, ISSUE 14 satellite) ---------
    @property
    def watchdog(self):
        """THIS scheduler's trip surface: the injected per-replica
        watchdog when one was given, else the process default."""
        return (self._watchdog if self._watchdog is not None
                else _health.default_watchdog())

    def health(self) -> Dict[str, Any]:
        """Failover input (the replica shim's contract): ``failed`` =
        watchdog-tripped, or closed WITHOUT a drain (a draining
        replica serves its own backlog — resubmitting it elsewhere
        would double-serve), or a launched loop thread that DIED.
        With an injected per-replica ``watchdog`` this is genuinely
        per-replica (one in-process replica's trip no longer fails the
        whole tier — the PR 8 note, closed); without one, in-process
        replicas share the process default and a trip fails them over
        together (out-of-process replicas are isolated by their
        process boundary)."""
        r = self.readiness()
        wd = r.get("watchdog") or {}
        tripped = bool(wd.get("tripped"))
        closed = bool(r.get("closed"))
        draining = bool(r.get("draining"))
        dead_loop = bool(r.get("wedged_loop"))
        return {
            "failed": tripped or (closed and not draining) or dead_loop,
            "tripped": tripped,
            "closed": closed,
            "draining": draining,
            "ready": bool(r.get("ready")),
            # wall anchor (ISSUE 19): health probes double as clock-
            # offset samples — the router reads this against the
            # probe's RTT midpoint (same contract as load_snapshot)
            "wall_s": time.time(),
        }

    # ---- lifecycle internals (scheduler thread) ---------------------
    def _finalize(self, req: Request, state: RequestState,
                  error: Optional[str] = None) -> None:
        if req.ts_done is None:
            req.ts_done = self.clock()
        req.finalize(state, error)
        self.metrics.on_finish(req)
        # close any still-open lifecycle spans (idempotent: a DONE
        # request already ended queue/ttft at admit/first-token)
        trace.end(getattr(req, "_span_queue", None))
        trace.end(getattr(req, "_span_ttft", None))
        trace.end(getattr(req, "_span_request", None),
                  state=state.value, n_tokens=len(req.tokens))
        # SLO phase attribution (ISSUE 19): fold the request's stamped
        # timeline into the fixed phase vector — the per-phase
        # histograms the router/autoscaler control loops read
        self.metrics.on_phases(req)
        # sampling fate: tail-keep errored/outlier requests that the
        # head decision dropped (no-op while tracing is off)
        if trace.is_enabled():
            e2e = (req.ts_done - req.ts_arrival) * 1e3
            trace.finish_request(
                getattr(req, "_trace_id", req.id),
                error=state is not RequestState.DONE,
                latency_ms=e2e)
        if state is not RequestState.DONE:
            # non-DONE terminals never reach the harvest path's final
            # stream event — emit it here so streaming clients unblock
            self._stream(req, [], True)

    def _requeue_mid_decode(self, req: Request) -> None:
        """The paged store ran dry under this row mid-decode
        (``extend_for_segment`` could not cover its next segment): the
        request goes BACK TO THE QUEUE with its generated tokens kept.
        Its re-join uses the effective prompt (prompt + generated) and
        remaining budget, so positions and sampling keys land exactly
        where the uninterrupted run's would — the retry completes
        TOKEN-IDENTICALLY, and since its prefix pages were published
        before eviction the re-prefill is normally a cache hit (pages
        released to the allocator; Retry-After for new arrivals keeps
        quoting the windowed free-rate). Requeued at the FRONT of its
        bucket: it has sunk cost and its next starvation check happens
        at plan() time, so it cannot spin."""
        from tpuflow.packaging.lm import _bucket_len

        bucket = _bucket_len(req.effective_len())
        if bucket > self.max_bucket or req.remaining_new() < 1:
            # the transcript outgrew the largest bucket — not
            # resumable under this scheduler's config (rare: needs a
            # prompt already at max_bucket); fail it honestly instead
            # of requeueing something no pool can ever re-admit
            self.metrics.on_mid_decode_eviction(req.bucket,
                                                resumable=False)
            self._finalize(
                req, RequestState.CANCELLED,
                f"out of KV pages mid-decode and the transcript needs "
                f"bucket {bucket} > max_bucket {self.max_bucket} — "
                f"not resumable")
            return
        req.state = RequestState.QUEUED
        req.slot = None
        req.bucket = bucket
        # the eviction + queue wait + re-prefill interval is NOT
        # inter-token latency (queue_wait_ms measures it): a stale
        # stamp here would record one giant itl_ms sample at the
        # retry's first boundary and poison the windowed p95 the
        # router places against
        req.ts_last_tokens = None
        with self._lock:
            self._queues.setdefault(bucket, deque()).appendleft(req)
            self._work.notify_all()
        self.metrics.on_mid_decode_eviction(bucket)

    def _stream(self, req: Request, new: List[int], finished: bool) -> None:
        if req.stream_cb is None or (not new and not finished):
            return
        try:
            req.stream_cb(req, new, finished)
        except Exception as e:  # a client's callback must never be
            # able to stall or kill the decode loop
            self.metrics.event(req.id, "stream_cb_error", error=repr(e))

    def prepare(self, *buckets: int) -> None:
        """Pre-build AND pre-compile the slot pools for the given
        prompt buckets: a throwaway request is joined, one segment is
        decoded, and the pool is rewound — so the first real request
        pays neither pool construction nor the join/segment compiles.
        Call BEFORE opening the server to traffic: like
        :meth:`run_until_idle`, it drives device state and must not
        race the scheduler thread."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "prepare() while the background thread is running "
                "would race the device state; call it before start()"
            )
        for b in buckets:
            self._pool(int(b)).warm()

    def _pool(self, bucket: int) -> SlotPool:
        pool = self.pools.get(bucket)
        if pool is None:
            s = self.sampling
            # build OUTSIDE the lock (construction allocates device
            # buffers); only the scheduler thread creates pools, so no
            # duplicate-build race — but the INSERT takes the lock
            # because cancel()/idle()/metrics_snapshot() iterate this
            # dict from HTTP handler threads
            if self.kv_spec is not None:
                # ONE page store + allocator + prefix tree for the
                # whole scheduler — every bucket's pool shares it
                # (and, when speculating, ONE draft store indexed by
                # the same page tables); chain imports may have built
                # it before any pool existed
                self._ensure_kv()
                pool = PagedSlotPool(
                    self.model, self.params, self.kv_state, bucket,
                    self.slots, self.max_new_cap, seg=self.seg,
                    temperature=s["temperature"], top_k=s["top_k"],
                    top_p=s["top_p"], eos_id=s["eos_id"], seed=s["seed"],
                    spec_k=self.speculate_k,
                    draft_model=self.draft_model,
                    draft_params=self.draft_params,
                )
            else:
                pool = SlotPool(
                    self.model, self.params, bucket, self.slots,
                    self.max_new_cap, seg=self.seg, rounds=self.rounds,
                    temperature=s["temperature"], top_k=s["top_k"],
                    top_p=s["top_p"], eos_id=s["eos_id"], seed=s["seed"],
                )
            with self._lock:
                self.pools[bucket] = pool
        return pool

    def _sweep(self, pool: SlotPool, now: float) -> bool:
        """Evict cancelled/expired running requests (slot freed for
        immediate reuse)."""
        progress = False
        for slot, req in enumerate(pool.occupants):
            if req is None:
                continue
            if req.cancel_requested:
                pool.evict(slot)
                self._finalize(req, RequestState.CANCELLED,
                               "cancelled mid-decode")
                progress = True
            elif req.expired(now):
                pool.evict(slot)
                self._finalize(req, RequestState.EXPIRED,
                               "deadline hit mid-decode")
                progress = True
        return progress

    def step(self) -> bool:
        """One boundary iteration over every bucket with work: sweep →
        admit → decode one segment → stream/harvest. Returns whether
        any progress was made (False = idle)."""
        now = self.clock()
        progress = False
        if self.kv_spec is not None and self._chain_inbox:
            # land inbound page-chain chunks FIRST (ISSUE 14): a
            # request awaiting its transfer admits the same boundary
            # the last chunk lands, and chunks interleave with the
            # segments below while their request is still queued
            progress |= self._drain_chain_inbox()
        if self.kv_spec is not None and self._fetch_inbox:
            # answer outbound chain fetches (ISSUE 16): a directory
            # pull's donor gather happens here, between segments, so
            # it never stalls a decode mid-segment
            progress |= self._drain_fetch_inbox()
        with self._lock:
            buckets = set(self._queues) | set(self.pools)
            # deadline expiry MID-QUEUE (before any slot is spent on it)
            expired: List[Request] = []
            for b in buckets:
                q = self._queues.get(b)
                if not q or not any(
                    r.cancel_requested or r.deadline_ts is not None
                    for r in q
                ):  # the common no-deadline case: skip the rebuild
                    continue
                keep: Deque[Request] = deque()
                for req in q:
                    if req.cancel_requested:
                        expired.append(req)  # finalize outside as cancel
                    elif req.expired(now):
                        expired.append(req)
                    else:
                        keep.append(req)
                self._queues[b] = keep
        for req in expired:
            state = (RequestState.CANCELLED if req.cancel_requested
                     else RequestState.EXPIRED)
            self._finalize(req, state, f"{state.value} while queued")
            progress = True

        for b in sorted(buckets):
            with self._lock:
                has_pending = bool(self._queues.get(b))
            if not has_pending and b not in self.pools:
                continue
            pool = self._pool(b)
            progress |= self._sweep(pool, now)
            admits: List[tuple] = []
            chunk_admits: List[tuple] = []  # chunked prefill (ISSUE 13)
            ring_admits: List[tuple] = []  # ring prefill offload
            page_starved = False
            # hot-expert admission gate (ISSUE 18): while the last
            # segment's hottest expert exceeded the capacity-factor
            # share, NEW admissions hold (the queue keeps its head) —
            # in-flight rows below run regardless, so a routing hot
            # spot shapes admission, never wedges the batch. An idle
            # pool never gates (loads are stale the moment the rows
            # that produced them finish).
            moe_hot = self._moe_admission_hot(pool)
            moe_blocked = False
            with self._lock:
                q = self._queues.get(b, deque())
                # horizon exhausted + fully drained → rewind for the
                # queue (a fresh round restores full admission room;
                # paged pools have no shared horizon — reset no-ops)
                if (q and not pool.has_live()
                        and not pool.can_admit(q[0].max_new_tokens)):
                    pool.reset()
                # admit: freed slots take the queue head(s), FIFO
                free = pool.free_slots()
                while free and q and pool.can_admit(q[0].max_new_tokens):
                    if moe_hot:
                        moe_blocked = True
                        break
                    if self._transfer_blocked(q[0], now):
                        # the head's inbound page chain is still
                        # streaming: hold it (its admission will hit
                        # the imported prefix) — bounded by the
                        # transfer_wait_s local-prefill fallback
                        break
                    if self.kv_state is not None:
                        # paged admission asks the ALLOCATOR, not the
                        # pool: out of pages → the head stays QUEUED
                        # (Retry-After from the page free-rate) until
                        # finishing/cancelled requests release theirs.
                        # INCREMENTAL reserve (ISSUE 11): prompt +
                        # first-segment pages only — the plan grows at
                        # decode boundaries (extend_for_segment), so a
                        # request holds pages proportional to tokens
                        # generated, not its worst-case budget. A
                        # mid-decode-evicted head re-plans with its
                        # effective prompt + remaining budget (resume).
                        plan = self.kv_state.plan(
                            q[0].effective_prompt(),
                            q[0].remaining_new(),
                            initial_new=pool.segment_advance())
                        if plan is None:
                            page_starved = True
                            break
                        # Ring offload (ISSUE 13) gates on the
                        # UNCACHED suffix (= plan.width tokens), after
                        # the prefix match: a duplicate (or multi-turn
                        # follow-up) of a long prompt hits the tree
                        # like any other request instead of re-running
                        # the whole sequence-parallel pass — a full
                        # hit (width 1) never rings; the landing only
                        # ever writes the plan's private pages.
                        ring = (self.ring_prefill is not None
                                and plan.width > 1
                                and plan.width
                                >= self.ring_prefill_min_tokens)
                        # cap-provisioning baseline for the held-vs-
                        # budget accounting (what a per-slot slab at
                        # max_new_cap would have reserved)
                        plan.cap_budget_pages = self.kv_state.pages_needed(
                            q[0].effective_len(), self.max_new_cap)
                        req = q.popleft()
                        budget = self.prefill_budget_tokens
                        if ring:
                            ring_admits.append((free.pop(0), req, plan))
                        elif (budget is not None
                                and plan.width - 1 > budget):
                            # the uncached suffix exceeds one
                            # boundary's budget: chunked admission
                            chunk_admits.append((free.pop(0), req,
                                                 plan))
                        else:
                            admits.append((free.pop(0), req, plan))
                    else:
                        req = q.popleft()
                        admits.append((free.pop(0), req))
                self.metrics.on_queue_depth(
                    sum(len(x) for x in self._queues.values())
                )
            if page_starved:
                self.metrics.on_page_wait(b)
            if moe_blocked:
                self.metrics.on_moe_capacity_wait(b)
            for adm in admits + chunk_admits + ring_admits:
                if len(adm) == 3:
                    self.metrics.on_prefix(adm[1], adm[2])
            if admits:
                pool.join(admits)
            for slot, req, plan in ring_admits:
                pool.join_ring(slot, req, plan, self.ring_prefill)
                self.metrics.on_ring_prefill(req, req.effective_len(),
                                             self.ring_prefill)
            for slot, req, plan in chunk_admits:
                pool.begin_chunked(slot, req, plan)
            if admits or chunk_admits or ring_admits:
                t_adm = self.clock()
                for adm in admits + chunk_admits + ring_admits:
                    _slot, req = adm[0], adm[1]
                    req.state = RequestState.RUNNING
                    req.ts_admitted = t_adm
                    if req.await_transfer is not None:
                        # phase attribution (ISSUE 19): charge the
                        # transfer phase up to when its transfer
                        # settled (landed or failed), never past
                        # admission — phases() clamps the rest
                        st_tx = self._transfers.get(
                            str(req.await_transfer))
                        if st_tx is not None:
                            req.ts_transfer = st_tx.get("ts_settled",
                                                        t_adm)
                    self.metrics.on_admit(req)
                    # queue-wait span ends where ts_admitted is stamped
                    # — span duration and metrics queue_wait_ms
                    # describe the same interval
                    trace.end(getattr(req, "_span_queue", None),
                              slot=_slot)
                progress = True
                # prefill-only rows (ISSUE 14) are complete the moment
                # their prompt pass lands: export + free the slot
                # BEFORE any segment runs (chunked ones complete below
                # at their final chunk instead)
                for adm in admits + ring_admits:
                    if len(adm) == 3 and adm[1].prefill_only:
                        self._complete_prefill(pool, adm[0], adm[1])
            if (self.prefill_budget_tokens is not None
                    and isinstance(pool, PagedSlotPool)
                    and pool.prefilling.any()):
                # chunked prefill (ISSUE 13): ONE budget-bounded chunk
                # per boundary, round-robin over mid-prefill rows —
                # the decode segment below runs in the same boundary,
                # so chunks and segments strictly interleave
                adv = pool.advance_prefill(self.prefill_budget_tokens)
                if adv is not None:
                    _slot_pf, n_pf, done_pf = adv
                    self.metrics.on_prefill_chunk(b, n_pf, done_pf)
                    if done_pf:
                        req_pf = pool.occupants[_slot_pf]
                        if req_pf is not None:
                            # prefill/first-decode boundary stamp —
                            # the chunked pass is the one place the
                            # prefill phase is separable from the
                            # admission stamp (ISSUE 19)
                            req_pf.ts_prefill_done = self.clock()
                        if (req_pf is not None
                                and req_pf.prefill_only):
                            self._complete_prefill(pool, _slot_pf,
                                                   req_pf)
                    progress = True
            if pool.decode_live() and self.kv_state is not None:
                # incremental allocation (ISSUE 11): cover every live
                # row's next-segment writes BEFORE dispatch — a row the
                # store cannot cover is evicted back to the queue with
                # its prefix published (resume machinery), never left
                # to scatter KV into the sink or deadlock the pool.
                # Evictions go ONE AT A TIME with a re-sweep between:
                # the freed pages usually rescue the rest of the batch.
                while True:
                    starved, n_ext = pool.extend_for_segment()
                    if n_ext:
                        self.metrics.on_page_extends(n_ext)
                    if not starved:
                        break
                    slot, req = starved[0]
                    # publish BEFORE evict: the tree retains its own
                    # references, so the retry's re-prefill is a hit
                    # (pages stay LRU-evictable under pressure)
                    pool.publish_generated(slot)
                    pool.evict(slot)
                    self._requeue_mid_decode(req)
                    progress = True
            if pool.decode_live():
                events, live = pool.run_segment()
                _health.heartbeat(f"{self.metrics.prefix}.segment")
                seg_ts = self.clock()
                for slot, req, new, finished in events:
                    if new:
                        req.tokens.extend(new)
                        # per-row ITL (ISSUE 13): delta since this
                        # row's previous token-producing boundary,
                        # normalized per emitted token
                        if req.ts_last_tokens is not None:
                            self.metrics.on_itl(
                                req,
                                (seg_ts - req.ts_last_tokens) * 1e3,
                                len(new))
                        req.ts_last_tokens = seg_ts
                    # `finished` with no tokens = the first sampled
                    # token WAS the EOS: still a completed decode step,
                    # so TTFT must be stamped (or the histogram would
                    # silently drop exactly the fastest requests)
                    if (new or finished) and req.ts_first_token is None:
                        req.ts_first_token = seg_ts
                        self.metrics.on_first_token(req)
                        trace.end(getattr(req, "_span_ttft", None))
                    if finished:
                        if self.kv_insert_generated:
                            # publish the prompt+completion page chain
                            # BEFORE evict releases this request's
                            # references (the tree retains its own)
                            pool.publish_generated(slot)
                        pool.evict(slot)
                        self._finalize(req, RequestState.DONE)
                    self._stream(req, new, finished)
                self.metrics.on_segment(live, pool.slots)
                if self.moe_experts:
                    # per-expert load harvest (ISSUE 18): the segment
                    # fn counted each live token's top-k assignments —
                    # the latest segment IS the gate's window
                    load = getattr(pool, "last_expert_load", None)
                    if load is not None:
                        self._moe_load = np.asarray(load, np.float64)
                        self.metrics.on_moe_load(self._moe_load)
                if getattr(pool, "spec_k", 0):
                    drafted, accepted = pool.last_spec_stats
                    if drafted:
                        self.metrics.on_spec_round(drafted, accepted)
                progress = True
        if self.kv_state is not None:
            self.metrics.on_kv(self.kv_state)
        return progress

    # ---- drive modes ------------------------------------------------
    def run_until_idle(self) -> None:
        """Offline drive: loop :meth:`step` on the calling thread until
        no queued or running work remains."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "run_until_idle() while the background thread is "
                "running would race the device state"
            )
        while self.step():
            pass

    def idle(self) -> bool:
        with self._lock:
            if any(self._queues.values()):
                return False
            if self._fetch_inbox:  # an unanswered chain fetch is work
                return False
            pools = list(self.pools.values())
        return not any(p.has_live() for p in pools)

    def start(self) -> None:
        """Online drive: scheduler loop on a background thread (all
        device work stays on that thread; ``submit``/``cancel`` are
        thread-safe entry points)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._closed = False

        def loop():
            while not self._stop.is_set():
                _health.heartbeat(f"{self.metrics.prefix}.loop")
                try:
                    progress = self.step()
                except Exception as e:
                    # the only thread that decodes must never die
                    # silently (submit() would keep queueing into a
                    # black hole): record the fault, fail everything
                    # outstanding so result() waiters unblock with an
                    # error, and keep serving later arrivals
                    self.metrics.event("-scheduler-", "step_error",
                                       error=repr(e))
                    if self._watchdog is not None:
                        # flight isolation (ISSUE 14): a DEDICATED
                        # watchdog latches the fault so health() fails
                        # THIS replica over — the process default is
                        # deliberately not tripped here (the legacy
                        # single-scheduler contract: keep serving
                        # later arrivals)
                        self._watchdog.trip(
                            f"{self.metrics.prefix}: scheduler step "
                            f"failed: {type(e).__name__}: {e}")
                    self._fail_outstanding(f"scheduler step failed: "
                                           f"{type(e).__name__}: {e}")
                    progress = False
                if not progress:
                    with self._work:
                        self._work.wait(timeout=0.02)

        self._thread = threading.Thread(target=loop, name="tpuflow-serve",
                                        daemon=True)
        self._thread.start()

    def drain(self, wait_s: Optional[float] = None) -> None:
        """Graceful drain (ISSUE 8): stop admitting — :meth:`submit`
        raises :class:`SchedulerClosed` (HTTP 503) — while everything
        ALREADY submitted (queued and running) is served to completion
        by the still-running loop; ``/readyz`` flips immediately so a
        load balancer stops sending traffic. Non-blocking by default;
        ``wait_s`` blocks up to that many seconds for :meth:`idle`.
        The drain is recorded on the flight recorder's manifest notes
        (a post-mortem bundle dumped during/after the drain says so).
        Pair with :meth:`stop` once drained to tear the loop down;
        offline callers drive the remaining work with
        :meth:`run_until_idle` themselves."""
        with self._lock:
            first = not self._closed
            self._closed = True
            self._draining = True
            depth = sum(len(q) for q in self._queues.values())
            pools = list(self.pools.values())
            self._work.notify_all()
        if first:
            from tpuflow.obs import flight as _flight
            from tpuflow.obs.gauges import inc_counter, set_gauge

            set_gauge(f"{self.metrics.prefix}.draining", 1.0)
            inc_counter(f"{self.metrics.prefix}.drains_total")
            self.metrics.event("-scheduler-", "drain", queue_depth=depth)
            _flight.annotate(f"{self.metrics.prefix}.drain", {
                "ts": self.clock(),
                "queue_depth": depth,
                "running": sum(p.live_count() for p in pools),
            })
        if wait_s is not None:
            deadline = time.time() + wait_s
            while not self.idle() and time.time() < deadline:
                time.sleep(0.01)

    @property
    def draining(self) -> bool:
        """True between :meth:`drain` and teardown — closed to new
        work but still serving out the admitted backlog (a FAILED
        replica is closed and NOT draining; the router's failover
        telling them apart is the point of this property)."""
        return self._draining

    def drained(self) -> bool:
        """True once a drain has both been requested and finished
        serving everything it admitted — ``_draining``, not merely
        closed: ``stop(drain=False)`` CANCELS outstanding work, and
        the resulting idle closed scheduler must not read as a clean
        zero-truncation drain."""
        return self._draining and self.idle()

    def load_snapshot(self) -> Dict[str, Any]:
        """Lock-cheap load sensor (ISSUE 8): queue depth, running
        rows, free/total KV pages and windowed TTFT / queue-wait p95 —
        a plain dict, so the multi-replica router (or any external
        load balancer) never parses Prometheus text to place a
        request. Safe from any thread; one lock hop plus int reads.
        Percentile keys are None until traffic exists; they quote the
        metrics plane's WINDOWED view when the snapshot ring is
        ticking and degrade to cumulative otherwise (PR 5
        semantics)."""
        from tpuflow.obs import timeseries

        with self._lock:
            depth = sum(len(q) for q in self._queues.values())
            pools = list(self.pools.values())
            closed, draining = self._closed, self._draining
        out: Dict[str, Any] = {
            "queue_depth": depth,
            "running": sum(p.live_count() for p in pools),
            "slots_per_bucket": self.slots,
            "max_queue": self.max_queue,
            "closed": closed,
            "draining": draining,
            # disaggregation sensors (ISSUE 14): the router's
            # two-phase placement reads the class; transfer volume
            # rides for dashboards/external LBs
            "replica_class": self.replica_class,
            "kv_transfer_pages": self.metrics.kv_transfer_pages,
            "kv_transfer_bytes": self.metrics.kv_transfer_bytes,
            # deployment sensors (ISSUE 15): the router's version
            # fence / pin_version placement reads these
            "model_version": self.model_version,
            # clock-alignment anchor (ISSUE 19): this process's wall
            # clock at snapshot time — the router pairs it with the
            # probe's RTT midpoint to estimate the per-replica offset
            # that lines merged tier traces up
            "wall_s": time.time(),
        }
        if self.speculate_k:
            out["draft_version"] = self.draft_version
        if self.moe_experts:
            # expert-affinity sensor (ISSUE 18): the router steers new
            # placements away from replicas whose routing runs hot
            out["moe_hot_expert_frac"] = self.moe_hot_expert_frac()
            load = self._moe_load
            out["moe_expert_load"] = (
                None if load is None else [float(x) for x in load])
        # shed sensor (ISSUE 17): the router's Retry-After derives
        # from the cached snapshot plane — carrying the hint here
        # saves one RPC per eligible replica per shed, exactly when
        # the tier is overloaded. Computed OUTSIDE the lock block
        # above: retry_after_s() takes the same non-reentrant lock.
        out["retry_after_s"] = float(self.retry_after_s())
        if self.kv_state is not None:
            a = self.kv_state.allocator
            out["kv_pages_free"] = a.free_count()
            out["kv_pages_total"] = a.total
        elif self.kv_spec is not None:  # paged but no pool built yet
            out["kv_pages_free"] = self.kv_spec.pages - 1
            out["kv_pages_total"] = self.kv_spec.pages - 1
        pfx = self.metrics.prefix
        hists = (("ttft_ms", self.metrics.ttft_ms),
                 ("queue_wait_ms", self.metrics.queue_wait_ms),
                 ("itl_ms", self.metrics.itl_ms),
                 ("kv_transfer_ms", self.metrics.kv_transfer_ms))
        # cold sensor (no traffic yet): the percentile keys are None
        # without paying the windowed-delta walk — this path runs once
        # per replica per ROUTED REQUEST, so the empty case must be a
        # couple of int reads
        windowed = (timeseries.windowed_summaries(f"{pfx}.")
                    if any(len(h) for _, h in hists) else {})
        for key, hist in hists:
            if not len(hist):
                out[f"{key}_p95"] = None
                continue
            win = windowed.get(f"{pfx}.{key}")
            pcts = (win["percentiles"] if win else {}) or hist.percentiles()
            out[f"{key}_p95"] = pcts.get("p95")
        # per-phase TTFT/e2e attribution (ISSUE 19): windowed p95 per
        # SLO phase — what item 3's control loop reads to learn WHICH
        # phase is burning the budget, not just that p95 moved
        phases = {}
        for ph, hist in self.metrics.phase_hists.items():
            if not len(hist):
                continue
            win = windowed.get(f"{pfx}.req_phase_ms.{ph}")
            pcts = (win["percentiles"] if win else {}) or hist.percentiles()
            phases[ph] = pcts.get("p95")
        if phases:
            out["phase_ms_p95"] = phases
        # windowed error rate (ISSUE 20): failure terminals + transfer
        # fallbacks per trailing window — a long-healthy replica's
        # error SPIKE is visible to placement and the canary scorer,
        # not buried under its cumulative history (degrades to
        # cumulative without a ticking ring, PR 5 semantics)
        rate, errors, requests = self.metrics.windowed_error_rate()
        out["error_rate"] = round(rate, 6)
        out["errors_windowed"] = errors
        out["requests_windowed"] = requests
        # SLO verdicts (ISSUE 20): the process default evaluator's
        # compact view, cached — never a delta walk per placement
        from tpuflow.obs import slo as _slo

        ev = _slo.default_evaluator()
        if ev is not None:
            out["slo"] = ev.verdicts_compact()
        return out

    def version_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-``model_version`` cumulative metric cuts (ISSUE 20):
        counters + raw histogram states per version label — what the
        canary scorer delta-differences to compare blue vs green
        mid-rollout. Plain dicts off the metrics plane; safe from any
        thread."""
        return self.metrics.version_snapshot()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the loop. ``drain=True`` serves out queued+running work
        first; ``drain=False`` cancels everything outstanding (their
        ``result()`` unblocks with state CANCELLED)."""
        with self._lock:
            self._closed = True  # no new admissions either way
        deadline = time.time() + timeout
        started = self._thread is not None and self._thread.is_alive()
        if started and drain:
            while not self.idle() and time.time() < deadline:
                time.sleep(0.01)
        self._stop.set()
        if started:
            with self._work:
                self._work.notify_all()
            self._thread.join(timeout=max(0.1, deadline - time.time()))
        # leftover finalization runs EVEN when the loop never started:
        # requests queued before start() must still reach a terminal
        # state or their result() waiters hang forever
        self._fail_outstanding("scheduler stopped")

    def _fail_outstanding(self, error: str) -> None:
        """Drive every queued AND running request to a terminal state
        (queues emptied, slots evicted) — shutdown and fault paths."""
        leftovers: List[Request] = []
        with self._lock:
            for q in self._queues.values():
                leftovers.extend(q)
                q.clear()
            pools = list(self.pools.values())
        for pool in pools:
            for slot, req in enumerate(pool.occupants):
                if req is not None:
                    pool.evict(slot)
                    leftovers.append(req)
        for req in leftovers:
            self._finalize(req, RequestState.CANCELLED, error)

    # ---- introspection ----------------------------------------------
    def readiness(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Readiness (vs liveness) probe state — the ``/readyz`` half
        of the split health check (ISSUE 5). NOT ready when:

        - the scheduler is closed/stopping (drain in progress);
        - the watchdog tripped (NaN guard / stall — a post-mortem is
          the right next step, not more traffic);
        - work is pending but no decode segment completed within
          ``stall_after_s`` (wedged device/thread: queue fills while
          ``/healthz`` keeps answering — exactly the failure liveness
          cannot see);
        - the background loop thread exists but stopped beating.

        Returns ``{"ready": bool, ...detail}``; detail carries queue
        depth, running rows, watchdog state and heartbeat ages so the
        probe's reason is in the probe body."""
        t = time.monotonic() if now is None else now
        pfx = self.metrics.prefix
        with self._lock:
            depth = sum(len(q) for q in self._queues.values())
            closed = self._closed
            pools = list(self.pools.values())
        running = sum(p.live_count() for p in pools)
        seg_age = _health.heartbeat_age(f"{pfx}.segment", now=t)
        loop_age = _health.heartbeat_age(f"{pfx}.loop", now=t)
        # per-replica isolation (ISSUE 14 satellite): an injected
        # watchdog scopes the trip signal to THIS scheduler
        wd = self.watchdog
        threaded = self._thread is not None and self._thread.is_alive()
        # progress signal while work is pending: the FRESHEST of the
        # last segment and the loop heartbeat. The loop beats between
        # step() calls even while idle, so the first request after an
        # idle gap sees a fresh loop (ready — the stale segment stamp
        # is history, not a wedge); a thread stuck inside step()
        # (hung collective, first-touch pool compile) stops beating
        # both, and goes not-ready after stall_after_s. Readiness is
        # NOT latched: it recovers on the next probe once progress
        # resumes.
        ages = [a for a in (seg_age, loop_age) if a is not None]
        progress_age = min(ages) if ages else None
        stalled = bool(
            (depth or running)
            and progress_age is not None
            and progress_age > self.stall_after_s
        )
        # a launched-then-dead loop thread is a stall even with no
        # pending work: the next submit would queue into a black hole
        wedged_loop = bool(
            loop_age is not None and not threaded and not closed
            and loop_age > self.stall_after_s
        )
        ready = not (closed or wd.tripped or stalled or wedged_loop)
        return {
            "ready": ready,
            "closed": closed,
            "draining": self._draining,
            # the loop THREAD died after launch (distinct from a slow
            # step: a live thread inside a long compile/segment is
            # stalled-not-dead) — the replica shim's failover input
            "wedged_loop": wedged_loop,
            "watchdog": wd.state(),
            "queue_depth": depth,
            "running": running,
            "last_segment_age_s": (
                None if seg_age is None else round(seg_age, 3)
            ),
            "last_loop_age_s": (
                None if loop_age is None else round(loop_age, 3)
            ),
            "stall_after_s": self.stall_after_s,
        }

    def _requests_snapshot(self) -> List[Dict[str, Any]]:
        """JSON-able states of every queued + running request — the
        flight recorder's ``<prefix>_requests.json`` section (what was
        in flight when the process died)."""
        with self._lock:
            queued = [r for q in self._queues.values() for r in q]
            pools = list(self.pools.items())
        out = []
        for req in queued:
            rec = {"id": req.id, "state": "queued",
                   "bucket": req.bucket,
                   "prompt_tokens": int(req.prompt_ids.size),
                   "n_tokens": len(req.tokens)}
            if req.prefill_only:
                rec["prefill_only"] = True
            if req.await_transfer is not None:
                # transfer state (ISSUE 14): a post-mortem must tell a
                # request waiting on its inbound page chain from one
                # waiting on capacity
                tid = str(req.await_transfer)
                st = self._transfers.get(tid)
                rec["await_transfer"] = tid
                rec["transfer"] = (
                    "pending" if st is None
                    else "failed" if st.get("failed")
                    else "landed" if st.get("done") else "pending")
            out.append(rec)
        for b, pool in pools:
            for slot, req in enumerate(pool.occupants):
                if req is not None:
                    rec = {"id": req.id, "state": req.state.value,
                           "bucket": b, "slot": slot,
                           "prompt_tokens": int(req.prompt_ids.size),
                           "n_tokens": len(req.tokens)}
                    if bool(getattr(pool, "prefilling",
                                    np.zeros(0, bool))[slot:slot + 1].any()):
                        # chunked prefill (ISSUE 13): a post-mortem
                        # must tell a row mid-prompt from one decoding
                        rec["prefilling"] = True
                        rec["prefill_next"] = int(pool.prefill_next[slot])
                    out.append(rec)
        return out

    def kv_snapshot(self) -> Optional[Dict[str, Any]]:
        """Paged-KV accounting: allocator + prefix-tree stats, per-pool
        page-table occupancy, and bytes-per-live-token — the payload of
        ``tools/kv_memory_report.py`` and the flight recorder's
        ``<prefix>_kv.json`` section. None under the contiguous cache."""
        kvs = self.kv_state
        if kvs is None:
            return None
        snap = kvs.snapshot()
        with self._lock:
            pools = list(self.pools.items())
        live_tokens = 0
        tables: Dict[str, Any] = {}
        for b, pool in pools:
            if not isinstance(pool, PagedSlotPool):
                continue
            rows = []
            for slot, req in enumerate(pool.occupants):
                if req is None:
                    continue
                plan = pool.plans[slot]
                kv_len = int(min(pool.pos[slot], pool.kv_limit[slot]))
                live_tokens += kv_len
                held = 0 if plan is None else len(plan.table)
                budget = 0 if plan is None else plan.budget_pages
                rows.append({
                    "slot": slot, "id": req.id, "kv_len": kv_len,
                    "pages": 0 if plan is None else len(plan.owned),
                    "shared_prefix_tokens":
                        0 if plan is None else plan.matched_tokens,
                    # incremental allocation (ISSUE 11): what the row
                    # holds NOW vs the worst case it used to reserve
                    "budget_pages": budget,
                    "held_vs_budget": (round(held / budget, 3)
                                       if budget else None),
                })
            tables[str(b)] = rows
        snap["pools"] = tables
        snap["live_kv_tokens"] = live_tokens
        snap["bytes_per_live_token"] = (
            round(kvs.bytes_in_use() / live_tokens, 1)
            if live_tokens else None
        )
        return snap

    def spec_snapshot(self) -> Optional[Dict[str, Any]]:
        """Speculative-decoding state for post-mortems (the flight
        recorder's ``<prefix>_spec.json`` section): cumulative and
        windowed acceptance so a bundle shows whether a slow tail was
        acceptance COLLAPSE. None when speculation is off."""
        if not self.speculate_k:
            return None
        rounds, drafted, accepted, windowed = self.metrics.spec_totals()
        return {
            "k": self.speculate_k,
            "rounds": rounds,
            "drafted": drafted,
            "accepted": accepted,
            "accept_rate": (accepted / drafted if drafted else None),
            "accept_rate_windowed": windowed,
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        with self._lock:
            pools = list(self.pools.items())
        pfx = self.metrics.prefix  # honor per-scheduler namespacing
        for b, pool in pools:
            snap[f"{pfx}.pool{b}.live"] = float(pool.live_count())
            if isinstance(pool, PagedSlotPool):
                continue  # no shared horizon/rounds to report
            snap[f"{pfx}.pool{b}.t"] = float(pool.t)
            snap[f"{pfx}.pool{b}.rounds"] = float(pool.rounds_started)
        if self.kv_state is not None:
            a = self.kv_state.allocator
            snap[f"{pfx}.kv_pages_total"] = float(a.total)
            snap[f"{pfx}.kv_pages_in_use"] = float(a.in_use())
            snap[f"{pfx}.kv_bytes_in_use"] = float(
                self.kv_state.bytes_in_use())
            snap[f"{pfx}.kv_bytes_total"] = float(
                self.kv_state.bytes_total())
        return snap


def serve_texts(
    packaged_lm,
    prompts: Sequence[str],
    max_new_tokens: int,
    serve_slots: int,
    *,
    seg: int = 8,
    rounds: int = 1,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    seed: int = 0,
    kv: str = "contiguous",
    kv_pages: Optional[int] = None,
    kv_page_size: int = 16,
    kv_quant: Optional[str] = None,
    kv_kernel: Optional[bool] = None,
    speculate_k: int = 0,
    draft_model=None,
    draft_params=None,
    prefill_budget_tokens: Optional[int] = None,
    ring_prefill: Optional[int] = None,
    ring_prefill_min_tokens: int = 512,
) -> List[str]:
    """Offline text frontend over the slot scheduler — what
    ``PackagedLM.generate_text(serve_slots=..., scheduler='slot')``
    routes through. Returns prompt+continuation strings in input order,
    token-identical to the wave-drained path under the same seed.
    ``kv='paged'`` serves through the paged KV store (same tokens,
    different memory model — see :class:`ServeScheduler`);
    ``speculate_k`` adds draft-model speculative decoding on top
    (still the same tokens — oracle-parity acceptance)."""
    tok = packaged_lm._require_tokenizer()
    # rounds=1: an offline drain rewinds its horizon for free between
    # rounds (reset() is bookkeeping, not device work), so the extra
    # decode room a long-lived server buys with rounds>1 would only
    # inflate every KV buffer (and each decode step's attention span)
    # ~rounds-fold for nothing here
    sched = ServeScheduler(
        packaged_lm.model, packaged_lm.params, tokenizer=tok,
        slots=serve_slots, seg=seg, rounds=rounds,
        max_new_cap=max_new_tokens, max_queue=max(1, len(prompts)),
        temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id,
        seed=seed, kv=kv, kv_pages=kv_pages, kv_page_size=kv_page_size,
        kv_quant=kv_quant, kv_kernel=kv_kernel, speculate_k=speculate_k,
        draft_model=draft_model, draft_params=draft_params,
        prefill_budget_tokens=prefill_budget_tokens,
        ring_prefill=ring_prefill,
        ring_prefill_min_tokens=ring_prefill_min_tokens,
    )
    reqs = [sched.submit(p, max_new_tokens) for p in prompts]
    sched.run_until_idle()
    out = []
    for req in reqs:
        if req.state is not RequestState.DONE:  # pragma: no cover
            raise RuntimeError(
                f"request {req.id} ended {req.state.value}: {req.error}"
            )
        full = np.concatenate([req.prompt_ids,
                               np.asarray(req.tokens, np.int32)])
        out.append(tok.decode(full).decode("utf-8", "replace"))
    return out
