"""Canary scoring for blue/green rollouts (ISSUE 20) — the decision
that closes the deployment loop.

PR 15's :class:`~tpuflow.serve.deploy.DeploymentManager` rotates a
weight push to 100% of the tier on pure mechanics: if the swap
succeeds, the version ships. This module makes the FIRST rotation a
judged canary window: while the new-version replica and the remaining
old-version replicas both serve traffic, a :class:`CanaryScorer`
delta-differences the tier's per-version metric cuts
(:meth:`Router.version_snapshot`, ISSUE 20) per evaluation window and
compares new vs old on the signals that matter:

- **windowed error rate** — failure terminals + transfer fallbacks
  over completions, absolute ceiling AND ratio vs old;
- **ttft/itl p95 ratios** — the latency regressions a user feels;
- **phase-vector regressions** — the PR 19 per-phase p95s localize
  WHY a bad version is bad (a transfer blowup vs a queue_wait blowup
  name different suspects) — annotation, not an independent trigger;
- optional **pin_version quality probes** — prompts with expected
  token outputs, pinned to the new version (PR 15's token-identical
  per-version A/B), run as the final gate before full rotation.

Verdicts: ``retire_new`` (the push is bad — the manager drains the
NEW replica with the same zero-truncation machinery a normal rotation
uses on old ones and recycles it as standby; the tier never rotates
past the canary) or ``retire_old`` (proceed with the normal
rotation). Scoring happens on the manager's :meth:`tick` cadence —
never on the router's submit hot path — and all arithmetic is plain
host dicts/lists (pure host policy, pinned by the same grep-guard
idiom as the router tier).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpuflow.obs.timeseries import delta_histogram

#: histogram names inside a version cut compared at p95
_LATENCY_HISTS = ("ttft_ms", "itl_ms")


@dataclass
class CanaryPolicy:
    """Scoring thresholds for one canary window sequence. Defaults
    suit a bench/test cadence; production wants ``window_s`` at tens
    of seconds (burn-rate window sizing in README: the window must
    see ``min_requests`` of BOTH versions or it scores as
    inconclusive and is retried, not counted)."""

    #: clean evaluation windows required before retire_old
    windows: int = 3
    #: evaluation window length (manager-clock seconds)
    window_s: float = 5.0
    #: per-window per-version request floor below which the window is
    #: inconclusive (neither counted nor failed — traffic decides)
    min_requests: int = 8
    #: absolute new-version windowed error-rate ceiling
    max_error_rate: float = 0.05
    #: new/old windowed error-rate ratio that breaches (only past the
    #: absolute ceiling — a 0.1% vs 0.01% ratio is noise, not a fire)
    error_ratio: float = 3.0
    #: new/old p95 ratio on ttft_ms / itl_ms that breaches
    latency_ratio: float = 1.5
    #: new/old per-phase p95 ratio recorded as a localization
    #: annotation (phase regressions explain a breach, never trigger
    #: one alone)
    phase_ratio: float = 2.0
    #: consecutive bad windows that retire the new version early
    fail_windows: int = 2
    #: liveness cap: consecutive INCONCLUSIVE windows after which the
    #: scorer concludes anyway instead of holding the blue/green
    #: window forever on a drained tier (a hold with zero traffic can
    #: never score). Any unconfirmed bad window biases the forced
    #: verdict to retire_new; a clean-but-idle hold completes the
    #: rollout (matching what a canary-less push would have done),
    #: running the quality probes first when configured. 0 disables.
    max_idle_windows: int = 40
    #: optional quality probes: ``(prompt_tokens, expected_tokens)``
    #: pairs submitted pinned to the NEW version as the final gate
    quality_probes: Tuple = field(default_factory=tuple)
    #: wall budget for the probe phase before it fails closed
    probe_timeout_s: float = 60.0


class CanaryScorer:
    """Score one rollout's new-vs-old version cuts window by window.

    Drive with :meth:`tick` on the deployment manager's cadence (the
    clock is injectable — virtual-clock benches and tests pass the
    tier's clock). The scorer owns its captures: each window's
    comparison is ``version_snapshot(now) - version_snapshot(window
    start)``, so it needs no snapshot ring and works under any
    clock."""

    def __init__(self, router, *, old_label: str, new_label: str,
                 policy: Optional[CanaryPolicy] = None,
                 clock: Callable[[], float] = time.time):
        self.router = router
        self.old_label = str(old_label)
        self.new_label = str(new_label)
        self.policy = policy or CanaryPolicy()
        self.clock = clock
        self.windows_scored = 0
        self.consecutive_bad = 0
        self.consecutive_inconclusive = 0
        self.bad_windows = 0
        self._starved_reason: Optional[str] = None
        self.window_results: List[Dict[str, Any]] = []
        self._base: Optional[Dict[str, Any]] = None
        self._next_t: Optional[float] = None
        self._verdict: Optional[str] = None
        self._probes: Optional[List[Any]] = None
        self._probe_t0: Optional[float] = None
        self._probe_failures: List[str] = []

    # ---- lifecycle ---------------------------------------------------
    def begin(self) -> None:
        """Capture the baseline cut and arm the first window."""
        self._base = self.router.version_snapshot()
        self._next_t = self.clock() + self.policy.window_s

    def tick(self) -> Optional[str]:
        """Advance: score a window when one is due, run the probe
        gate when the horizon is reached. Returns the final verdict
        (``retire_new`` / ``retire_old``) once decided, else None
        (keep scoring)."""
        if self._verdict is not None:
            return self._verdict
        if self._base is None:
            self.begin()
            return None
        if self._probes is not None:
            return self._tick_probes()
        if self.clock() < self._next_t:
            return None
        self._next_t = self.clock() + self.policy.window_s
        self.score_window()
        return self._verdict

    @property
    def verdict(self) -> Optional[str]:
        return self._verdict

    # ---- window scoring ----------------------------------------------
    @staticmethod
    def _delta_cut(base: Optional[Dict[str, Any]],
                   cur: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
        """Windowed view of one version's cut: counter deltas (clamped
        at 0 — the reset idiom) + delta histograms."""
        if cur is None:
            return None
        b = base or {}
        out: Dict[str, Any] = {
            k: max(0, int(cur.get(k, 0)) - int(b.get(k, 0)))
            for k in ("done", "failed", "transfer_fallbacks",
                      "tokens_out")
        }
        out["requests"] = out["done"] + out["failed"]
        bh = b.get("hists", {})
        out["hists"] = {
            name: delta_histogram(st, bh.get(name))
            for name, st in cur.get("hists", {}).items()
        }
        return out

    @staticmethod
    def _err(cut: Dict[str, Any]) -> float:
        reqs = max(1, cut["requests"])
        return (cut["failed"] + cut["transfer_fallbacks"]) / reqs

    @staticmethod
    def _p95(cut: Dict[str, Any], name: str) -> Optional[float]:
        h = cut["hists"].get(name)
        if h is None or not h.n:
            return None
        return h.percentile(95.0)

    def score_window(self) -> Dict[str, Any]:
        """Compare the window's new-vs-old deltas and fold the result
        into the running verdict state. Inconclusive windows (either
        version under the traffic floor) are retried, not counted."""
        pol = self.policy
        snap = self.router.version_snapshot()
        new = self._delta_cut(
            (self._base or {}).get(self.new_label),
            snap.get(self.new_label))
        old = self._delta_cut(
            (self._base or {}).get(self.old_label),
            snap.get(self.old_label))
        self._base = snap
        res: Dict[str, Any] = {
            "ts": self.clock(), "bad": False, "inconclusive": False,
            "reasons": [], "phase_regressions": [],
            "new_requests": 0 if new is None else new["requests"],
            "old_requests": 0 if old is None else old["requests"],
        }
        if new is None or new["requests"] < pol.min_requests:
            res["inconclusive"] = True
            self.window_results.append(res)
            self.consecutive_inconclusive += 1
            if (pol.max_idle_windows
                    and self.consecutive_inconclusive
                    >= pol.max_idle_windows):
                # liveness give-up: a drained tier can never feed a
                # window, and an eternal hold wedges the rollout
                if self.bad_windows:
                    self._starved_reason = (
                        f"canary starved: {self.consecutive_inconclusive}"
                        f" consecutive idle window(s) with "
                        f"{self.bad_windows} unconfirmed bad window(s)")
                    self._verdict = "retire_new"
                elif pol.quality_probes:
                    self._start_probes()
                else:
                    self._verdict = "retire_old"
            return res
        self.consecutive_inconclusive = 0
        err_new = self._err(new)
        res["error_rate_new"] = round(err_new, 4)
        has_old = old is not None and old["requests"] >= pol.min_requests
        if has_old:
            err_old = self._err(old)
            res["error_rate_old"] = round(err_old, 4)
            if (err_new > pol.max_error_rate
                    and err_new > pol.error_ratio * max(err_old, 1e-9)):
                res["reasons"].append(
                    f"error rate {err_new:.3f} vs old {err_old:.3f} "
                    f"(> {pol.max_error_rate:g} and > "
                    f"{pol.error_ratio:g}x old)")
            for name in _LATENCY_HISTS:
                pn, po = self._p95(new, name), self._p95(old, name)
                if pn is None or po is None or po <= 0:
                    continue
                ratio = pn / po
                res[f"{name}_p95_ratio"] = round(ratio, 3)
                if ratio > pol.latency_ratio:
                    res["reasons"].append(
                        f"{name} p95 x{ratio:.2f} "
                        f"({pn:.1f}ms vs {po:.1f}ms)")
            # phase localization (never a trigger): WHICH phase of the
            # PR 19 vector blew up names the suspect subsystem
            for name in new["hists"]:
                if not name.startswith("req_phase_ms."):
                    continue
                pn, po = self._p95(new, name), self._p95(old, name)
                if pn is None or po is None or po <= 0:
                    continue
                if pn / po > pol.phase_ratio:
                    res["phase_regressions"].append(
                        f"{name.split('.', 1)[1]} p95 x{pn / po:.2f}")
        else:
            res["no_old_baseline"] = True
            # no comparand: only the absolute error ceiling can judge
            if err_new > pol.max_error_rate:
                res["reasons"].append(
                    f"error rate {err_new:.3f} > {pol.max_error_rate:g}"
                    f" (no old-version baseline)")
        res["bad"] = bool(res["reasons"])
        self.window_results.append(res)
        self.windows_scored += 1
        if res["bad"]:
            self.bad_windows += 1
            self.consecutive_bad += 1
        else:
            self.consecutive_bad = 0
        if self.consecutive_bad >= pol.fail_windows:
            self._verdict = "retire_new"
        elif self.windows_scored >= pol.windows:
            if self.bad_windows:
                # unhealed badness at the horizon: not confident —
                # protect the tier
                self._verdict = "retire_new"
            elif pol.quality_probes:
                self._start_probes()
            else:
                self._verdict = "retire_old"
        return res

    # ---- quality probes (final gate) ---------------------------------
    def _start_probes(self) -> None:
        import numpy as np

        self._probes = []
        self._probe_t0 = self.clock()
        for prompt, expected in self.policy.quality_probes:
            exp = [int(t) for t in expected]
            try:
                req = self.router.submit(
                    np.asarray(prompt, np.int32), len(exp),
                    pin_version=self.new_label)
            except Exception as e:
                self._probe_failures.append(
                    f"probe submit failed: {type(e).__name__}: {e}")
                continue
            self._probes.append((req, exp))

    def _tick_probes(self) -> Optional[str]:
        pending = []
        for req, exp in self._probes:
            state = getattr(req.state, "value", req.state)
            if state in ("queued", "running"):
                pending.append((req, exp))
                continue
            if state != "done":
                self._probe_failures.append(
                    f"probe {state}: {getattr(req, 'error', None)}")
            elif [int(t) for t in req.tokens] != exp:
                self._probe_failures.append(
                    f"probe tokens diverged from expected "
                    f"({list(req.tokens)[:8]}... vs {exp[:8]}...)")
        self._probes = pending
        if pending:
            if (self.clock() - self._probe_t0
                    > self.policy.probe_timeout_s):
                # fail CLOSED: an unanswerable probe is not a pass
                self._probe_failures.append(
                    f"{len(pending)} probe(s) timed out after "
                    f"{self.policy.probe_timeout_s:g}s")
                self._verdict = "retire_new"
            return self._verdict
        self._verdict = ("retire_new" if self._probe_failures
                         else "retire_old")
        return self._verdict

    # ---- summary ------------------------------------------------------
    def reasons(self) -> List[str]:
        """Every breach reason across scored windows + probe
        failures — what the rollback record carries."""
        out: List[str] = []
        for res in self.window_results:
            out.extend(res["reasons"])
        if self._starved_reason:
            out.append(self._starved_reason)
        out.extend(self._probe_failures)
        return out

    def summary(self) -> Dict[str, Any]:
        """JSON-able scoring record for the deploy history / flight
        note: verdict, window tallies, breach reasons, and the phase
        localizations that say WHY."""
        phases: List[str] = []
        for res in self.window_results:
            phases.extend(res.get("phase_regressions", ()))
        return {
            "old": self.old_label, "new": self.new_label,
            "verdict": self._verdict,
            "windows_scored": self.windows_scored,
            "bad_windows": self.bad_windows,
            "inconclusive_windows": sum(
                1 for r in self.window_results if r["inconclusive"]),
            "reasons": self.reasons(),
            "phase_regressions": phases,
            "probe_failures": list(self._probe_failures),
        }
