"""Replica transport shim for the multi-replica serving tier.

The router (:mod:`tpuflow.serve.router`) never talks to a
:class:`~tpuflow.serve.scheduler.ServeScheduler` directly — it talks to
a :class:`Replica`, the narrow surface a serving backend must offer:
submit / cancel / load_snapshot / health / drain, plus the offline
drive hooks the deterministic tests and the virtual-clock bench use.
:class:`InProcessReplica` is the one backend today (N schedulers in one
process, each on its own scheduler thread); an HTTP backend speaking to
a remote ``python -m tpuflow.serve`` instance implements the same
methods over ``POST /v1/generate`` + ``GET /readyz`` + the
``load_snapshot`` JSON and drops in without touching the router —
which is exactly the seam where ROADMAP item 3's prefill/decode
disaggregation becomes a config change.

Thread discipline: everything here delegates to scheduler entry points
that are already thread-safe (``submit``/``cancel``/``load_snapshot``)
or documented single-thread (``step``/``run_until_idle`` — offline
drive only). No device work happens in this module: the router tier is
pure host policy, and a guard test pins that boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from tpuflow.serve.request import Request


class Replica:
    """Abstract replica surface (duck-typed; subclassing optional).

    Required of every backend:

    - ``name`` — stable identity for placement/affinity bookkeeping;
    - :meth:`submit` / :meth:`cancel` — the request surface, raising
      the scheduler's own ``QueueFull`` / ``SchedulerClosed`` /
      ``ValueError`` taxonomy;
    - :meth:`load_snapshot` — the placement sensor (queue depth,
      running rows, free KV pages, windowed latency p95s);
    - :meth:`health` — ``{"failed": bool, ...}``, the failover input;
    - :meth:`drain` / :meth:`stop` / :meth:`start`;
    - :meth:`bucket_of` and the ``slots`` / ``max_new_cap`` /
      ``page_size`` attributes — what the router needs to pin stream
      ids and hash prefix chunks the way the replica's cache does.
    """

    name: str = "?"

    def submit(self, prompt, max_new_tokens=None, **kw) -> Request:
        raise NotImplementedError

    def cancel(self, request) -> bool:
        raise NotImplementedError

    def load_snapshot(self) -> Dict[str, Any]:
        raise NotImplementedError

    def health(self) -> Dict[str, Any]:
        raise NotImplementedError


class InProcessReplica(Replica):
    """One in-process :class:`ServeScheduler` behind the replica
    surface. Give each replica its own metrics namespace
    (``ServeMetrics(gauge_prefix="serve.replica0")`` etc.) or their
    gauges overwrite each other in the shared registry — the
    ``serve.replica<i>`` spelling additionally renders as a
    ``replica="i"`` label in the Prometheus exposition."""

    def __init__(self, scheduler, name: Optional[str] = None):
        self.sched = scheduler
        self.name = name or scheduler.metrics.prefix

    # ---- request surface (any thread) -------------------------------
    def submit(self, prompt, max_new_tokens=None, *,
               deadline_s: Optional[float] = None,
               stream_cb: Optional[Callable] = None,
               request_id: Optional[str] = None,
               stream_id: Optional[int] = None,
               speculate: bool = True) -> Request:
        return self.sched.submit(
            prompt, max_new_tokens, deadline_s=deadline_s,
            stream_cb=stream_cb, request_id=request_id,
            stream_id=stream_id, speculate=speculate,
        )

    def cancel(self, request) -> bool:
        return self.sched.cancel(request)

    # ---- sensors -----------------------------------------------------
    def load_snapshot(self) -> Dict[str, Any]:
        return self.sched.load_snapshot()

    def readiness(self) -> Dict[str, Any]:
        return self.sched.readiness()

    def health(self) -> Dict[str, Any]:
        """Failover input: ``failed`` = watchdog-tripped, or closed
        WITHOUT a drain (a draining replica serves its own backlog —
        resubmitting it elsewhere would double-serve), or a launched
        loop thread that DIED (``readiness()``'s ``wedged_loop``: the
        thread-alive-aware signal — a live thread inside a long
        first-touch compile or slow segment is stalled, not dead, and
        must NOT cascade into failover). NOTE the watchdog is
        process-global (PR 5): in-process replicas share it, so a
        NaN/stall trip fails the whole in-process tier over at once —
        per-replica watchdog isolation arrives with out-of-process
        backends."""
        r = self.sched.readiness()
        wd = r.get("watchdog") or {}
        tripped = bool(wd.get("tripped"))
        closed = bool(r.get("closed"))
        draining = bool(r.get("draining"))
        dead_loop = bool(r.get("wedged_loop"))
        return {
            "failed": tripped or (closed and not draining) or dead_loop,
            "tripped": tripped,
            "closed": closed,
            "draining": draining,
            "ready": bool(r.get("ready")),
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self.sched.metrics_snapshot()

    @property
    def metrics(self):
        return self.sched.metrics

    # ---- shape facts the router pins placement on --------------------
    @property
    def slots(self) -> int:
        return self.sched.slots

    @property
    def max_new_cap(self) -> int:
        return self.sched.max_new_cap

    @property
    def page_size(self) -> Optional[int]:
        spec = self.sched.kv_spec
        return None if spec is None else spec.page_size

    @property
    def tokenizer(self):
        return self.sched.tokenizer

    def bucket_of(self, prompt_len: int) -> int:
        from tpuflow.packaging.lm import _bucket_len

        return _bucket_len(int(prompt_len))

    def pages_needed(self, prompt_len: int, max_new: int) -> Optional[int]:
        from tpuflow.serve.pages import pages_needed

        spec = self.sched.kv_spec
        if spec is None:
            return None
        return pages_needed(int(prompt_len), int(max_new), spec.page_size)

    def retry_after_s(self) -> float:
        return self.sched.retry_after_s()

    # ---- lifecycle ---------------------------------------------------
    def start(self) -> None:
        self.sched.start()

    def drain(self) -> None:
        self.sched.drain()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self.sched.stop(drain=drain, timeout=timeout)

    def prepare(self, *buckets: int) -> None:
        self.sched.prepare(*buckets)

    # ---- offline drive (tests / virtual-clock bench) -----------------
    def step(self) -> bool:
        return self.sched.step()

    def idle(self) -> bool:
        return self.sched.idle()
