"""Replica transport shim for the multi-replica serving tier.

The router (:mod:`tpuflow.serve.router`) never talks to a
:class:`~tpuflow.serve.scheduler.ServeScheduler` directly — it talks to
a :class:`Replica`, the narrow surface a serving backend must offer:
submit / cancel / load_snapshot / health / drain, plus the offline
drive hooks the deterministic tests and the virtual-clock bench use.
Two backends (ISSUE 8 built the seam; ISSUE 14 fills it):

- :class:`InProcessReplica` — N schedulers in one process, each on its
  own scheduler thread, sharing loaded weights;
- :class:`HTTPReplica` — an OUT-OF-PROCESS worker (its own ``python -m
  tpuflow.serve`` instance that loaded weights itself) behind the
  ``/v1/worker/*`` endpoints of :mod:`tpuflow.serve.http`. The worker
  process owns its device state, its own process-default watchdog and
  its own blast radius: one worker dying fails over exactly one
  replica, and the router's ``--connect host:port,...`` CLI turns the
  tier into config. Streaming rides chunked NDJSON; KV page chains
  cross as the serve/pages.py wire format (base64 over JSON) — the
  prefill/decode disaggregation transport.

Thread discipline: everything here delegates to scheduler entry points
that are already thread-safe (``submit``/``cancel``/``load_snapshot``)
or documented single-thread (``step``/``run_until_idle`` — offline
drive only). No device work happens in this module: the router tier is
pure host policy, and a guard test pins that boundary (HTTPReplica is
host-only by construction — the device lives in another process).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tpuflow.serve.request import (
    QueueFull,
    Request,
    RequestState,
    SchedulerClosed,
)


class Replica:
    """Abstract replica surface (duck-typed; subclassing optional).

    Required of every backend:

    - ``name`` — stable identity for placement/affinity bookkeeping;
    - :meth:`submit` / :meth:`cancel` — the request surface, raising
      the scheduler's own ``QueueFull`` / ``SchedulerClosed`` /
      ``ValueError`` taxonomy;
    - :meth:`load_snapshot` — the placement sensor (queue depth,
      running rows, free KV pages, windowed latency p95s). An optional
      ``retry_after_s`` key is the shed hint (ISSUE 17): when present,
      the router's cached snapshot plane derives tier Retry-After from
      it instead of firing one :meth:`retry_after_s` RPC per eligible
      replica at the exact moment the tier is overloaded — backends
      without the key still work, they just pay the RPC fallback;
    - :meth:`health` — ``{"failed": bool, ...}``, the failover input;
    - :meth:`drain` / :meth:`stop` / :meth:`start`;
    - :meth:`bucket_of` and the ``slots`` / ``max_new_cap`` /
      ``page_size`` attributes — what the router needs to pin stream
      ids and hash prefix chunks the way the replica's cache does.
    """

    name: str = "?"

    def submit(self, prompt, max_new_tokens=None, **kw) -> Request:
        raise NotImplementedError

    def cancel(self, request) -> bool:
        raise NotImplementedError

    def load_snapshot(self) -> Dict[str, Any]:
        raise NotImplementedError

    def health(self) -> Dict[str, Any]:
        raise NotImplementedError

    def trace_spans(self, request_id: str):
        """Per-replica spans of one trace for the router's tier-trace
        fan-out (ISSUE 19). ``None`` means this replica records into
        the ROUTER's own process-wide span ring (in-process replicas)
        — its spans are already in the router's local view and fanning
        out would double-count them. Out-of-process backends return
        the replica-local span list instead."""
        return None


class InProcessReplica(Replica):
    """One in-process :class:`ServeScheduler` behind the replica
    surface. Give each replica its own metrics namespace
    (``ServeMetrics(gauge_prefix="serve.replica0")`` etc.) or their
    gauges overwrite each other in the shared registry — the
    ``serve.replica<i>`` spelling additionally renders as a
    ``replica="i"`` label in the Prometheus exposition."""

    def __init__(self, scheduler, name: Optional[str] = None):
        self.sched = scheduler
        self.name = name or scheduler.metrics.prefix

    # ---- request surface (any thread) -------------------------------
    def submit(self, prompt, max_new_tokens=None, *,
               deadline_s: Optional[float] = None,
               stream_cb: Optional[Callable] = None,
               request_id: Optional[str] = None,
               stream_id: Optional[int] = None,
               speculate: bool = True,
               await_transfer: Optional[str] = None,
               trace_ctx: Optional[Dict[str, Any]] = None) -> Request:
        return self.sched.submit(
            prompt, max_new_tokens, deadline_s=deadline_s,
            stream_cb=stream_cb, request_id=request_id,
            stream_id=stream_id, speculate=speculate,
            await_transfer=await_transfer, trace_ctx=trace_ctx,
        )

    def cancel(self, request) -> bool:
        return self.sched.cancel(request)

    # ---- prefill/decode disaggregation (ISSUE 14) -------------------
    @property
    def replica_class(self) -> str:
        return getattr(self.sched, "replica_class", "mixed")

    def submit_prefill(self, prompt, *,
                       deadline_s: Optional[float] = None,
                       stream_cb: Optional[Callable] = None,
                       request_id: Optional[str] = None,
                       trace_ctx: Optional[Dict[str, Any]] = None
                       ) -> Request:
        return self.sched.submit_prefill(
            prompt, deadline_s=deadline_s, stream_cb=stream_cb,
            request_id=request_id, trace_ctx=trace_ctx)

    def offer_chain(self, wire, *, transfer_id: Optional[str] = None,
                    last: bool = True,
                    trace_ctx: Optional[Dict[str, Any]] = None) -> str:
        return self.sched.offer_chain(wire, transfer_id=transfer_id,
                                      last=last, trace_ctx=trace_ctx)

    def fail_transfer(self, transfer_id: str,
                      reason: str = "transfer failed") -> None:
        self.sched.fail_transfer(transfer_id, reason)

    # ---- tiered KV / directory pulls (ISSUE 16) ---------------------
    def request_chain(self, tokens, on_ready) -> None:
        """Donor side of a directory pull: answer with this replica's
        deepest coverage of the prefix (resident or spilled) via
        ``on_ready(wire_or_None)`` at the scheduler's next boundary."""
        self.sched.request_chain(tokens, on_ready)

    def kv_chain_report(self) -> List[Dict[str, Any]]:
        return self.sched.kv_chain_report()

    # ---- zero-downtime deployment (ISSUE 15) ------------------------
    @property
    def model_version(self):
        return self.sched.model_version

    def swap_from_manifest(self, mpath: str, *,
                           draft: bool = False) -> Dict[str, Any]:
        """Hot-swap this replica's weights from a published sharded
        manifest (quiescent replicas only — the standby contract;
        :meth:`ServeScheduler.swap_from_manifest`)."""
        return self.sched.swap_from_manifest(mpath, draft=draft)

    def reopen(self) -> None:
        self.sched.reopen()

    # ---- sensors -----------------------------------------------------
    def load_snapshot(self) -> Dict[str, Any]:
        return self.sched.load_snapshot()

    def version_snapshot(self) -> Dict[str, Any]:
        """Per-version metric cuts (ISSUE 20) — the canary scorer's
        comparand."""
        return self.sched.version_snapshot()

    def readiness(self) -> Dict[str, Any]:
        return self.sched.readiness()

    def health(self) -> Dict[str, Any]:
        """Failover input — delegates to
        :meth:`ServeScheduler.health`. Per-replica isolation (ISSUE
        14, closing the PR 8 note): construct each scheduler with its
        OWN ``watchdog=`` and a trip fails over only that replica;
        without one, in-process replicas share the process default
        and fail over together. A live thread inside a long
        first-touch compile is stalled, not dead, and never cascades
        into failover (``wedged_loop`` is thread-alive-aware)."""
        return self.sched.health()

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self.sched.metrics_snapshot()

    @property
    def metrics(self):
        return self.sched.metrics

    # ---- shape facts the router pins placement on --------------------
    @property
    def slots(self) -> int:
        return self.sched.slots

    @property
    def max_new_cap(self) -> int:
        return self.sched.max_new_cap

    @property
    def page_size(self) -> Optional[int]:
        spec = self.sched.kv_spec
        return None if spec is None else spec.page_size

    @property
    def tokenizer(self):
        return self.sched.tokenizer

    def bucket_of(self, prompt_len: int) -> int:
        from tpuflow.packaging.lm import _bucket_len

        return _bucket_len(int(prompt_len))

    def pages_needed(self, prompt_len: int, max_new: int) -> Optional[int]:
        from tpuflow.serve.pages import pages_needed

        spec = self.sched.kv_spec
        if spec is None:
            return None
        return pages_needed(int(prompt_len), int(max_new), spec.page_size)

    def retry_after_s(self) -> float:
        return self.sched.retry_after_s()

    # ---- lifecycle ---------------------------------------------------
    def start(self) -> None:
        self.sched.start()

    def drain(self) -> None:
        self.sched.drain()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self.sched.stop(drain=drain, timeout=timeout)

    def prepare(self, *buckets: int) -> None:
        self.sched.prepare(*buckets)

    # ---- offline drive (tests / virtual-clock bench) -----------------
    def step(self) -> bool:
        return self.sched.step()

    def idle(self) -> bool:
        return self.sched.idle()


class _RemoteTokenizer:
    """Tokenizer proxy over a worker's ``/v1/worker/encode|decode`` —
    the router never needs local weights OR a local tokenizer to front
    remote workers (``--connect`` loads nothing)."""

    def __init__(self, replica: "HTTPReplica"):
        self._rep = replica

    def encode(self, text: str):
        out = self._rep._post_json("/v1/worker/encode", {"text": text})
        return np.asarray(out["ids"], np.int32)

    def decode(self, ids) -> bytes:
        ids = np.asarray(ids, np.int32).reshape(-1).tolist()
        out = self._rep._post_json("/v1/worker/decode", {"ids": ids})
        return out["text"].encode("utf-8")


class HTTPReplica(Replica):
    """Out-of-process replica: the same 10-method surface spoken over
    HTTP to a worker ``python -m tpuflow.serve`` instance (which
    loaded its own weights — per-process device state, per-process
    watchdog, real blast-radius containment). ``submit`` streams
    chunked NDJSON on a per-request reader thread that mirrors the
    remote request into a local shadow :class:`Request` (tokens,
    terminal state, stream callbacks), so the router drives remote and
    in-process replicas identically; a dropped connection finalizes
    the shadow CANCELLED — never-admitted requests then ride the
    router's normal failover resubmission, token-identically (their
    pinned stream id travels with them). Page-chain transfers cross as
    the serve/pages.py wire format, base64 over JSON.

    Offline drive (``step``) is not available over HTTP — remote tiers
    run online (``Router.start()``)."""

    def __init__(self, address: str, *, name: Optional[str] = None,
                 timeout_s: float = 120.0,
                 connect_timeout_s: float = 10.0):
        addr = address
        for pfx in ("http://", "https://"):
            if addr.startswith(pfx):
                addr = addr[len(pfx):]
        addr = addr.rstrip("/")
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"address must be host:port (got {address!r})")
        self.host, self.port = host, int(port)
        self.address = f"{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.metrics = None  # no local event log to merge
        cfg = self._get_json("/v1/worker/config")
        self.name = name or str(cfg.get("name") or self.address)
        self.slots = int(cfg.get("slots", 1))
        self.max_new_cap = int(cfg.get("max_new_cap", 64))
        self.page_size = cfg.get("page_size")
        if self.page_size is not None:
            self.page_size = int(self.page_size)
        self.replica_class = str(cfg.get("replica_class", "mixed"))
        self.model_version = cfg.get("model_version")
        self.tokenizer = (_RemoteTokenizer(self)
                          if cfg.get("has_tokenizer") else None)

    # ---- plumbing ----------------------------------------------------
    def _open(self, method: str, path: str, body=None,
              timeout: Optional[float] = None):
        import http.client

        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout_s if timeout is None else timeout)
        payload = None if body is None else json.dumps(body).encode()
        headers = ({"Content-Type": "application/json"}
                   if payload is not None else {})
        conn.request(method, path, body=payload, headers=headers)
        return conn, conn.getresponse()

    @staticmethod
    def _raise_for(status: int, obj: Dict[str, Any]) -> None:
        """Map worker HTTP statuses back onto the scheduler's own
        exception taxonomy — the router's retry/shed/failover logic
        must not care which transport a replica speaks."""
        if status == 429:
            raise QueueFull(int(obj.get("depth", 0)),
                            float(obj.get("retry_after_s", 1.0)))
        if status == 503:
            raise SchedulerClosed(str(obj.get("error", "closed")))
        if status == 400:
            raise ValueError(str(obj.get("error", "bad request")))
        if status >= 400:
            trace = obj.get("trace")
            raise RuntimeError(
                f"worker returned {status}: {obj.get('error')}"
                + (f" [{' | '.join(trace[-3:])}]" if trace else ""))

    def _call(self, method: str, path: str, body=None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        conn, resp = self._open(method, path, body, timeout=timeout)
        try:
            raw = resp.read()
        finally:
            conn.close()
        obj = json.loads(raw.decode() or "{}")
        self._raise_for(resp.status, obj)
        return obj

    def _get_json(self, path: str) -> Dict[str, Any]:
        return self._call("GET", path,
                          timeout=self.connect_timeout_s)

    def _post_json(self, path: str, body) -> Dict[str, Any]:
        return self._call("POST", path, body)

    # ---- request surface ---------------------------------------------
    def _encode_prompt(self, prompt) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts need a worker-side tokenizer")
            return self.tokenizer.encode(prompt)
        return np.asarray(prompt, np.int32).reshape(-1)

    def submit(self, prompt, max_new_tokens=None, *,
               deadline_s: Optional[float] = None,
               stream_cb: Optional[Callable] = None,
               request_id: Optional[str] = None,
               stream_id: Optional[int] = None,
               speculate: bool = True,
               await_transfer: Optional[str] = None,
               trace_ctx: Optional[Dict[str, Any]] = None) -> Request:
        ids = self._encode_prompt(prompt)
        if max_new_tokens is None:
            max_new_tokens = self.max_new_cap
        body: Dict[str, Any] = {
            "prompt": ids.tolist(),
            "max_new_tokens": int(max_new_tokens),
            "speculate": bool(speculate),
        }
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        if request_id:
            body["id"] = str(request_id)
        if stream_id is not None:
            body["stream_id"] = int(stream_id)
        if await_transfer is not None:
            body["await_transfer"] = str(await_transfer)
        if trace_ctx:  # distributed-trace context (ISSUE 19)
            body["trace_ctx"] = dict(trace_ctx)
        conn, resp = self._open("POST", "/v1/worker/submit", body)
        if resp.status != 200:
            try:
                obj = json.loads(resp.read().decode() or "{}")
            finally:
                conn.close()
            self._raise_for(resp.status, obj)
        shadow = Request(prompt_ids=ids,
                         max_new_tokens=int(max_new_tokens),
                         id=request_id or "", stream_cb=stream_cb)
        shadow.stream_id = int(stream_id or 0) % max(1, self.slots)
        shadow.speculate = bool(speculate)
        threading.Thread(
            target=self._reader, args=(conn, resp, shadow),
            name=f"tpuflow-httprep-{self.name}-{shadow.id}",
            daemon=True).start()
        return shadow

    def _reader(self, conn, resp, shadow: Request) -> None:
        """Per-request stream reader: mirror NDJSON events into the
        shadow request. A lost connection (worker died mid-flight)
        finalizes CANCELLED — with no tokens and no admission stamp
        that is exactly the router's failover-candidate shape."""
        try:
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line.decode())
                if "tokens" in ev and not ev.get("done"):
                    new = [int(t) for t in ev["tokens"]]
                    if new and shadow.ts_admitted is None:
                        shadow.ts_admitted = time.time()
                        shadow.state = RequestState.RUNNING
                    if new and shadow.ts_first_token is None:
                        shadow.ts_first_token = time.time()
                    shadow.tokens.extend(new)
                    if shadow.stream_cb is not None and new:
                        try:
                            shadow.stream_cb(shadow, new, False)
                        except Exception:
                            pass
                elif ev.get("done"):
                    state = RequestState(ev.get("state", "done"))
                    final = [int(t) for t in ev.get("tokens", [])]
                    if len(final) >= len(shadow.tokens):
                        extra = final[len(shadow.tokens):]
                        shadow.tokens.extend(extra)
                    if ev.get("ts_admitted") and shadow.ts_admitted is None:
                        shadow.ts_admitted = float(ev["ts_admitted"])
                    shadow.finalize(state, ev.get("error"))
                    if shadow.stream_cb is not None:
                        try:
                            shadow.stream_cb(shadow, [], True)
                        except Exception:
                            pass
                    return
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass
        if shadow.state in (RequestState.QUEUED, RequestState.RUNNING):
            shadow.finalize(RequestState.CANCELLED,
                            "replica connection lost")
            if shadow.stream_cb is not None:
                try:
                    shadow.stream_cb(shadow, [], True)
                except Exception:
                    pass

    def cancel(self, request) -> bool:
        # the frontend's own cancel route IS the worker cancel (same
        # scheduler, same id semantics)
        rid = request.id if isinstance(request, Request) else str(request)
        try:
            return bool(self._post_json("/v1/cancel",
                                        {"id": rid}).get("cancelled"))
        except Exception:
            return False

    # ---- prefill/decode disaggregation ------------------------------
    def submit_prefill(self, prompt, *,
                       deadline_s: Optional[float] = None,
                       stream_cb: Optional[Callable] = None,
                       request_id: Optional[str] = None,
                       trace_ctx: Optional[Dict[str, Any]] = None
                       ) -> Request:
        """Run a prefill-only request on the worker and mirror its
        exported wire back (``shadow.export``); the blocking HTTP call
        rides a background thread so the caller (the router, possibly
        on another replica's scheduler thread) never blocks."""
        ids = self._encode_prompt(prompt)
        shadow = Request(prompt_ids=ids, max_new_tokens=1,
                         id=request_id or "", stream_cb=stream_cb)
        shadow.prefill_only = True

        def run():
            from tpuflow.serve.pages import wire_from_json

            err = None
            try:
                out = self._post_json("/v1/worker/prefill", {
                    "prompt": ids.tolist(),
                    "id": shadow.id,
                    **({"deadline_s": float(deadline_s)}
                       if deadline_s is not None else {}),
                    **({"trace_ctx": dict(trace_ctx)}
                       if trace_ctx else {}),
                })
                if out.get("wire") is not None:
                    shadow.export = wire_from_json(out["wire"])
            except Exception as e:
                err = f"{type(e).__name__}: {e}"
            state = (RequestState.DONE if shadow.export is not None
                     else RequestState.CANCELLED)
            shadow.finalize(state, err)
            if shadow.stream_cb is not None:
                try:
                    shadow.stream_cb(shadow, [], True)
                except Exception:
                    pass

        threading.Thread(
            target=run, daemon=True,
            name=f"tpuflow-httprep-pf-{self.name}-{shadow.id}").start()
        return shadow

    def offer_chain(self, wire, *, transfer_id: Optional[str] = None,
                    last: bool = True,
                    trace_ctx: Optional[Dict[str, Any]] = None) -> str:
        from tpuflow.serve.pages import wire_to_json

        out = self._post_json("/v1/worker/offer_chain", {
            "transfer_id": transfer_id, "last": bool(last),
            "wire": wire_to_json(wire),
            **({"trace_ctx": dict(trace_ctx)} if trace_ctx else {}),
        })
        return str(out["transfer_id"])

    def fail_transfer(self, transfer_id: str,
                      reason: str = "transfer failed") -> None:
        try:
            self._post_json("/v1/worker/fail_transfer", {
                "transfer_id": str(transfer_id), "reason": str(reason)})
        except Exception:
            pass  # an unreachable worker times the transfer out itself

    # ---- tiered KV / directory pulls (ISSUE 16) ---------------------
    def request_chain(self, tokens, on_ready) -> None:
        """Donor side over HTTP: the blocking fetch rides a background
        thread (the worker answers at its next scheduler boundary), so
        the caller — the router, possibly on another replica's
        scheduler thread — never blocks. ``on_ready(None)`` on any
        transport fault: the puller falls back to local prefill."""
        ids = np.asarray(tokens, np.int32).reshape(-1).tolist()

        def run():
            from tpuflow.serve.pages import wire_from_json

            wire = None
            try:
                out = self._post_json("/v1/worker/fetch_chain",
                                      {"tokens": ids})
                if out.get("wire") is not None:
                    wire = wire_from_json(out["wire"])
            except Exception:
                wire = None
            try:
                on_ready(wire)
            except Exception:
                pass

        threading.Thread(
            target=run, daemon=True,
            name=f"tpuflow-httprep-fetch-{self.name}").start()

    def kv_chain_report(self) -> List[Dict[str, Any]]:
        try:
            return list(self._get_json(
                "/v1/worker/chain_report").get("chains", ()))
        except Exception:
            return []

    # ---- zero-downtime deployment (ISSUE 15) ------------------------
    def swap_from_manifest(self, mpath: str, *,
                           draft: bool = False) -> Dict[str, Any]:
        """Hot-swap the WORKER's weights from a manifest path in the
        shared checkpoint namespace (the same operating assumption the
        sharded format already makes) — the worker validates config
        compatibility itself and a mismatch comes back as the 400 →
        ``ValueError`` (SwapMismatchError) taxonomy, loudly. The
        restore can take a while on big models: ride the long request
        timeout, not the connect timeout."""
        out = self._call("POST", "/v1/worker/swap_weights",
                         {"manifest": str(mpath), "draft": bool(draft)},
                         timeout=max(self.timeout_s, 300.0))
        if not draft:
            self.model_version = out.get("model_version")
        # "swapped" is the version this CALL installed (draft swaps
        # leave model_version untouched) — the same contract as
        # ServeScheduler.swap_from_manifest's return value
        return out.get("swapped") or {}

    def reopen(self) -> None:
        self._post_json("/v1/worker/reopen", {})

    # ---- sensors -----------------------------------------------------
    def load_snapshot(self) -> Dict[str, Any]:
        return self._get_json("/v1/worker/load_snapshot")

    def version_snapshot(self) -> Dict[str, Any]:
        """Per-version metric cuts (ISSUE 20); an unreachable worker
        contributes nothing to the tier aggregate rather than failing
        the canary read."""
        try:
            return self._get_json("/v1/worker/version_snapshot")
        except Exception:
            return {}

    def readiness(self) -> Dict[str, Any]:
        conn, resp = self._open("GET", "/readyz", None,
                                timeout=self.connect_timeout_s)
        try:
            raw = resp.read()
        finally:
            conn.close()
        return json.loads(raw.decode() or "{}")

    def health(self) -> Dict[str, Any]:
        """A worker that stopped answering IS failed — the process
        boundary is the isolation unit (one dead worker fails over
        exactly one replica; the others never see it)."""
        try:
            return self._get_json("/v1/worker/health")
        except Exception as e:
            return {"failed": True, "error": repr(e)}

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self._get_json("/v1/metrics")

    def trace_spans(self, request_id: str):
        """Tier-trace fan-out donor (ISSUE 19): this worker's spans
        (+ event-log instants) for one trace id — the replica-local
        ``/v1/trace/<id>`` body. An unreachable worker contributes
        nothing rather than failing the whole tier view."""
        try:
            return list(self._get_json(
                f"/v1/trace/{request_id}").get("spans", ()))
        except Exception:
            return []

    # ---- shape facts -------------------------------------------------
    def bucket_of(self, prompt_len: int) -> int:
        from tpuflow.packaging.lm import _bucket_len

        return _bucket_len(int(prompt_len))

    def pages_needed(self, prompt_len: int, max_new: int) -> Optional[int]:
        from tpuflow.serve.pages import pages_needed

        if self.page_size is None:
            return None
        return pages_needed(int(prompt_len), int(max_new),
                            self.page_size)

    def retry_after_s(self) -> float:
        try:
            return float(self._get_json(
                "/v1/worker/retry_after")["retry_after_s"])
        except Exception:
            return 1.0

    # ---- lifecycle ---------------------------------------------------
    def start(self) -> None:
        """The worker process runs its own scheduler loop."""

    def prepare(self, *buckets: int) -> None:
        """Worker-side warm-up is the worker's own concern."""

    def drain(self) -> None:
        try:
            self._post_json("/v1/admin/drain", {})
        except Exception:
            pass

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        try:
            self._call("POST", "/v1/worker/stop",
                       {"drain": bool(drain), "timeout": float(timeout)},
                       timeout=timeout + 5.0)
        except Exception:
            pass

    # ---- offline drive ----------------------------------------------
    def step(self) -> bool:
        return False  # remote tiers run online (Router.start())

    def idle(self) -> bool:
        try:
            snap = self.load_snapshot()
        except Exception:
            return True
        return (int(snap.get("queue_depth", 0)) == 0
                and int(snap.get("running", 0)) == 0)


def launch_worker(model: str, *, host: str = "127.0.0.1", port: int = 0,
                  extra_args: Optional[List[str]] = None,
                  startup_timeout_s: float = 180.0):
    """Spawn an out-of-process worker — ``python -m tpuflow.serve
    --model <model> --port 0 ...`` in a fresh process that loads
    weights itself — and return ``(Popen, "host:port")`` once the
    serving banner prints. The caller wraps the address in an
    :class:`HTTPReplica` (and owns the process: terminate it to
    simulate a replica death)."""
    import re
    import subprocess
    import sys

    import select

    cmd = [sys.executable, "-m", "tpuflow.serve", "--model", str(model),
           "--host", host, "--port", str(port)]
    cmd.extend(extra_args or [])
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + startup_timeout_s
    banner = []
    while time.time() < deadline:
        # select-gated read: a worker wedged BEFORE printing anything
        # (device-init deadlock) must still hit the timeout — a bare
        # readline() would block past it forever
        ready, _, _ = select.select([proc.stdout], [], [], 0.25)
        if not ready:
            if proc.poll() is not None:
                raise RuntimeError(
                    "worker exited before serving:\n" + "".join(banner))
            continue
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    "worker exited before serving:\n" + "".join(banner))
            time.sleep(0.05)
            continue
        banner.append(line)
        m = re.search(r"http://([^\s:]+):(\d+)", line)
        if m:
            return proc, f"{m.group(1)}:{m.group(2)}"
    proc.terminate()
    raise RuntimeError(
        f"worker did not serve within {startup_timeout_s}s:\n"
        + "".join(banner))
