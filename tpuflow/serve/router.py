"""Multi-replica serving tier: the load-aware front router (ISSUE 8).

Everything below this module is ONE scheduler on one process; the
router is the layer that opens horizontal scale (ROADMAP item 3): it
owns N replicas (:class:`~tpuflow.serve.replica.Replica` — in-process
``ServeScheduler`` backends today, HTTP backends later) behind the one
submit/stream/cancel surface the HTTP frontend already speaks, and
turns the observability planes into CONTROL inputs:

- **placement** is least-loaded over each replica's
  ``load_snapshot()`` (queue depth + running rows; free KV pages and
  windowed TTFT p95 ride along for dashboards and external LBs) —
  never a Prometheus text parse;
- **prefix affinity**: the prompt's page-size token chunks are hashed
  exactly the way ``serve/pages.py::PrefixCache`` chunks them
  (:func:`tpuflow.serve.pages.chunk_keys`), and the deepest chain the
  router has seen before pulls the request to the replica that already
  holds those KV pages — shared-system-prompt traffic sticks where its
  prefill is already cached, with a load-slack valve so a hot prefix
  cannot starve the tier down to one replica;
- **backpressure / shedding**: per-replica ``QueueFull`` is retried on
  the next-best replica; when EVERY eligible replica rejects (or all
  KV allocators are dry with backlogs, or the optional tier-wide queue
  bound is hit) the router raises its own ``QueueFull`` carrying the
  MIN across-replica Retry-After — the soonest any capacity frees;
- **failover**: a replica that trips the watchdog or closes without
  draining gets its still-QUEUED (never-admitted) requests resubmitted
  elsewhere; the router pins every request's sampling ``stream_id``
  from ONE tier-global per-bucket counter, so outputs — including
  resubmitted ones — are TOKEN-IDENTICAL to the same trace served by a
  single scheduler;
- **graceful drain**: :meth:`Router.drain` stops admissions (503),
  drains every replica (each finishes its admitted backlog — zero
  truncated streams), flips ``/readyz`` and annotates the flight
  recorder's manifest; wired to SIGTERM by ``python -m tpuflow.serve``
  through train/preempt.py's signal channel and to HTTP via
  ``POST /v1/admin/drain``.

The router is PURE HOST POLICY: it never touches device arrays — all
device work stays on the replica schedulers' threads (a grep guard in
tests/test_serve_router.py pins this boundary the way PR 7's jit-site
guard pins the compile registry).

FLEET-SCALE HOT PATH (ISSUE 17): placement cost is flat in tier
width. Submit reads a **cached snapshot plane** (per-replica load
snapshots refreshed synchronously per submit by default, or on the
maintenance cadence with ``snapshot_cache=True`` — a bounded-staleness
view corrected by local deltas at place time) instead of fanning one
RPC per replica per request; candidate order comes from lazy
version-stamped **heaps** keyed exactly like the old full sort
``(queue_depth + running, -kv_pages_free, idx)``; the affinity /
prefill-affinity / tier-directory / hot-head tables are **sharded
LRU maps** (one lock per shard, keyed by the chunk digest's first
byte) so concurrent submits don't convoy; stream-id pinning
serializes on a **per-bucket** counter lock (token identity needs
counter-read→place→commit atomic only per bucket, never globally);
and :meth:`Router.maintain` probes health **concurrently** with a
sweep deadline, so one wedged replica's health RPC cannot stall
failover for the rest of the tier. ``bench.py --serve-fleet`` drives
2→128 virtual-clock replicas through this path and records router
µs/placed-request vs width.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import OrderedDict, deque
from concurrent import futures as _futures
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpuflow.obs import trace as _trace
from tpuflow.serve.pages import chunk_keys
from tpuflow.serve.request import (
    QueueFull,
    Request,
    RequestState,
    SchedulerClosed,
)


class RouterMetrics:
    """Router-tier event log (bounded, same contract as
    :class:`~tpuflow.serve.metrics.ServeMetrics`'s): per-request-id
    placement/shed/failover events, merged with each replica's own
    events on read so ``GET /v1/events/<id>`` tells one story."""

    def __init__(self, max_event_requests: int = 512,
                 max_events_per_request: int = 128):
        self._lock = threading.Lock()
        self._events: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
        self._max_requests = max_event_requests
        self._max_per_request = max_events_per_request
        # read-side merge hooks (the replicas' metrics.events fns)
        self.merge_sources: List[Callable[[str], List[Dict[str, Any]]]] = []

    def event(self, request_id: str, name: str, **detail: Any) -> None:
        rec = {"ts": time.time(), "event": name}
        if detail:
            rec.update(detail)
        with self._lock:
            log = self._events.get(request_id)
            if log is None:
                self._events[request_id] = log = []
                while len(self._events) > self._max_requests:
                    self._events.popitem(last=False)
            log.append(rec)
            if len(log) > self._max_per_request:
                del log[: len(log) - self._max_per_request]

    def events(self, request_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events.get(request_id, []))
        for src in self.merge_sources:
            try:
                out.extend(src(request_id))
            except Exception:
                pass
        out.sort(key=lambda r: r.get("ts", 0.0))
        return out


class RouterRequest:
    """One tier-level request: a stable client handle whose UNDERLYING
    replica request may be swapped by failover. The client surface
    (``wait``/``result``/``summary``/``tokens``/``state``) always
    describes the CURRENT inner request; stream callbacks from a
    superseded inner are dropped, and a replica-shutdown cancellation
    of a never-admitted request is held back from the client until the
    router has had the chance to resubmit it elsewhere."""

    def __init__(self, router: "Router", request_id: str,
                 prompt_ids: np.ndarray, max_new_tokens: int,
                 stream_id: int, bucket: int,
                 deadline_ts: Optional[float],
                 stream_cb: Optional[Callable]):
        self.id = request_id
        self.prompt_ids = prompt_ids
        self.max_new_tokens = int(max_new_tokens)
        self.stream_id = int(stream_id)
        self.bucket = int(bucket)
        self.deadline_ts = deadline_ts
        self.stream_cb = stream_cb
        self.client_cancelled = False
        self.speculate = True  # per-request spec opt-out (ISSUE 9)
        # version pin (ISSUE 15): placement AND failover restricted to
        # replicas serving exactly this model version — the
        # token-identical A/B surface during a rollout
        self.pin_version: Optional[str] = None
        self.resubmits = 0
        self.ts_arrival: Optional[float] = None
        self._router = router
        self._lock = threading.Lock()
        self._gen = 0
        self._inner: Optional[Request] = None
        self._replica_idx: int = -1
        self._done = threading.Event()
        self._orphaned = False  # terminal held back pending failover
        self._error: Optional[str] = None
        # prefill/decode disaggregation (ISSUE 14): a transferred
        # request binds to its decode home IMMEDIATELY (the inner
        # request queues there gated on the transfer id, keeping its
        # FIFO position); _transfer tracks the PREFILL leg — phase
        # 'prefill' (prompt pass in flight on the prefill replica) →
        # 'landing' (claimed by completion/abort) → 'decode' (chunks
        # shipped). Aborts release the inner via fail_transfer.
        self._transfer: Optional[Dict[str, Any]] = None
        # distributed tracing (ISSUE 19): the router's root span
        # (ended at the terminal) and the trace context stamped into
        # every worker RPC for this request. Both None when tracing is
        # off OR the request is head-dropped — the router pays span
        # costs only for sampled requests (the hot-path overhead
        # budget); a tail-kept trace recovers the REPLICA spans, which
        # buffer regardless of the head decision.
        self._tspan = None
        self._tctx: Optional[Dict[str, Any]] = None

    # ---- wiring (router-owned) --------------------------------------
    def _make_cb(self) -> Callable:
        """A stream callback bound to the NEXT generation: events from
        any earlier (superseded) inner request are dropped, and the
        replica-shutdown terminal of a failover-eligible request is
        suppressed until :meth:`Router.maintain` decides its fate."""
        with self._lock:
            self._gen += 1
            gen = self._gen

        def cb(inner: Request, new: List[int], finished: bool) -> None:
            with self._lock:
                if gen != self._gen:
                    return  # stale generation: failover superseded it
                if finished and self._failover_candidate(inner):
                    self._orphaned = True
                    return
            if self.stream_cb is not None and (new or finished):
                self.stream_cb(self, list(new), finished)
            if finished:
                self._done.set()
                self._router._on_request_done(self)

        return cb

    def _failover_candidate(self, inner: Request) -> bool:
        """A terminal that should NOT reach the client (yet): the
        replica cancelled a request the CLIENT never cancelled, before
        it was ever admitted and before any token existed — replica
        shutdown, not a request outcome. Token-identity holds across a
        resubmit because nothing was produced."""
        return (inner.state is RequestState.CANCELLED
                and not self.client_cancelled
                and inner.ts_admitted is None
                and not inner.tokens
                and self._router._accepting_failover())

    def _bind(self, replica_idx: int, inner: Request) -> None:
        with self._lock:
            self._inner = inner
            self._replica_idx = replica_idx
            self._orphaned = False

    def _failover_pending(self) -> bool:
        with self._lock:
            inner = self._inner
            if self._done.is_set() or self.client_cancelled:
                return False
            return self._orphaned or (
                inner is not None
                and inner.state is RequestState.QUEUED)

    def _finalize_failed(self, error: str) -> None:
        """No replica left to serve this request: surface the terminal
        the suppression held back."""
        with self._lock:
            if self._done.is_set():
                return
            self._error = error
        if self.stream_cb is not None:
            try:
                self.stream_cb(self, [], True)
            except Exception:
                pass
        self._done.set()
        self._router._on_request_done(self)

    def _claim_transfer(self, from_phase: str, to_phase: str) -> bool:
        """CAS on the transfer phase: exactly one of a prefill
        completion callback and a maintenance-sweep rescue may move
        the request forward."""
        with self._lock:
            if (self._transfer is None
                    or self._transfer.get("phase") != from_phase):
                return False
            self._transfer["phase"] = to_phase
            return True

    # ---- client surface ---------------------------------------------
    @property
    def inner(self) -> Request:
        with self._lock:
            return self._inner

    @property
    def replica(self) -> int:
        with self._lock:
            return self._replica_idx

    @property
    def state(self) -> RequestState:
        inner = self.inner
        if inner is None:  # mid-transfer: not yet bound anywhere
            return (RequestState.CANCELLED if self._done.is_set()
                    else RequestState.QUEUED)
        return inner.state

    @property
    def tokens(self) -> List[int]:
        inner = self.inner
        return [] if inner is None else inner.tokens

    @property
    def error(self) -> Optional[str]:
        inner = self.inner
        return self._error or (None if inner is None else inner.error)

    def timing(self) -> Dict[str, Optional[float]]:
        inner = self.inner
        if inner is None:
            return {"queue_wait_ms": None, "ttft_ms": None,
                    "decode_ms": None, "e2e_ms": None}
        return inner.timing()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} still {self.state.value} after "
                f"{timeout}s"
            )
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        inner = self.inner
        if inner is None:
            out: Dict[str, Any] = {
                "id": self.id, "state": self.state.value,
                "tokens": [], "n_tokens": 0, "error": self._error,
                "metrics": self.timing(),
            }
        else:
            out = inner.summary()
            out["id"] = self.id
            if self._error:
                out["error"] = out["error"] or self._error
        if self.resubmits:
            out["resubmits"] = self.resubmits
        return out


_POOL_LOCK = threading.Lock()
_POOL = None  # process-shared probe pool (lazily created)


def _probe_pool():
    """The process-shared thread pool behind concurrent snapshot
    refreshes and health probes. Shared across every router in the
    process (a test suite constructs hundreds of tiers — per-router
    pools would pile up idle threads), bounded by core count, and
    never shut down: probe tasks are tiny and the pool drains at
    process exit."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            import os
            from concurrent.futures import ThreadPoolExecutor

            _POOL = ThreadPoolExecutor(
                max_workers=min(16, max(4, os.cpu_count() or 4)),
                thread_name_prefix="tpuflow-router-probe")
        return _POOL


class _ShardedLRU:
    """A bounded LRU map sharded by the first byte of its keys — the
    chunk digests :func:`tpuflow.serve.pages.chunk_keys` produces are
    uniform in every byte, so shard fill is even. One lock per shard:
    concurrent submits walking the affinity/directory/hot tables
    convoy only when they touch the same shard, not on one global
    router lock.

    Matches the plain ``OrderedDict`` tables it replaces: WRITES bump
    recency and evict beyond the per-shard cap; reads never bump.
    ``update`` applies a read-modify-write under the shard lock and
    must return a FRESH value (copy-on-write) when the old one may be
    concurrently read outside the lock."""

    def __init__(self, capacity: int, shards: int = 16):
        n = 1
        while n * 2 <= max(1, int(shards)):
            n *= 2
        self._mask = n - 1
        self._cap = max(1, int(capacity) // n)
        self._maps: List["OrderedDict[bytes, Any]"] = [
            OrderedDict() for _ in range(n)]
        self._locks = [threading.Lock() for _ in range(n)]

    def _shard(self, key) -> int:
        return (key[0] if key else 0) & self._mask

    def get(self, key, default=None):
        j = self._shard(key)
        with self._locks[j]:
            return self._maps[j].get(key, default)

    def put(self, key, value) -> None:
        j = self._shard(key)
        m = self._maps[j]
        with self._locks[j]:
            m[key] = value
            m.move_to_end(key)
            while len(m) > self._cap:
                m.popitem(last=False)

    def update(self, key, fn: Callable[[Any], Any]) -> Any:
        j = self._shard(key)
        m = self._maps[j]
        with self._locks[j]:
            val = fn(m.get(key))
            m[key] = val
            m.move_to_end(key)
            while len(m) > self._cap:
                m.popitem(last=False)
            return val

    def values(self) -> List[Any]:
        out: List[Any] = []
        for j, m in enumerate(self._maps):
            with self._locks[j]:
                out.extend(m.values())
        return out

    def __len__(self) -> int:
        total = 0
        for j, m in enumerate(self._maps):
            with self._locks[j]:
                total += len(m)
        return total


class Router:
    """Front tier over N replicas — one submit/stream/cancel surface
    with load-aware placement, prefix affinity, shedding, failover and
    graceful drain (module docstring has the policy tour). Duck-types
    the scheduler surface :mod:`tpuflow.serve.http` drives, so
    ``start_http_server(router)`` serves the whole tier.

    Drive it online (:meth:`start`: replica loops + a maintenance
    thread that polls health and fails replicas over) or offline
    (:meth:`run_until_idle` steps replicas + maintenance on the
    calling thread — deterministic tests and the virtual-clock
    bench)."""

    def __init__(
        self,
        replicas: Sequence,
        *,
        tokenizer=None,
        affinity: bool = True,
        affinity_slack: int = 4,
        affinity_capacity: int = 65536,
        placement: str = "load",
        max_total_queue: Optional[int] = None,
        shed_on_dry_kv: bool = True,
        clock: Callable[[], float] = time.time,
        name: str = "router",
        transfer_min_tokens: Optional[int] = None,
        transfer_chunk_pages: int = 8,
        standby: Sequence[int] = (),
        tier_directory: bool = False,
        snapshot_cache: bool = False,
        health_timeout_s: float = 5.0,
        affinity_shards: int = 16,
        expert_hot_threshold: float = 0.5,
    ):
        """``placement='load'`` is the real policy (least-loaded with
        prefix affinity when ``affinity``); ``'spray'`` hashes the
        whole prompt to a replica — the locality-blind control the
        bench A/Bs against. ``affinity_slack`` is the load valve: an
        affinity candidate more than this many requests busier than
        the least-loaded replica is passed over (cache locality is
        worth a short wait, not a hot spot). ``max_total_queue``
        (default: the sum of replica ``max_queue``) sheds at the tier
        level before every replica must be tried; ``shed_on_dry_kv``
        429s immediately when every eligible replica's page allocator
        cannot cover the request AND already has a backlog — the
        all-allocators-dry backpressure contract, with Retry-After =
        the min across replicas (the soonest ANY of them frees
        enough).

        PREFILL/DECODE DISAGGREGATION (ISSUE 14): replicas declaring
        ``replica_class='prefill'`` are excluded from decode placement
        and serve prompt passes only; when at least one prefill- and
        one decode-capable replica exist, the tier is DISAGGREGATED
        and placement is two-phase — the decode home is picked by
        prefix affinity + load + page headroom, and a request whose
        estimated uncached suffix is at least ``transfer_min_tokens``
        (default two pages) prefills on the least-loaded prefill
        replica, whose exported KV page chain streams to the decode
        home in ``transfer_chunk_pages``-page chunks (landing between
        that replica's decode segments — transfer overlap) before the
        request admits there as a prefix hit. Every transfer failure
        (prefill rejected, wire CRC, dead replica) falls back to a
        plain local-prefill submit: tokens are identical either way,
        so disaggregation is purely a placement optimization.

        TIER-GLOBAL PREFIX DIRECTORY (ISSUE 16): ``tier_directory``
        lifts the per-replica affinity table into a tier-wide map from
        chunk-key chains to EVERY replica (and tier — resident page
        tree, host pool, disk) holding them: placement writes feed the
        resident entries, and the maintenance sweep merges each
        replica's ``kv_chain_report()`` (its spilled chains). A
        request whose prompt none of its home's caches cover, but
        which SOME live replica holds ≥ ``transfer_min_tokens``
        deeper, triggers a cross-replica PULL riding the exact
        ``offer_chain``/``await_transfer`` machinery above: the holder
        re-exports (or serves from its spill pool) at its next
        boundary and the chain streams to the home in transfer chunks.
        Every pull fault falls back to a local prefill — like the
        disagg transfer, a pull is purely a work-placement
        optimization and tokens are identical either way.

        FLEET-SCALE HOT PATH (ISSUE 17): ``snapshot_cache=False``
        (the default) refreshes the snapshot plane synchronously at
        every submit — the same per-request view the tier always had,
        minus any other RPC fan-out; ``snapshot_cache=True`` lets
        submit read the bounded-staleness plane the maintenance sweep
        refreshes (staleness ≤ the maintain cadence, corrected by
        local place-time deltas) — zero snapshot RPCs on the hot
        path, the fleet-width mode. ``health_timeout_s`` bounds one
        maintenance sweep's wait on concurrent health probes: a probe
        still in flight at the deadline is parked and re-checked next
        sweep (slow is NOT failed) instead of stalling failover for
        the rest of the tier. ``affinity_shards`` (power of two)
        shards the affinity/directory/hot tables' locks.

        EXPERT-AFFINITY (ISSUE 18): MoE replicas publish
        ``moe_hot_expert_frac`` in their load snapshots — the share of
        the last decode segment's expert-routed tokens that landed on
        the single hottest expert. When the load-placement winner's
        fraction is at or above ``expert_hot_threshold`` (its routing
        is collapsing onto one expert, so its host capacity gate is
        close to holding admissions) and prefix affinity did NOT
        already pin the request, the router prefers a cooler replica
        within the same ``affinity_slack`` load window
        (``expert_affinity_hits``); with no cool replica in the
        window it keeps the winner (``expert_affinity_spills``).
        Dense replicas publish no fraction and are always 'cool', so
        the valve is a no-op on non-MoE tiers."""
        if not replicas:
            raise ValueError("router needs at least one replica")
        if placement not in ("load", "spray"):
            raise ValueError(
                f"placement must be 'load' or 'spray', got {placement!r}"
            )
        self.replicas = list(replicas)
        self.clock = clock
        # flight-provider/gauge identity: a process running SEVERAL
        # router tiers (multi-model serving) must name them apart or
        # the last tier's post-mortem section evicts the first's —
        # the ServeMetrics gauge_prefix rule, one layer up
        self.name = str(name)
        self.metrics = RouterMetrics()
        self.metrics.merge_sources = [
            rep.metrics.events for rep in self.replicas
            if getattr(rep, "metrics", None) is not None
        ]
        self._placement = placement
        self.slots = int(getattr(self.replicas[0], "slots", 1))
        self.max_new_cap = int(
            getattr(self.replicas[0], "max_new_cap", 64))
        self.tokenizer = tokenizer
        if tokenizer is None:
            self.tokenizer = getattr(self.replicas[0], "tokenizer", None)
        ps = getattr(self.replicas[0], "page_size", None)
        self.affinity_ps: Optional[int] = (
            int(ps) if (affinity and ps) else None)
        self.affinity_slack = int(affinity_slack)
        self._affinity_cap = int(affinity_capacity)
        self._affinity_shards = int(affinity_shards)
        # sharded state maps (ISSUE 17): chunk-key → holder, one lock
        # per shard so concurrent submits don't convoy on the router
        self._affinity = _ShardedLRU(self._affinity_cap,
                                     self._affinity_shards)
        # replica classes (ISSUE 14): prefill-class replicas never
        # decode; the tier is DISAGGREGATED when both phases exist
        self.classes: List[str] = [
            str(getattr(rep, "replica_class", "mixed") or "mixed")
            for rep in self.replicas]
        self._prefill_set = {i for i, c in enumerate(self.classes)
                             if c == "prefill"}
        self._decode_set = [i for i, c in enumerate(self.classes)
                            if c != "prefill"]
        if not self._decode_set:
            raise ValueError(
                "router needs at least one decode-capable replica "
                "(every replica is prefill-class)")
        # zero-downtime deployment (ISSUE 15): STANDBY replicas are
        # registered (health-polled, swappable) but excluded from
        # placement until a rollout activates them; RETIRING replicas
        # are draining out of an old version (their backlog finishes,
        # no new placements — the blue/green shift)
        self._standby = {int(i) for i in standby}
        bad = [i for i in self._standby
               if not 0 <= i < len(self.replicas)]
        if bad:
            raise ValueError(f"standby indices out of range: {bad}")
        self._retiring: set = set()
        if not [i for i in self._decode_set if i not in self._standby]:
            raise ValueError(
                "router needs at least one ACTIVE decode-capable "
                "replica (every decode replica is standby)")
        # hottest chain heads (bounded): the rollout's prefix-warmth
        # replay source — deepest chunk-chain key → hit count + the
        # covering token prefix (a version bump invalidates cached KV,
        # so warmth is REBUILT by re-prefilling these, not transferred)
        self._hot_cap = 512
        self._hot = _ShardedLRU(self._hot_cap, self._affinity_shards)
        # rollout hook: DeploymentManager.tick rides the maintenance
        # cadence through here (online tiers)
        self.on_maintain: List[Callable[[], Any]] = []
        self.disaggregated = bool(self._prefill_set)
        if transfer_min_tokens is None:
            transfer_min_tokens = 2 * int(ps) if ps else 1 << 30
        self.transfer_min_tokens = int(transfer_min_tokens)
        self.transfer_chunk_pages = max(1, int(transfer_chunk_pages))
        # prefill-side affinity: repeated prefixes prefill where their
        # pages already sit in the PREFILL replica's own tree
        self._pf_affinity = _ShardedLRU(self._affinity_cap,
                                        self._affinity_shards)
        # tier-global prefix directory (ISSUE 16): chunk key →
        # {replica idx: tier} over every holder, resident AND spilled
        # (LRU-capped like the affinity table; staleness is safe — a
        # pull miss fail_transfers into a local prefill)
        self.tier_directory = bool(tier_directory)
        self._directory = _ShardedLRU(self._affinity_cap,
                                      self._affinity_shards)
        self.shed_on_dry_kv = bool(shed_on_dry_kv)
        self._snapshot_cache = bool(snapshot_cache)
        self.health_timeout_s = float(health_timeout_s)
        self._lock = threading.Lock()
        # per-bucket stream-counter locks (ISSUE 17): counter-read →
        # place → counter-commit is ONE critical section, but only PER
        # BUCKET — the tier-global pinning counter is per bucket, so
        # two racers in DIFFERENT buckets can never share a stream id
        # and need not serialize. _place_lock guards only the lazy
        # lock-table itself. Bucket locks are never taken from replica
        # callbacks → no inversion against _lock / RouterRequest._lock.
        self._place_lock = threading.Lock()
        self._bucket_locks: Dict[int, threading.Lock] = {}
        self._inflight: Dict[str, RouterRequest] = {}
        self._admit_counts: Dict[int, int] = {}  # tier-global stream ids
        self._failed: Dict[int, str] = {}
        self._seq = 0
        self._draining = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # counters (mirrored onto the obs registry as router.*)
        self.counts: Dict[str, int] = {
            "placed": 0, "affinity_hits": 0, "affinity_spills": 0,
            "expert_affinity_hits": 0, "expert_affinity_spills": 0,
            "shed": 0, "shed_kv": 0, "rejected": 0, "failovers": 0,
            "replicas_failed": 0, "drains": 0,
            "transfers": 0, "transfer_fallbacks": 0,
            "pulls": 0, "pull_fallbacks": 0,
            "snapshot_refreshes": 0, "snapshot_errors": 0,
            "health_lagged": 0, "retry_probe_errors": 0,
        }
        self.placements: Dict[str, int] = {
            rep.name: 0 for rep in self.replicas}
        # ---- cached snapshot plane (ISSUE 17) -----------------------
        # one load snapshot per replica, plus index arrays + lazy
        # version-stamped heaps derived from it under _idx_lock; the
        # plane is refreshed per submit (sync mode) or per maintain
        # sweep (cached mode) and corrected by _note_placed deltas
        n_rep = len(self.replicas)
        self._idx_lock = threading.Lock()
        self._snaps: List[Dict[str, Any]] = [{} for _ in range(n_rep)]
        self._snap_ts: List[float] = [0.0] * n_rep
        self._score: List[int] = [0] * n_rep
        self._qd: List[int] = [0] * n_rep
        self._free: List[Optional[int]] = [None] * n_rep
        self._closed_snap: List[bool] = [False] * n_rep
        self._ver_label: List[Optional[str]] = [None] * n_rep
        self._in_heap: List[bool] = [False] * n_rep
        self._entry_ver: List[int] = [0] * n_rep
        # expert-affinity plane (ISSUE 18): hottest-expert token
        # fraction per replica, 0.0 for dense replicas (always cool)
        self._moe_hot: List[float] = [0.0] * n_rep
        self.expert_hot_threshold = float(expert_hot_threshold)
        # cross-process clock alignment (ISSUE 19): per-replica wall
        # offset (replica clock MINUS router clock) estimated from the
        # RTT midpoint of any probe whose reply carries a ``wall_s``
        # anchor (load_snapshot / health). |error| <= rtt/2, so the
        # sample behind the current estimate keeps its RTT
        # (_wall_rtt) as the quality bound and a one-off stalled
        # probe cannot displace a tighter estimate (see _note_wall).
        self._wall_off: List[float] = [0.0] * n_rep
        self._wall_rtt: List[float] = [float("inf")] * n_rep
        self._wall_ts: List[float] = [0.0] * n_rep
        # recently traced (head-sampled) request ids — the flight
        # recorder's tier-trace bundle reads these (bounded)
        self._recent_traced: "deque[str]" = deque(maxlen=8)
        self._heap: List[Tuple[int, int, int, int]] = []
        self._free_heap: List[Tuple[int, int, int]] = []
        self._agg_depth = 0
        self._n_depth0 = 0
        self._n_eligible = 0
        self._all_paged = False
        self._health_pending: Dict[int, Any] = {}
        self._plane_warm = False
        from tpuflow.serve.metrics import register_router_metrics

        register_router_metrics()
        self._refresh_plane(range(n_rep))
        if max_total_queue is None:
            mq = [self._snaps[i].get("max_queue")
                  for i in range(n_rep)]
            mq = [int(m) for m in mq if m]
            max_total_queue = sum(mq) if mq else None
        self.max_total_queue = max_total_queue
        # post-mortem: the flight recorder snapshots the tier state
        # (weakly bound, like the scheduler's request provider)
        import weakref

        from tpuflow.obs import flight as _flight

        ref = weakref.ref(self)

        def _provider():
            r = ref()
            return r.flight_snapshot() if r is not None else None

        _flight.add_provider(self.name, _provider)

    # ---- small helpers ----------------------------------------------
    def _safe_snapshot(self, idx: int) -> Dict[str, Any]:
        t0 = time.time()
        try:
            snap = self.replicas[idx].load_snapshot()
        except Exception:
            self._count("snapshot_errors")
            return {"queue_depth": 0, "running": 0, "closed": True}
        self._note_wall(idx, t0, time.time(), snap.get("wall_s"))
        return snap

    def _note_wall(self, idx: int, t0: float, t1: float,
                   wall_s: Any) -> None:
        """Fold one probe's wall anchor into the per-replica clock-
        offset estimate (ISSUE 19): the reply's ``wall_s`` was stamped
        somewhere inside [t0, t1] on the router's clock, so the RTT
        midpoint bounds the offset error by rtt/2. Best-RTT-wins with
        aging: a sample looser than 2x the current bound is noise
        unless the estimate has gone stale (120s)."""
        if wall_s is None:
            return
        try:
            wall_s = float(wall_s)
        except (TypeError, ValueError):
            return
        rtt = max(0.0, t1 - t0)
        now = time.monotonic()
        with self._idx_lock:
            if (rtt <= self._wall_rtt[idx] * 2.0
                    or now - self._wall_ts[idx] > 120.0):
                self._wall_off[idx] = wall_s - (t0 + t1) / 2.0
                self._wall_rtt[idx] = rtt
                self._wall_ts[idx] = now

    def _count(self, key: str, by: int = 1) -> None:
        from tpuflow.obs.gauges import inc_counter

        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + by
        inc_counter(f"router.{key}_total", by)

    def _directory_put(self, keys: Sequence[bytes], idx: int,
                       tier: str) -> None:
        # LRU-capped alongside the affinity table (same capacity —
        # one knob). Copy-on-write merge: readers hold entry dicts
        # outside the shard lock, so a writer must never mutate one
        # in place.
        for k in keys:
            def _merge(ent):
                ent = dict(ent) if ent else {}
                ent[idx] = tier
                return ent

            self._directory.update(k, _merge)

    # ---- cached snapshot plane (ISSUE 17) ---------------------------
    def _refresh_plane(self, indices, concurrent: bool = False) -> None:
        """Fetch fresh load snapshots for ``indices`` and rebuild the
        index arrays/heaps. Sync mode calls this per submit (the old
        per-request view, one code path); cached mode calls it from
        :meth:`maintain` — submit then reads local state only."""
        indices = [i for i in indices if 0 <= i < len(self.replicas)]
        if concurrent and len(indices) > 1:
            pool = _probe_pool()
            futs = [(i, pool.submit(self._safe_snapshot, i))
                    for i in indices]
            fetched = [(i, f.result()) for i, f in futs]
        else:
            fetched = [(i, self._safe_snapshot(i)) for i in indices]
        now = time.monotonic()
        for i, snap in fetched:
            self._snaps[i] = snap
            self._snap_ts[i] = now
        self._plane_warm = all(t > 0.0 for t in self._snap_ts)
        self._count("snapshot_refreshes")
        self._rebuild_index()

    def _ensure_plane(self, live: List[int]) -> None:
        """Cached mode: fetch only never-seen replicas (none, after
        __init__'s full refresh) — submit pays zero snapshot RPCs.
        O(1) once the plane is warm: the missing-scan only runs while
        some replica has never been snapshotted."""
        if self._plane_warm:
            return
        missing = [i for i in live if self._snap_ts[i] == 0.0]
        if missing:
            self._refresh_plane(missing, concurrent=len(missing) >= 8)

    def _rebuild_index(self) -> None:
        """Recompute the index arrays, aggregates, and heaps from the
        current snapshot plane — O(N), paid once per plane refresh or
        eligibility transition, never per candidate. Bumps every
        entry version so stale heap entries die lazily."""
        with self._lock:
            failed = set(self._failed)
            standby = set(self._standby)
        n = len(self.replicas)
        heap: List[Tuple[int, int, int, int]] = []
        free_heap: List[Tuple[int, int, int]] = []
        agg_depth = n_depth0 = n_eligible = 0
        all_paged = True
        # the live list is cached here (every failure-set transition
        # rebuilds the index) so submit never pays an O(N) scan for it
        self._live_cache = [i for i in range(n) if i not in failed]
        with self._idx_lock:
            for i in range(n):
                snap = self._snaps[i]
                qd = int(snap.get("queue_depth", 0) or 0)
                running = int(snap.get("running", 0) or 0)
                free = snap.get("kv_pages_free")
                free = None if free is None else int(free)
                closed = bool(snap.get("closed"))
                self._qd[i] = qd
                self._score[i] = qd + running
                self._free[i] = free
                self._closed_snap[i] = closed
                self._ver_label[i] = self._snap_version(snap)
                self._moe_hot[i] = float(
                    snap.get("moe_hot_expert_frac") or 0.0)
                self._entry_ver[i] += 1
                elig = (i not in failed and not closed
                        and i not in self._prefill_set
                        and i not in standby)
                self._in_heap[i] = elig
                if elig:
                    n_eligible += 1
                    agg_depth += qd
                    if qd == 0:
                        n_depth0 += 1
                    if free is None:
                        all_paged = False
                    heap.append((self._score[i], -(free or 0), i,
                                 self._entry_ver[i]))
                    free_heap.append((-(free or 0), i,
                                      self._entry_ver[i]))
            heapq.heapify(heap)
            heapq.heapify(free_heap)
            self._heap = heap
            self._free_heap = free_heap
            self._agg_depth = agg_depth
            self._n_depth0 = n_depth0
            self._n_eligible = n_eligible
            self._all_paged = all_paged and n_eligible > 0

    def _note_placed(self, idx: int, pages: int = 0) -> None:
        """Local delta correction after a successful placement: the
        cached plane learns +1 depth / -pages headroom immediately, so
        cached-mode submits spread between refreshes exactly the way
        sync-mode refetches would show."""
        with self._idx_lock:
            in_heap = self._in_heap[idx]
            if in_heap and self._qd[idx] == 0:
                self._n_depth0 -= 1
            self._qd[idx] += 1
            self._score[idx] += 1
            if in_heap:
                self._agg_depth += 1
            if self._free[idx] is not None and pages:
                self._free[idx] = max(0, self._free[idx] - int(pages))
            self._entry_ver[idx] += 1
            if in_heap:
                heapq.heappush(
                    self._heap,
                    (self._score[idx], -(self._free[idx] or 0), idx,
                     self._entry_ver[idx]))
                heapq.heappush(
                    self._free_heap,
                    (-(self._free[idx] or 0), idx,
                     self._entry_ver[idx]))

    def _pop_candidate_locked(self, restore: List[tuple]) -> Optional[int]:
        # caller holds _idx_lock; valid pops land in ``restore`` so an
        # unplaced candidate's entry goes back on the heap afterwards
        while self._heap:
            ent = heapq.heappop(self._heap)
            score, negfree, i, ver = ent
            if ver != self._entry_ver[i] or not self._in_heap[i]:
                continue  # stale entry: a fresh one exists (or i left)
            restore.append(ent)
            return i
        return None

    def _peek_max_free_locked(self) -> Optional[Tuple[int, int]]:
        # caller holds _idx_lock
        while self._free_heap:
            negfree, i, ver = self._free_heap[0]
            if ver != self._entry_ver[i] or not self._in_heap[i]:
                heapq.heappop(self._free_heap)
                continue
            return i, -negfree
        return None

    def _eligible_indices(self) -> List[int]:
        with self._idx_lock:
            return [i for i in range(len(self.replicas))
                    if self._in_heap[i]]

    def _eligible_order(self) -> List[int]:
        # the old full-sort order, off the cached arrays — the shed /
        # contention fallback, never the steady-state hot path
        with self._idx_lock:
            return sorted(
                (i for i in range(len(self.replicas))
                 if self._in_heap[i]),
                key=lambda i: (self._score[i], -(self._free[i] or 0),
                               i))

    def _staleness_s(self, live: Optional[List[int]] = None) -> float:
        if live is None:
            live = self._live_indices()
        now = time.monotonic()
        return max((now - self._snap_ts[i] for i in live
                    if self._snap_ts[i] > 0.0), default=0.0)

    def _bucket_lock(self, bucket: int) -> threading.Lock:
        with self._place_lock:
            lk = self._bucket_locks.get(bucket)
            if lk is None:
                lk = self._bucket_locks[bucket] = threading.Lock()
            return lk

    def _live_indices(self) -> List[int]:
        # O(1): every failure-set transition goes through
        # _rebuild_index, which REPLACES this list (never mutates it)
        # — so handing out the current one is a safe snapshot
        return self._live_cache

    def _accepting_failover(self) -> bool:
        with self._lock:
            return not (self._closed or self._draining)

    def _encode(self, prompt) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts need a tokenizer; submit token ids "
                    "or construct the router with one"
                )
            return np.asarray(self.tokenizer.encode(prompt), np.int32)
        return np.asarray(prompt, np.int32).reshape(-1)

    # ---- admission (any thread) -------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        *,
        deadline_s: Optional[float] = None,
        stream_cb: Optional[Callable] = None,
        request_id: Optional[str] = None,
        speculate: bool = True,
        pin_version: Optional[str] = None,
    ) -> RouterRequest:
        """Place one request on the best replica (module docstring has
        the policy). Raises the scheduler taxonomy: ``QueueFull``
        (tier saturated / all allocators dry — Retry-After is the min
        across replicas), :class:`SchedulerClosed` (draining/stopped),
        ``ValueError`` (never servable). ``speculate=False`` pins the
        request to plain decode on speculating replicas (ISSUE 9) and
        survives failover resubmission — tokens identical either
        way. ``pin_version`` (ISSUE 15) restricts placement — and any
        later failover — to replicas whose ``model_version`` label
        matches exactly: with the tier-global stream-id pinning this
        makes a version A/B during a rollout token-identical per
        version; a version nothing live serves raises
        :class:`SchedulerClosed` (503 — go elsewhere, the version is
        gone or not yet rolled). Every call — placed, shed, or
        rejected — lands in the ``router.place_ms`` histogram."""
        from tpuflow.obs.gauges import observe

        t0 = time.perf_counter()
        try:
            return self._submit(
                prompt, max_new_tokens, deadline_s=deadline_s,
                stream_cb=stream_cb, request_id=request_id,
                speculate=speculate, pin_version=pin_version)
        finally:
            observe("router.place_ms",
                    (time.perf_counter() - t0) * 1e3)

    def _submit(self, prompt, max_new_tokens, *, deadline_s, stream_cb,
                request_id, speculate, pin_version) -> RouterRequest:
        ids = self._encode(prompt)
        if max_new_tokens is None:
            max_new_tokens = self.max_new_cap
        with self._lock:
            if self._closed or self._draining:
                raise SchedulerClosed(
                    "router is stopped"
                    + (" (draining)" if self._draining else "")
                )
        live = self._live_indices()
        if not live:
            raise SchedulerClosed("router has no live replicas")
        # snapshot plane: sync mode pays the per-submit refresh (the
        # tier's historical freshness contract); cached mode reads
        # the view maintain() keeps within its poll cadence
        if self._snapshot_cache:
            self._ensure_plane(live)
        else:
            self._refresh_plane(live)
        with self._lock:
            standby = set(self._standby)
        # version-pinned and spray placements replicate the full-sort
        # ordering off the cached arrays (still zero per-request
        # RPCs); everything else — the fleet hot path — goes through
        # the heaps
        if pin_version is not None or self._placement == "spray":
            return self._submit_ordered(
                ids, int(max_new_tokens), live, standby, deadline_s,
                stream_cb, request_id, speculate, pin_version)
        return self._submit_heap(
            ids, int(max_new_tokens), live, standby, deadline_s,
            stream_cb, request_id, speculate)

    def _min_retry(self, pool: Sequence[int]) -> float:
        """Min Retry-After across ``pool`` — read from the cached
        snapshot plane's ``retry_after_s`` hint when the replica's
        load snapshot carries one (zero RPCs on an overloaded tier),
        with the per-replica RPC as the fallback for backends that
        don't; probe failures are COUNTED and logged, never silently
        swallowed."""
        vals = []
        for i in pool:
            hint = self._snaps[i].get("retry_after_s")
            if hint is not None:
                try:
                    vals.append(float(hint))
                    continue
                except (TypeError, ValueError):
                    pass
            try:
                vals.append(float(self.replicas[i].retry_after_s()))
            except Exception as e:
                self._count("retry_probe_errors")
                self.metrics.event(
                    "-shed-", "retry_probe_error",
                    replica=self.replicas[i].name, error=repr(e))
        return min(vals) if vals else 1.0

    def _shed(self, kind: str, depth: int,
              pool: Sequence[int]) -> None:
        retry = self._min_retry(pool)
        self._count("shed")
        if kind == "kv":
            self._count("shed_kv")
        self.metrics.event("-shed-", "shed", kind=kind,
                           depth=depth, retry_after_s=retry)
        raise QueueFull(depth, retry)

    def _kv_dry(self, rows: List[Tuple[int, Optional[int], int]],
                n_prompt: int, max_new: int) -> bool:
        # the original per-replica dry test, over cached rows: shed
        # only when EVERY eligible replica is paged, short of its OWN
        # pages_needed (page sizes may differ), and backlogged
        if not rows:
            return False
        for i, free, qd in rows:
            if free is None:
                return False  # not a paged tier: pages never the gate
            need = self.replicas[i].pages_needed(n_prompt, max_new)
            if not (free < (need or 0) and qd > 0):
                return False
        return True

    def _affinity_walk(
            self, ids: np.ndarray) -> Tuple[List[bytes], Optional[int]]:
        """Deepest-known-chain affinity target for this prompt, plus
        its chunk keys; also does the hot-head accounting (ISSUE 15):
        the deepest chain this prompt exercises, with its covering
        token prefix — what a rollout replays onto a freshly swapped
        replica to rebuild prefix warmth."""
        if self.affinity_ps is None or ids.size <= 1:
            return [], None
        keys = chunk_keys(ids[: ids.size - 1], self.affinity_ps)
        tgt = None
        for j in range(len(keys) - 1, -1, -1):
            tgt = self._affinity.get(keys[j])
            if tgt is not None:
                break
        if keys:
            head = keys[-1]
            prefix = np.asarray(ids[: len(keys) * self.affinity_ps],
                                np.int32)

            def _bump(rec):
                if rec is None:
                    rec = {"count": 0, "tokens": prefix}
                rec["count"] += 1
                return rec

            self._hot.update(head, _bump)
        return keys, tgt

    def _submit_ordered(self, ids, max_new_tokens, live, standby,
                        deadline_s, stream_cb, request_id, speculate,
                        pin_version) -> RouterRequest:
        """Version-pinned / spray placement: the original full-sort
        ordering, replayed over the cached plane arrays — same
        eligibility, same (load, -headroom, idx) key, same spray
        rotation; the only change is WHERE the load view comes from
        (the snapshot plane, not N per-request RPCs)."""
        with self._idx_lock:
            eligible = [i for i in range(len(self.replicas))
                        if self._in_heap[i]]
            scores = {i: self._score[i] for i in eligible}
            frees = {i: self._free[i] for i in eligible}
            qds = {i: self._qd[i] for i in eligible}
            vers = {i: self._ver_label[i] for i in eligible}
        if not eligible:
            raise SchedulerClosed(
                "every decode-capable replica is draining or closed")
        if pin_version is not None:
            eligible = [i for i in eligible if vers[i] == pin_version]
            if not eligible:
                raise SchedulerClosed(
                    f"model version {pin_version!r} is not served by "
                    f"any live replica")
        depth = sum(qds[i] for i in eligible)
        if (self.max_total_queue is not None
                and depth >= self.max_total_queue):
            self._shed("queue", depth, eligible)
        if self.shed_on_dry_kv:
            rows = [(i, frees[i], qds[i]) for i in eligible]
            if self._kv_dry(rows, int(ids.size), int(max_new_tokens)):
                self._shed("kv", depth, eligible)

        # ---- ordering: least-loaded (pinned) or spray ---------------
        # decode placement tie-break on PAGE HEADROOM (ISSUE 14): at
        # equal load, the replica with the most free pages hosts the
        # decode — that is the resource a decode-class replica sells
        order = sorted(
            eligible,
            key=lambda i: (scores[i], -(frees[i] or 0), i))
        affinity_used = False
        keys: List[bytes] = []
        if self._placement == "spray":
            import zlib

            j = zlib.crc32(ids.tobytes()) % len(order)
            order = sorted(eligible)[j:] + sorted(eligible)[:j]
        else:
            keys, tgt = self._affinity_walk(ids)
            if tgt is not None and tgt in eligible:
                if scores[tgt] <= scores[order[0]] + self.affinity_slack:
                    order.remove(tgt)
                    order.insert(0, tgt)
                    affinity_used = True
                else:
                    self._count("affinity_spills")
        # ---- expert-affinity valve (ISSUE 18) -----------------------
        # prefix affinity outranks expert cooling (cache locality is
        # deterministic; expert heat is one segment old), so the valve
        # only moves requests prefix affinity did not pin: if the
        # load winner's hottest-expert fraction says its MoE routing
        # has collapsed, prefer the best COOL replica inside the same
        # slack window rather than feeding the hot spot.
        if (not affinity_used and self._placement != "spray"
                and self._moe_hot[order[0]]
                >= self.expert_hot_threshold):
            hot = list(self._moe_hot)
            cool = [i for i in order[1:]
                    if hot[i] < self.expert_hot_threshold
                    and scores[i] <= scores[order[0]]
                    + self.affinity_slack]
            if cool:
                order.remove(cool[0])
                order.insert(0, cool[0])
                self._count("expert_affinity_hits")
            else:
                self._count("expert_affinity_spills")
        decisions = self._phase_decisions(ids, keys, order[0], live,
                                          standby)
        return self._place(
            ids, max_new_tokens, deadline_s, stream_cb, request_id,
            speculate, pin_version, first=order[0],
            candidates=iter(order), keys=keys,
            affinity_used=affinity_used, depth=depth,
            retry_pool=lambda: eligible, decisions=decisions)

    def _phase_decisions(self, ids, keys, home, live, standby):
        """The two second-phase placement decisions for a request
        whose decode HOME is ``home``, off the cached plane arrays.

        TWO-PHASE PLACEMENT (ISSUE 14): whether the PROMPT PASS runs
        on a prefill-class replica (when the tier is disaggregated
        and the home's estimated uncached suffix is long enough to be
        worth shipping pages) — the chain then follows the request to
        its decode home over the wire. Version fence (ISSUE 15): a
        chain exported by a replica on a DIFFERENT model version is
        garbage for the decode home — mid-rollout, transfers only
        cross same-version pairs; everything else local-prefills
        (tokens identical).

        TIER-GLOBAL DIRECTORY PULL (ISSUE 16): when the DIRECTORY
        knows a different live replica holds the prefix
        ≥ transfer_min_tokens deeper than anything the home has
        (resident or spilled), the chain is PULLED from that holder
        over offer_chain instead of recomputed."""
        do_transfer = False
        pf_live: List[int] = []
        if self.disaggregated and self._placement != "spray":
            home_v = self._ver_label[home]
            pf_live = [i for i in live if i in self._prefill_set
                       and not self._closed_snap[i]
                       and i not in standby
                       and self._ver_label[i] == home_v]
            if pf_live:
                cached_tokens = 0
                if keys:
                    for j, k in enumerate(keys):
                        if self._affinity.get(k) != home:
                            break
                        cached_tokens = (j + 1) * self.affinity_ps
                uncached = int(ids.size) - cached_tokens
                do_transfer = uncached >= self.transfer_min_tokens
        do_pull = False
        pull_src: Optional[int] = None
        pull_tokens: Optional[np.ndarray] = None
        if (self.tier_directory and not do_transfer
                and self._placement != "spray" and keys):
            home_v = self._ver_label[home]
            live_set = set(live)
            cached_tokens = 0
            for j, k in enumerate(keys):
                ent = self._directory.get(k)
                if not (self._affinity.get(k) == home
                        or (ent is not None and home in ent)):
                    break
                cached_tokens = (j + 1) * self.affinity_ps
            for j in range(len(keys) - 1, -1, -1):
                covered = (j + 1) * self.affinity_ps
                if (covered - cached_tokens
                        < self.transfer_min_tokens):
                    break  # shallower coverage only shrinks it
                ent = self._directory.get(keys[j])
                if not ent:
                    continue
                # holders must be live, open, same model version
                # (a chain under other weights is garbage — the
                # ISSUE 15 version fence); standby holders DO
                # donate (alive, just taking no placements)
                hold = [i for i in sorted(ent)
                        if i != home and i in live_set
                        and not self._closed_snap[i]
                        and self._ver_label[i] == home_v]
                if hold:
                    do_pull = True
                    pull_src = hold[0]
                    pull_tokens = ids[:covered]
                    break
        return do_transfer, pf_live, do_pull, pull_src, pull_tokens

    def _submit_heap(self, ids, max_new_tokens, live, standby,
                     deadline_s, stream_cb, request_id,
                     speculate) -> RouterRequest:
        """The fleet hot path: O(1) sheds off the plane aggregates,
        O(log N) candidate order off the lazy version-stamped heap
        (same (load, -headroom, idx) key the full sort used), the
        affinity valve applied against the heap's best. Entries
        popped for candidates that did NOT take the request go back
        on the heap; the placed replica's entry is superseded by
        :meth:`_note_placed`'s fresh one."""
        with self._idx_lock:
            n_eligible = self._n_eligible
            depth = self._agg_depth
        if n_eligible == 0:
            raise SchedulerClosed(
                "every decode-capable replica is draining or closed")
        if (self.max_total_queue is not None
                and depth >= self.max_total_queue):
            self._shed("queue", depth, self._eligible_indices())
        if self.shed_on_dry_kv:
            self._kv_shed_fast(ids, max_new_tokens, depth)
        keys, tgt = self._affinity_walk(ids)
        restore: List[tuple] = []
        rr: Optional[RouterRequest] = None
        try:
            with self._idx_lock:
                best = self._pop_candidate_locked(restore)
                best_score = self._score[best] if best is not None else 0
            if best is None:
                # contention fallback: every current heap entry is
                # checked out by a racing submit — fall back to the
                # array sort (never the sequential steady state)
                order0 = self._eligible_order()
                if not order0:
                    raise SchedulerClosed(
                        "every decode-capable replica is draining or "
                        "closed")
                best = order0[0]
                best_score = self._score[best]
            first = best
            affinity_used = False
            if (tgt is not None and 0 <= tgt < len(self.replicas)
                    and self._in_heap[tgt]):
                if (tgt == best
                        or self._score[tgt]
                        <= best_score + self.affinity_slack):
                    first = tgt
                    affinity_used = True
                else:
                    self._count("affinity_spills")
            # expert-affinity valve (ISSUE 18), heap flavor: only
            # when prefix affinity did not pin and the pick is
            # expert-hot does the O(N) cool scan run — the cold
            # branch costs one float compare. Candidates are chosen
            # under _idx_lock; _count (takes _lock) runs after it is
            # released. Redirecting ``first`` composes with
            # _heap_candidates, which yields first, then best, then
            # the remaining pops.
            if (not affinity_used
                    and self._moe_hot[first]
                    >= self.expert_hot_threshold):
                with self._idx_lock:
                    cool = [i for i in range(len(self.replicas))
                            if i != first and self._in_heap[i]
                            and self._moe_hot[i]
                            < self.expert_hot_threshold
                            and self._score[i]
                            <= best_score + self.affinity_slack]
                    pick = min(
                        cool,
                        key=lambda i: (self._score[i],
                                       -(self._free[i] or 0), i),
                    ) if cool else None
                if pick is not None:
                    first = pick
                    self._count("expert_affinity_hits")
                else:
                    self._count("expert_affinity_spills")
            decisions = self._phase_decisions(ids, keys, first, live,
                                              standby)
            rr = self._place(
                ids, max_new_tokens, deadline_s, stream_cb, request_id,
                speculate, None, first=first,
                candidates=self._heap_candidates(first, best, restore),
                keys=keys, affinity_used=affinity_used, depth=depth,
                retry_pool=self._eligible_indices,
                decisions=decisions)
            return rr
        finally:
            placed = rr._replica_idx if rr is not None else -1
            with self._idx_lock:
                for ent in restore:
                    score, negfree, i, ver = ent
                    if (i != placed and ver == self._entry_ver[i]
                            and self._in_heap[i]):
                        heapq.heappush(self._heap, ent)

    def _kv_shed_fast(self, ids, max_new_tokens, depth) -> None:
        """O(1) gates for the all-allocators-dry shed: a tier that is
        not fully paged, or has ANY idle eligible replica, or whose
        max-headroom replica covers its own pages_needed, cannot be
        all-dry — only when every gate fails does the exact (cached,
        RPC-free) per-replica scan run, preserving the original
        mixed-page-size dry semantics before a 429."""
        with self._idx_lock:
            if not self._all_paged or self._n_depth0 > 0:
                return
            top = self._peek_max_free_locked()
        if top is not None:
            i, free = top
            need = self.replicas[i].pages_needed(
                int(ids.size), int(max_new_tokens))
            if free >= (need or 0):
                return
        with self._idx_lock:
            rows = [(i, self._free[i], self._qd[i])
                    for i in range(len(self.replicas))
                    if self._in_heap[i]]
        if self._kv_dry(rows, int(ids.size), int(max_new_tokens)):
            self._shed("kv", depth, [i for i, _, _ in rows])

    def _heap_candidates(self, first: int, best: int,
                         restore: List[tuple]):
        """Candidate order for the heap path: the affinity pick (when
        promoted), the heap best, then lazy pops in exact sort order;
        if racing submits have the remaining entries checked out, the
        array sort finishes the walk so a rejection cascade still
        tries every eligible replica."""
        tried = {first}
        yield first
        if best != first:
            tried.add(best)
            yield best
        while True:
            with self._idx_lock:
                i = self._pop_candidate_locked(restore)
            if i is None:
                break
            if i in tried:
                continue
            tried.add(i)
            yield i
        for i in self._eligible_order():
            if i not in tried:
                tried.add(i)
                yield i

    def _place(self, ids, max_new_tokens, deadline_s, stream_cb,
               request_id, speculate, pin_version, *, first, candidates,
               keys, affinity_used, depth, retry_pool,
               decisions) -> RouterRequest:
        """Shared placement tail: stream-id pinning, the try-each-
        candidate loop, commit, events, and the transfer/pull
        kickoffs. ``retry_pool`` is a thunk — the Retry-After pool is
        only materialized when a shed/rejection actually needs it."""
        do_transfer, pf_live, do_pull, pull_src, pull_tokens = decisions
        bucket = self.replicas[first].bucket_of(int(ids.size))
        with self._lock:
            self._seq += 1
            rid = request_id or f"rt-{self._seq}"
        last_qf: Optional[QueueFull] = None
        saw_closed = False
        placed: Optional[int] = None
        placed_score = 0
        # counter-read → place → counter-commit is ONE critical
        # section, PER BUCKET (ISSUE 17): the tier-global per-bucket
        # stream pinning hands this submission EXACTLY the id a single
        # scheduler with the same slot count would — concurrent
        # submits IN THE SAME BUCKET must serialize here or two racers
        # share an id (same sampling stream) and every later id
        # desyncs from the parity sequence; different buckets advance
        # independent counters and proceed in parallel. The counter
        # advances only on successful placement, like the single
        # scheduler's.
        with self._bucket_lock(bucket):
            with self._lock:
                n = self._admit_counts.get(bucket, 0)
            stream_id = n % self.slots
            rr = RouterRequest(
                self, rid, ids, int(max_new_tokens), stream_id, bucket,
                None if deadline_s is None else self.clock() + deadline_s,
                stream_cb,
            )
            rr.speculate = bool(speculate)
            rr.pin_version = (None if pin_version is None
                              else str(pin_version))
            rr.ts_arrival = self.clock()
            # transfer-overlap contract (ISSUE 14): a transferred
            # request submits to its decode home IMMEDIATELY, gated on
            # the transfer id — it keeps its FIFO position there while
            # the prompt pass runs on the prefill replica and the
            # chain's chunks stream in between that replica's decode
            # segments; admission lands the boundary the last chunk
            # does (or falls back to a local prefill if anything on
            # the prefill path breaks — fail_transfer unblocks it)
            await_tid = (f"{rid}.tx" if (do_transfer or do_pull)
                         else None)
            # keyword added only when set: non-transferring tiers keep
            # the PR 8 replica signature (duck-typed backends/fakes)
            extra = ({"await_transfer": await_tid}
                     if await_tid is not None else {})
            # distributed tracing (ISSUE 19): spans + wire context only
            # for head-sampled requests — the 15-in-16 majority pays
            # one flag read and one crc32 (the <=2% place-p50 budget)
            if _trace.is_enabled() and _trace.head_sampled(rid):
                sp = _trace.begin(
                    "router.request", trace_id=rid, bucket=bucket,
                    prompt_tokens=int(ids.size),
                    max_new_tokens=int(max_new_tokens))
                if sp is not None:  # disabled in the begin race
                    rr._tspan = sp
                    rr._tctx = {"trace_id": rid,
                                "parent_span": sp.span}
                    extra["trace_ctx"] = rr._tctx
                    with self._lock:
                        self._recent_traced.append(rid)
            for idx in candidates:
                rep = self.replicas[idx]
                cb = rr._make_cb()
                try:
                    inner = rep.submit(
                        ids, int(max_new_tokens), deadline_s=deadline_s,
                        stream_cb=cb, request_id=rid,
                        stream_id=stream_id, speculate=rr.speculate,
                        **extra,
                    )
                except QueueFull as e:
                    last_qf = e
                    continue
                except SchedulerClosed:
                    saw_closed = True
                    continue
                rr._bind(idx, inner)
                if do_transfer:
                    rr._transfer = {"phase": "prefill", "tid": await_tid,
                                    "prefill": None, "pf_req": None}
                elif do_pull:
                    rr._transfer = {"phase": "pull", "tid": await_tid,
                                    "prefill": pull_src, "pf_req": None}
                with self._lock:
                    self._admit_counts[bucket] = n + 1
                    self._inflight[rid] = rr
                    self.placements[rep.name] = (
                        self.placements.get(rep.name, 0) + 1)
                if keys:
                    for k in keys:
                        self._affinity.put(k, idx)
                    if self.tier_directory:
                        self._directory_put(keys, idx, "resident")
                placed = idx
                placed_score = self._score[idx]
                break
        if placed is not None:
            try:
                pages = self.replicas[placed].pages_needed(
                    int(ids.size), int(max_new_tokens))
            except Exception:
                pages = 0
            self._note_placed(placed, int(pages or 0))
            self._count("placed")
            if affinity_used and placed == first:
                self._count("affinity_hits")
            self.metrics.event(rid, "placed",
                              replica=self.replicas[placed].name,
                              stream_id=stream_id, bucket=bucket,
                              affinity=bool(affinity_used
                                            and placed == first),
                              transfer=bool(do_transfer),
                              depth=placed_score)
            if do_transfer:
                self._begin_transfer(rr, pf_live, keys)
            elif do_pull:
                self._begin_pull(rr, pull_src, pull_tokens, await_tid)
            return rr
        # every eligible replica said no. If every refusal was a
        # drain/stop that landed after the eligibility snapshot, this
        # is the drain contract's 503 (go elsewhere), NOT a 429
        # (retry here) — a 429 would tell the LB to retry into a
        # draining tier.
        if rr._tspan is not None:
            _trace.end(rr._tspan, rejected=True)
            rr._tspan = rr._tctx = None
        if last_qf is None and saw_closed:
            raise SchedulerClosed("every replica is draining or closed")
        retry = self._min_retry(retry_pool())
        if last_qf is not None:
            retry = min(retry, last_qf.retry_after_s)
        self._count("rejected")
        self.metrics.event("-rejected-", "reject", depth=depth,
                          retry_after_s=retry)
        raise QueueFull(depth, retry)

    def cancel(self, request) -> bool:
        """Cancel by :class:`RouterRequest` or id (any replica)."""
        rr = request
        if not isinstance(rr, RouterRequest):
            with self._lock:
                rr = self._inflight.get(str(request))
        if rr is None:
            return False
        with rr._lock:
            rr.client_cancelled = True
            inner, idx = rr._inner, rr._replica_idx
            tx = rr._transfer
        if inner is None or idx < 0:
            if tx is not None:
                # mid-transfer: best-effort cancel of the prefill leg;
                # the transfer machinery surfaces the terminal when it
                # next touches this request (client_cancelled gates
                # every forward step)
                pf_idx, pf_req = tx.get("prefill"), tx.get("pf_req")
                if pf_idx is not None and pf_req is not None:
                    try:
                        self.replicas[pf_idx].cancel(pf_req)
                    except Exception:
                        pass
                return True
            return False
        try:
            return self.replicas[idx].cancel(inner)
        except Exception:
            return False

    def retry_after_s(self) -> float:
        vals = []
        for i in self._live_indices():
            try:
                vals.append(float(self.replicas[i].retry_after_s()))
            except Exception:
                pass
        return min(vals) if vals else 1.0

    def _on_request_done(self, rr: RouterRequest) -> None:
        with self._lock:
            self._inflight.pop(rr.id, None)
        if rr._tspan is not None:
            _trace.end(rr._tspan, state=rr.state.value,
                       replica=rr._replica_idx,
                       resubmits=rr.resubmits)
            rr._tspan = None

    # ---- prefill/decode transfers (ISSUE 14) ------------------------
    def _begin_transfer(self, rr: RouterRequest,
                        pf_candidates: List[int],
                        keys: List[bytes]) -> None:
        """Phase 1: run the prompt pass on a prefill-class replica.
        Prefill placement is its own affinity+load decision (a
        repeated prefix exports from the prefill replica's OWN tree
        without recomputing); every rejection falls through to the
        next candidate, and total rejection falls back to a local
        prefill on the decode home — tokens identical either way."""
        with self._idx_lock:
            open_pf = [i for i in pf_candidates
                       if not self._closed_snap[i]]
            pf_scores = {i: self._score[i] for i in open_pf}
        if not open_pf:
            return self._abort_transfer(
                rr, "no open prefill replica", claim=True)
        order = sorted(open_pf, key=lambda i: (pf_scores[i], i))
        if keys:
            tgt = None
            for j in range(len(keys) - 1, -1, -1):
                tgt = self._pf_affinity.get(keys[j])
                if tgt is not None:
                    break
            if (tgt in pf_scores
                    and pf_scores[tgt] <= pf_scores[order[0]]
                    + self.affinity_slack):
                order.remove(tgt)
                order.insert(0, tgt)

        def on_pf(inner, new, finished):
            if finished:
                self._finish_transfer(rr, inner)

        # tracing (ISSUE 19): the prefill leg gets its own child span
        # under the router root; its trace context rides the RPC with
        # trace_id = the REQUEST id (overriding the worker-side
        # ``{rid}.pf`` request id), so the prefill worker's spans join
        # the same trace. Conditional kwarg: untraced tiers keep the
        # PR 14 replica signature.
        pf_span = None
        pf_kw: Dict[str, Any] = {}
        if rr._tctx is not None:
            pf_span = _trace.begin(
                "router.prefill", trace_id=rr.id,
                parent_id=rr._tctx.get("parent_span"))
            if pf_span is not None:
                pf_kw["trace_ctx"] = {"trace_id": rr.id,
                                      "parent_span": pf_span.span}
                with rr._lock:
                    if rr._transfer is not None:
                        rr._transfer["span"] = pf_span
        for idx in order:
            rep = self.replicas[idx]
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer["prefill"] = idx
            try:
                pf_req = rep.submit_prefill(
                    rr.prompt_ids, stream_cb=on_pf,
                    request_id=f"{rr.id}.pf", **pf_kw)
            except Exception:
                continue
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer["pf_req"] = pf_req
            for k in keys:
                self._pf_affinity.put(k, idx)
            self.metrics.event(rr.id, "prefill_placed",
                              replica=rep.name)
            return
        self._abort_transfer(rr, "every prefill replica rejected",
                             claim=True)

    def _finish_transfer(self, rr: RouterRequest, pf_req) -> None:
        """Phase 2 (fires on the prefill replica's completion): stream
        the exported chain to the request's decode home — where it
        already sits QUEUED at its FIFO position, gated on the
        transfer id — in ``transfer_chunk_pages``-page chunks; its
        admission lands the boundary the last chunk does, as a prefix
        hit. Any breakage aborts the transfer instead: the decode home
        runs the prefill locally, tokens identical."""
        from tpuflow.serve.pages import split_chain

        if not rr._claim_transfer("prefill", "landing"):
            return  # a maintenance sweep already aborted this one
        with rr._lock:
            tid = (rr._transfer or {}).get("tid")
        d_idx = rr.replica
        wire = getattr(pf_req, "export", None)
        if (pf_req.state is not RequestState.DONE or wire is None
                or d_idx < 0 or tid is None):
            return self._abort_transfer(
                rr, f"prefill failed: "
                    f"{pf_req.error or pf_req.state.value}")
        rep = self.replicas[d_idx]
        # tracing (ISSUE 19): the wire leg is a CHILD of the prefill
        # span — the tier trace nests transfer under prefill — and its
        # context rides both the chunk metadata (split_chain) and the
        # offer_chain RPC, so the decode home's landing spans join as
        # children of this transfer span.
        tx_span = None
        tx_kw: Dict[str, Any] = {}
        tx_ctx = None
        with rr._lock:
            pf_span = (rr._transfer or {}).get("span")
        if rr._tctx is not None:
            tx_span = _trace.begin(
                "router.transfer", trace_id=rr.id,
                parent_id=(pf_span.span if pf_span is not None
                           else rr._tctx.get("parent_span")),
                transfer_id=tid, to_replica=rep.name)
            if tx_span is not None:
                tx_ctx = {"trace_id": rr.id,
                          "parent_span": tx_span.span}
                tx_kw["trace_ctx"] = tx_ctx
        try:
            chunks = split_chain(wire, self.transfer_chunk_pages,
                                 trace_ctx=tx_ctx)
            for j, ch in enumerate(chunks):
                rep.offer_chain(ch, transfer_id=tid,
                                last=(j == len(chunks) - 1), **tx_kw)
            if not chunks:
                # nothing cacheable to ship (sub-page prompt): unblock
                # the waiting admission rather than time it out
                if tx_span is not None:
                    _trace.end(tx_span, failed="empty chain")
                return self._abort_transfer(rr, "empty chain")
        except Exception as e:
            if tx_span is not None:
                _trace.end(tx_span, failed=repr(e))
            return self._abort_transfer(rr, repr(e))
        if tx_span is not None:
            _trace.end(tx_span, pages=int(wire.get("n_pages", 0)),
                       chunks=len(chunks))
            _trace.end(pf_span)
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer.pop("span", None)
        with rr._lock:
            if rr._transfer is not None:
                rr._transfer["phase"] = "decode"
        self._count("transfers")
        self.metrics.event(
            rr.id, "transfer",
            pages=int(wire.get("n_pages", 0)),
            bytes=sum(len(p) for p in wire.get("payloads", ())),
            to_replica=rep.name)

    def _abort_transfer(self, rr: RouterRequest, reason: str,
                        claim: bool = False) -> None:
        """The prefill path broke (rejected everywhere, dead replica,
        corrupt/empty export): tell the decode home to stop waiting —
        its ``fail_transfer`` releases the request to a LOCAL prefill
        at its next boundary. Purely a lost optimization: the pinned
        stream id makes the tokens identical."""
        if claim and not rr._claim_transfer("prefill", "landing"):
            return
        with rr._lock:
            tid = (rr._transfer or {}).get("tid")
            pf_span = (rr._transfer or {}).pop("span", None)
        if pf_span is not None:
            _trace.end(pf_span, failed=reason)
        self._count("transfer_fallbacks")
        self.metrics.event(rr.id, "transfer_fallback", reason=reason)
        d_idx = rr.replica
        if d_idx >= 0 and tid is not None:
            try:
                self.replicas[d_idx].fail_transfer(tid, reason)
            except Exception:
                pass

    # ---- tier-global directory pulls (ISSUE 16) ---------------------
    def _begin_pull(self, rr: RouterRequest, src_idx: int,
                    tokens: np.ndarray, tid: str) -> None:
        """Directory-routed cross-replica pull: ask the holder for its
        chain (answered at ITS next scheduler boundary — resident
        re-export or spill-pool read, whichever is deeper) and stream
        the wire to the request's decode home in transfer chunks over
        the same ``offer_chain``/``await_transfer`` machinery a
        disaggregated prefill transfer rides. The request already sits
        QUEUED at the home gated on ``tid``; any fault on this path
        fail_transfers it into a LOCAL prefill — tokens identical
        either way."""
        from tpuflow.serve.pages import split_chain

        src = self.replicas[src_idx]

        def _fallback(reason: str) -> None:
            self._count("pull_fallbacks")
            self.metrics.event(rr.id, "pull_fallback", reason=reason,
                              from_replica=src.name)
            d = rr.replica
            if d >= 0:
                try:
                    self.replicas[d].fail_transfer(tid, reason)
                except Exception:
                    pass

        def on_ready(wire) -> None:
            if not rr._claim_transfer("pull", "landing"):
                return  # a maintenance sweep already aborted this one
            d_idx = rr.replica
            if wire is None or not wire.get("n_pages"):
                return _fallback("holder had nothing to export")
            if d_idx < 0 or d_idx == src_idx:
                # failover rebound the request onto the holder itself:
                # its own plan() promotes locally, no wire needed
                return _fallback("request landed on the holder")
            # tracing (ISSUE 19): a directory pull's wire leg is a
            # transfer span under the router root, its context riding
            # the chunk metadata + offer_chain like a disagg transfer
            tx_span = None
            tx_ctx = None
            tx_kw: Dict[str, Any] = {}
            if rr._tctx is not None:
                tx_span = _trace.begin(
                    "router.pull", trace_id=rr.id,
                    parent_id=rr._tctx.get("parent_span"),
                    transfer_id=tid, from_replica=src.name)
                if tx_span is not None:
                    tx_ctx = {"trace_id": rr.id,
                              "parent_span": tx_span.span}
                    tx_kw["trace_ctx"] = tx_ctx
            try:
                chunks = split_chain(wire, self.transfer_chunk_pages,
                                     trace_ctx=tx_ctx)
                for j, ch in enumerate(chunks):
                    self.replicas[d_idx].offer_chain(
                        ch, transfer_id=tid,
                        last=(j == len(chunks) - 1), **tx_kw)
            except Exception as e:
                if tx_span is not None:
                    _trace.end(tx_span, failed=repr(e))
                return _fallback(repr(e))
            if tx_span is not None:
                _trace.end(tx_span,
                           pages=int(wire.get("n_pages", 0)),
                           chunks=len(chunks))
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer["phase"] = "decode"
            self._count("pulls")
            self._directory_put(
                [bytes.fromhex(h) for h in
                 wire.get("chunk_keys", ())],
                d_idx, "resident")
            self.metrics.event(
                rr.id, "pull",
                pages=int(wire.get("n_pages", 0)),
                bytes=sum(len(p) for p in wire.get("payloads", ())),
                from_replica=src.name,
                to_replica=self.replicas[d_idx].name)

        try:
            src.request_chain(tokens, on_ready)
        except Exception as e:
            if rr._claim_transfer("pull", "landing"):
                _fallback(repr(e))

    def directory_sweep(self) -> int:
        """Merge every live replica's spilled-chain report into the
        directory (the resident entries placement already wrote).
        Rides :meth:`maintain`; returns rows merged."""
        merged = 0
        for idx in self._live_indices():
            rep = self.replicas[idx]
            report = getattr(rep, "kv_chain_report", None)
            if report is None:
                continue
            try:
                chains = report()
            except Exception:
                continue
            for ch in chains or ():
                try:
                    keys = [bytes.fromhex(h) for h in ch["keys"]]
                    tier = str(ch.get("tier", "host"))
                except (KeyError, TypeError, ValueError):
                    continue
                self._directory_put(keys, idx, tier)
                merged += 1
        return merged

    # ---- deployment plane (ISSUE 15) --------------------------------
    @staticmethod
    def _snap_version(snap: Dict[str, Any]) -> Optional[str]:
        """The comparable version label out of a load snapshot — ONE
        normalization (serve.deploy.version_label) shared with the
        deployment plane, so pin_version placement and the disagg
        version fence can never drift from what a rollout records."""
        from tpuflow.serve.deploy import version_label

        return version_label(snap.get("model_version"))

    def replica_version(self, idx: int, target: str = "model"):
        """One replica's current model (or draft) version, as its
        load snapshot reports it."""
        snap = self._safe_snapshot(idx)
        return snap.get("draft_version" if target == "draft"
                        else "model_version")

    def versions(self) -> Dict[str, Optional[str]]:
        """``{replica_name: version label}`` across the tier — the
        mid-rollout mix at a glance."""
        return {self.replicas[i].name: self._snap_version(
                    self._safe_snapshot(i))
                for i in range(len(self.replicas))}

    def standby_indices(self) -> List[int]:
        with self._lock:
            return sorted(self._standby)

    def active_indices(self) -> List[int]:
        """Replicas currently taking traffic (live, not standby, not
        retiring) — the set a rollout must move to the new version."""
        with self._lock:
            failed = set(self._failed)
            out = [i for i in range(len(self.replicas))
                   if i not in failed and i not in self._standby
                   and i not in self._retiring]
        return out

    def set_standby(self, idx: int) -> None:
        """Park a replica as standby (no placement until
        :meth:`activate`)."""
        with self._lock:
            self._standby.add(int(idx))
        self._rebuild_index()

    def activate(self, idx: int) -> None:
        """Standby → active: the replica joins placement (least-
        loaded, so traffic shifts to it naturally) — the blue half of
        the blue/green shift."""
        with self._lock:
            self._standby.discard(int(idx))
            self._retiring.discard(int(idx))
            self._failed.pop(int(idx), None)
        # a freshly activated replica may have swapped weights while
        # parked — refetch its snapshot so the version fence sees the
        # new label before the next placement, then rebuild the heaps
        self._refresh_plane([int(idx)])
        self.metrics.event("-deploy-", "replica_activated",
                           replica=self.replicas[idx].name)

    def begin_retire(self, idx: int) -> None:
        """Active → retiring: drain the replica (its admitted backlog
        finishes — zero truncated streams; new submits already route
        elsewhere because its snapshot reads closed)."""
        with self._lock:
            self._retiring.add(int(idx))
        try:
            self.replicas[idx].drain()
        except Exception:
            pass
        # the drain flips the replica's snapshot to closed — refetch
        # so cached-plane submits route around it immediately
        self._refresh_plane([int(idx)])
        self.metrics.event("-deploy-", "replica_retiring",
                           replica=self.replicas[idx].name)

    def retire(self, idx: int) -> None:
        """Give up on a retiring replica (wedged drain): excluded
        from placement like any failed replica, never recycled."""
        with self._lock:
            self._retiring.discard(int(idx))
        self.mark_failed(idx, reason="retired (deploy)")
        self._rebuild_index()

    def recycle_as_standby(self, idx: int) -> None:
        """Drained-out replica → the next rollout's standby."""
        with self._lock:
            self._retiring.discard(int(idx))
            self._standby.add(int(idx))
            self._failed.pop(int(idx), None)
        self._rebuild_index()
        self.metrics.event("-deploy-", "replica_recycled",
                           replica=self.replicas[idx].name)

    def hot_heads(self, n: int = 8) -> List[np.ndarray]:
        """The ``n`` hottest chain-head token prefixes the tier has
        seen (by placement count) — the rollout's replay source: a
        version bump invalidates cached KV, so warmth on the incoming
        replica is rebuilt by RE-PREFILLING these, never by
        transferring stale pages."""
        recs = sorted(self._hot.values(),
                      key=lambda r: -int(r["count"]))[: max(0, int(n))]
        return [np.array(r["tokens"], np.int32) for r in recs]

    def is_online(self) -> bool:
        """Whether the online maintenance thread is running (the
        rollout manager starts freshly swapped replicas' loops only
        on online tiers)."""
        return self._thread is not None and self._thread.is_alive()

    # ---- failover (maintenance) -------------------------------------
    def mark_failed(self, replica: "int | str", reason: str = "") -> None:
        """Exclude a replica from placement and make its queued
        requests failover-eligible (also the operator's manual lever —
        the watchdog path calls it from :meth:`maintain`)."""
        idx = replica
        if not isinstance(idx, int):
            idx = next(i for i, r in enumerate(self.replicas)
                       if r.name == replica)
        with self._lock:
            if idx in self._failed:
                return
            self._failed[idx] = reason or "marked failed"
        self._count("replicas_failed")
        self.metrics.event("-failover-", "replica_failed",
                          replica=self.replicas[idx].name, reason=reason)
        self._rebuild_index()

    def _probe_health(self, idx: int) -> Dict[str, Any]:
        t0 = time.time()
        try:
            h = self.replicas[idx].health()
        except Exception as e:
            return {"failed": True, "error": repr(e)}
        self._note_wall(idx, t0, time.time(), h.get("wall_s"))
        return h

    def maintain(self) -> bool:
        """One health/failover sweep: poll every live replica's
        :meth:`health`, fail the tripped/closed ones, resubmit their
        never-admitted requests elsewhere. Returns whether anything
        changed. The online maintenance thread calls this on a poll
        interval; offline drivers interleave it with replica steps.

        Fleet scale (ISSUE 17): the sweep first refreshes the cached
        snapshot plane (concurrently past 8 replicas), then probes
        health through the shared pool under a ``health_timeout_s``
        sweep deadline — a probe that misses the deadline carries over
        to the next sweep (slow ≠ failed, counted ``health_lagged``)
        instead of stalling failover for the whole tier."""
        from tpuflow.obs.gauges import set_gauge

        progress = False
        live = self._live_indices()
        # staleness is measured BEFORE the refresh: it reports the age
        # the previous interval actually left behind — the bound a
        # cached-plane submit could have observed
        set_gauge("router.snapshot_staleness_s",
                  self._staleness_s(live))
        self._refresh_plane(live, concurrent=len(live) >= 8)
        live_set = set(live)
        self._health_pending = {i: f for i, f in
                                self._health_pending.items()
                                if i in live_set}
        results: Dict[int, Dict[str, Any]] = {}
        if len(live) <= 1:
            for idx in live:
                results[idx] = self._probe_health(idx)
        else:
            pool = _probe_pool()
            futs = {}
            for idx in live:
                f = self._health_pending.pop(idx, None)
                if f is None:
                    f = pool.submit(self._probe_health, idx)
                futs[idx] = f
            deadline = time.monotonic() + self.health_timeout_s
            for idx, f in futs.items():
                try:
                    results[idx] = f.result(
                        timeout=max(0.0, deadline - time.monotonic()))
                except _futures.TimeoutError:
                    self._health_pending[idx] = f
                    self._count("health_lagged")
        for idx in sorted(results):
            h = results[idx]
            if h.get("failed"):
                self.mark_failed(idx, reason=str(
                    h.get("error")
                    or ("tripped" if h.get("tripped")
                        else "closed" if h.get("closed")
                        else "wedged-loop")))
                progress = True
        with self._lock:
            failed = dict(self._failed)
            pending = [rr for rr in self._inflight.values()
                       if rr._replica_idx in failed]
        for rr in pending:
            if rr._failover_pending():
                progress |= self._failover(rr)
        # ADMITTED work on a DEAD replica (closed / wedged loop — not
        # merely watchdog-tripped, whose loop keeps decoding and will
        # finish its rows) can neither complete nor be replayed
        # token-identically (tokens were already streamed): fail it to
        # the client now instead of hanging result() until the
        # client's own timeout and pinning idle()/drain() open forever
        for rr in pending:
            # re-read the CURRENT home: the failover loop above may
            # have just rebound this request to a healthy replica (and
            # its scheduler may already have admitted it) — acting on
            # the stale pre-failover index would cancel a perfectly
            # good resubmission
            if rr._replica_idx not in failed:
                continue
            why = failed.get(rr._replica_idx, "")
            if "tripped" in why or rr._done.is_set():
                continue
            if rr._failover_pending():
                continue  # queued: the next sweep retries placement
            inner = rr.inner
            if inner is not None and inner.ts_admitted is not None:
                try:
                    self.replicas[rr._replica_idx].cancel(inner)
                except Exception:
                    pass
                rr._finalize_failed(
                    "replica failed with this request mid-decode")
                progress = True
        # disaggregation sweep (ISSUE 14): transfers stranded on a
        # FAILED prefill replica abort, releasing their decode-home
        # admission to a local prefill (the completion callback is the
        # normal path — this is the safety net when a replica dies
        # without finalizing its prefill request)
        with self._lock:
            stranded = [rr for rr in self._inflight.values()
                        if rr._transfer is not None
                        and rr._transfer.get("phase") == "prefill"
                        and rr._transfer.get("prefill") in failed]
            # directory pulls stranded on a failed HOLDER (ISSUE 16):
            # same safety net, same fallback
            stranded_pulls = [rr for rr in self._inflight.values()
                              if rr._transfer is not None
                              and rr._transfer.get("phase") == "pull"
                              and rr._transfer.get("prefill") in failed]
        for rr in stranded:
            self._abort_transfer(rr, "prefill replica failed",
                                 claim=True)
            progress = True
        for rr in stranded_pulls:
            if rr._claim_transfer("pull", "landing"):
                self._count("pull_fallbacks")
                d = rr.replica
                if d >= 0:
                    tid = (rr._transfer or {}).get("tid")
                    try:
                        self.replicas[d].fail_transfer(
                            tid, "pull holder failed")
                    except Exception:
                        pass
                progress = True
        if self.tier_directory:
            self.directory_sweep()
        set_gauge("router.replicas", float(len(self.replicas)))
        set_gauge("router.replicas_failed", float(len(failed)))
        # deployment hook (ISSUE 15): an active rollout's state
        # machine advances on the same cadence as health/failover
        for hook in list(self.on_maintain):
            try:
                hook()
            except Exception:
                pass
        return progress

    def _failover(self, rr: RouterRequest) -> bool:
        """Resubmit one never-admitted request off its failed replica.
        Token-identity: the pinned ``stream_id`` travels with it, and
        nothing had been produced (the candidate test guarantees it)."""
        with rr._lock:
            old_idx, old_inner = rr._replica_idx, rr._inner
        # decode-capable candidates only: a prefill-class replica must
        # never inherit a decode through failover either; standby
        # replicas take no traffic, and a version-pinned request only
        # moves to a replica serving exactly that version (ISSUE 15)
        with self._lock:
            standby = set(self._standby)
        candidates = [i for i in self._live_indices()
                      if i != old_idx and i not in self._prefill_set
                      and i not in standby]
        # cached plane, not a snapshot fan-out: _failover runs right
        # after maintain()'s refresh, so the arrays are this sweep's
        with self._idx_lock:
            scores = {i: self._score[i] for i in candidates}
            closed = {i: self._closed_snap[i] for i in candidates}
            vers = {i: self._ver_label[i] for i in candidates}
        if rr.pin_version is not None:
            candidates = [i for i in candidates
                          if vers[i] == rr.pin_version]
        order = sorted(
            (i for i in candidates if not closed[i]),
            key=lambda i: (scores[i], i),
        )
        if not order:
            if not self._accepting_failover() or not candidates:
                rr._finalize_failed(
                    "replica failed and no replica left to resubmit to")
            return False
        now = self.clock()
        deadline_s = (None if rr.deadline_ts is None
                      else max(0.0, rr.deadline_ts - now))
        for idx in order:
            rep = self.replicas[idx]
            cb = rr._make_cb()  # invalidates the old generation FIRST
            try:
                inner = rep.submit(
                    rr.prompt_ids, rr.max_new_tokens,
                    deadline_s=deadline_s, stream_cb=cb,
                    request_id=rr.id, stream_id=rr.stream_id,
                    speculate=rr.speculate,
                )
            except (QueueFull, SchedulerClosed):
                continue
            if rr.ts_arrival is not None:
                inner.ts_arrival = rr.ts_arrival
            rr._bind(idx, inner)
            rr.resubmits += 1
            with self._lock:
                self.placements[rep.name] = (
                    self.placements.get(rep.name, 0) + 1)
            try:
                pages = rep.pages_needed(int(rr.prompt_ids.size),
                                         int(rr.max_new_tokens))
            except Exception:
                pages = 0
            self._note_placed(idx, int(pages or 0))
            self._count("failovers")
            self.metrics.event(rr.id, "failover",
                              from_replica=self.replicas[old_idx].name,
                              to_replica=rep.name,
                              stream_id=rr.stream_id)
            if old_inner is not None:
                try:  # best-effort: the old home may be long dead
                    self.replicas[old_idx].cancel(old_inner)
                except Exception:
                    pass
            return True
        return False  # nowhere to go right now; retried next sweep

    # ---- drain / lifecycle ------------------------------------------
    def drain(self, wait_s: Optional[float] = None) -> None:
        """Tier-wide graceful drain: 503 new submits, drain every
        replica (each finishes its admitted backlog), flip ``/readyz``,
        annotate the flight manifest. Non-blocking unless ``wait_s``."""
        with self._lock:
            first = not self._draining
            self._draining = True
        if first:
            from tpuflow.obs import flight as _flight
            from tpuflow.obs.gauges import set_gauge

            self._count("drains")
            set_gauge("router.draining", 1.0)
            depth = sum(int(self._safe_snapshot(i).get("queue_depth", 0))
                        for i in self._live_indices())
            self.metrics.event("-router-", "drain", queue_depth=depth)
            _flight.annotate("router.drain", {
                "ts": self.clock(),
                "queue_depth": depth,
                "inflight": len(self._inflight),
                "replicas": [self.replicas[i].name
                             for i in self._live_indices()],
            })
            for i in self._live_indices():
                try:
                    self.replicas[i].drain()
                except Exception:
                    pass
        if wait_s is not None:
            deadline = time.time() + wait_s
            while not self.idle() and time.time() < deadline:
                time.sleep(0.01)

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        return self._draining and self.idle()

    def idle(self) -> bool:
        with self._lock:
            if self._inflight:
                return False
        return all(self.replicas[i].idle() for i in self._live_indices())

    def start(self, poll_s: float = 0.25) -> None:
        """Online drive: start every replica's loop plus the router's
        maintenance thread (health polling → failover)."""
        for i in self._live_indices():
            self.replicas[i].start()
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.is_set():
                try:
                    self.maintain()
                except Exception:
                    pass
                self._stop_evt.wait(poll_s)

        self._thread = threading.Thread(
            target=loop, name="tpuflow-router", daemon=True)
        self._thread.start()

    def run_until_idle(self) -> None:
        """Offline drive: step every live replica and the maintenance
        sweep on the calling thread until nothing makes progress (the
        single-scheduler ``run_until_idle`` contract, tier-wide)."""
        while True:
            progress = False
            for i in self._live_indices():
                rep = self.replicas[i]
                if not rep.idle():
                    progress |= bool(rep.step())
            progress |= self.maintain()
            if not progress:
                return

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        deadline = time.time() + timeout
        if drain:
            self.drain(wait_s=timeout)
        with self._lock:
            self._closed = True
            self._draining = True
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=max(0.1, deadline - time.time()))
        for i in range(len(self.replicas)):
            try:
                self.replicas[i].stop(
                    drain=drain,
                    timeout=max(0.1, deadline - time.time()))
            except Exception:
                pass
        with self._lock:
            leftovers = list(self._inflight.values())
        for rr in leftovers:
            rr._finalize_failed("router stopped")

    # ---- introspection ----------------------------------------------
    def readiness(self) -> Dict[str, Any]:
        """Tier ``/readyz``: ready while the router is open and at
        least one live replica is ready; per-replica detail rides in
        the body so the probe's reason is in the probe."""
        per: Dict[str, Any] = {}
        ready_n = 0
        depth = 0
        with self._lock:
            failed = dict(self._failed)
            draining, closed = self._draining, self._closed
            standby = set(self._standby)
            retiring = set(self._retiring)
        for i, rep in enumerate(self.replicas):
            try:
                r = rep.readiness()
            except Exception as e:
                r = {"ready": False, "error": repr(e)}
            snap = self._safe_snapshot(i)
            depth += int(snap.get("queue_depth", 0))
            ok = bool(r.get("ready")) and i not in failed
            ready_n += ok
            per[rep.name] = {
                "ready": ok,
                "failed": failed.get(i),
                "class": self.classes[i],
                "standby": i in standby,
                "retiring": i in retiring,
                "model_version": self._snap_version(snap),
                "queue_depth": snap.get("queue_depth"),
                "running": snap.get("running"),
                "draining": snap.get("draining"),
            }
        # a disaggregated tier with only its prefill replicas ready
        # cannot serve a single token — readiness needs a DECODE home
        # that is actually TAKING traffic (standby replicas don't)
        decode_ready = sum(
            1 for i, rep in enumerate(self.replicas)
            if i in set(self._decode_set)
            and i not in standby
            and per[rep.name]["ready"])
        return {
            "ready": bool(decode_ready) and not (draining or closed),
            "closed": closed,
            "draining": draining,
            "replicas_ready": ready_n,
            "queue_depth": depth,
            "running": sum(int(p.get("running") or 0)
                           for p in per.values()),
            "replicas": per,
        }

    def snapshot(self) -> Dict[str, float]:
        """Router-tier gauges/counters as a flat dotted dict."""
        with self._lock:
            out = {f"router.{k}": float(v) for k, v in self.counts.items()}
            out["router.inflight"] = float(len(self._inflight))
            out["router.replicas"] = float(len(self.replicas))
            out["router.replicas_live"] = float(
                len(self.replicas) - len(self._failed))
            out["router.replicas_standby"] = float(len(self._standby))
            out["router.replicas_retiring"] = float(len(self._retiring))
            out["router.affinity_table"] = float(len(self._affinity))
            if self.tier_directory:
                out["router.directory_table"] = float(
                    len(self._directory))
            for name, n in self.placements.items():
                out[f"router.placements.{name}"] = float(n)
        return out

    def metrics_snapshot(self) -> Dict[str, Any]:
        """The tier's ``/v1/metrics`` body: every replica's snapshot
        (their per-replica gauge prefixes keep them apart) plus the
        router's own counters and the aggregate queue depth."""
        snap: Dict[str, Any] = {}
        for i in range(len(self.replicas)):
            try:
                snap.update(self.replicas[i].metrics_snapshot())
            except Exception:
                pass
        snap.update(self.snapshot())
        snap["router.queue_depth"] = float(sum(
            int(self._safe_snapshot(i).get("queue_depth", 0))
            for i in self._live_indices()))
        # hot-path observability (ISSUE 17): placement latency
        # percentiles + snapshot-plane staleness, so the flat-overhead
        # claim is operator-visible on /v1/metrics
        from tpuflow.obs.gauges import get_histogram

        h = get_histogram("router.place_ms")
        if h is not None and h.n:
            for p in (50, 95, 99):
                snap[f"router.place_ms_p{p}"] = float(
                    h.percentile(p))
        snap["router.snapshot_staleness_s"] = float(
            self._staleness_s())
        return snap

    def load_snapshot(self) -> Dict[str, Any]:
        """Tier-aggregate load sensor (an LB in front of SEVERAL
        routers composes the same way replicas compose under one)."""
        per = {i: self._safe_snapshot(i) for i in self._live_indices()}
        with self._lock:
            closed, draining = self._closed, self._draining
        out: Dict[str, Any] = {
            "queue_depth": sum(int(s.get("queue_depth", 0))
                               for s in per.values()),
            "running": sum(int(s.get("running", 0))
                           for s in per.values()),
            "closed": closed,
            "draining": draining,
            "replicas": {self.replicas[i].name: s
                         for i, s in per.items()},
        }
        frees = [s.get("kv_pages_free") for s in per.values()]
        if frees and all(f is not None for f in frees):
            out["kv_pages_free"] = int(sum(frees))
        # fleet hot-path health (ISSUE 17): an LB composing several
        # routers can see each tier's snapshot-plane freshness and
        # placement latency without scraping Prometheus
        out["snapshot_staleness_s"] = float(self._staleness_s())
        # wall anchor (ISSUE 19): a tier-of-tiers LB estimates THIS
        # router's clock offset the way this router estimates its
        # replicas' — the sensor composes
        out["wall_s"] = time.time()
        from tpuflow.obs.gauges import get_histogram

        h = get_histogram("router.place_ms")
        if h is not None and h.n:
            out["place_ms_p95"] = float(h.percentile(95))
        with self._lock:
            out["snapshot_refreshes"] = int(
                self.counts.get("snapshot_refreshes", 0))
            out["snapshot_errors"] = int(
                self.counts.get("snapshot_errors", 0))
            out["health_lagged"] = int(
                self.counts.get("health_lagged", 0))
        # tier windowed error rate (ISSUE 20): request-weighted sum of
        # the per-replica windowed sensors — an LB (or the canary
        # scorer) sees an error SPIKE, not a cumulative average
        errs = sum(float(s.get("errors_windowed", 0) or 0)
                   for s in per.values())
        reqs = sum(float(s.get("requests_windowed", 0) or 0)
                   for s in per.values())
        out["error_rate"] = round(errs / reqs, 6) if reqs else 0.0
        out["errors_windowed"] = errs
        out["requests_windowed"] = reqs
        return out

    def version_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Tier-level per-version metric cuts (ISSUE 20): each live
        replica's :meth:`version_snapshot` summed per version label —
        counters add, histogram states add bucket-wise — so blue and
        green are directly comparable mid-rollout no matter how the
        router spread their traffic. Replicas without the sensor
        (duck-typed fakes, old workers) contribute nothing."""
        out: Dict[str, Dict[str, Any]] = {}
        for i in self._live_indices():
            fetch = getattr(self.replicas[i], "version_snapshot", None)
            if fetch is None:
                continue
            try:
                snap = fetch()
            except Exception:
                continue
            for label, cut in (snap or {}).items():
                agg = out.get(label)
                if agg is None:
                    out[label] = {
                        k: (dict((hn, dict(hs))
                                 for hn, hs in v.items())
                            if k == "hists" else v)
                        for k, v in cut.items()
                    }
                    continue
                for k, v in cut.items():
                    if k == "hists":
                        for hn, hs in v.items():
                            cur = agg["hists"].get(hn)
                            if cur is None:
                                agg["hists"][hn] = dict(hs)
                                continue
                            cur["counts"] = [
                                a + b for a, b in zip(cur["counts"],
                                                      hs["counts"])]
                            cur["n"] = cur["n"] + hs["n"]
                            cur["total"] = cur["total"] + hs["total"]
                            cur["vmin"] = min(cur["vmin"], hs["vmin"])
                            cur["vmax"] = max(cur["vmax"], hs["vmax"])
                    else:
                        agg[k] = agg.get(k, 0) + v
        return out

    # ---- tier trace collection (ISSUE 19) ---------------------------
    def tier_trace(self, request_id: str,
                   export_path: Optional[str] = None) -> Dict[str, Any]:
        """ONE merged tier trace for a request: the router's own spans
        and event-log instants, plus a fan-out to every replica that
        touched the request (the event log knows — placed, prefill,
        transfer endpoints), each part offset-corrected by that
        replica's RTT-midpoint clock estimate into the ROUTER's epoch
        and merged with monotone parent/child edges
        (:func:`tpuflow.obs.trace.merge_tier_spans`). In-process
        replicas share the router's span ring and are covered by the
        local part (``trace_spans() is None``). ``export_path`` also
        writes the merged view as one Chrome trace."""
        rid = str(request_id)
        events = self.metrics.events(rid)
        local = _trace.spans_for(rid)
        for ev in events:
            attrs = {k: v for k, v in ev.items()
                     if k not in ("ts", "event")}
            local.append({
                "name": f"event:{ev.get('event')}",
                "span_id": None, "parent_id": None, "thread": None,
                "start_s": round(float(ev.get("ts", 0.0)), 6),
                "dur_ms": 0.0, "instant": True, "attrs": attrs,
            })
        parts = [("router", 0.0, local)]
        # the replicas this request touched, from the event log: its
        # decode home, prefill replica, and any transfer/pull endpoint
        by_name = {self.replicas[i].name: i
                   for i in range(len(self.replicas))}
        touched: List[int] = []
        for ev in events:
            for key in ("replica", "to_replica", "from_replica"):
                idx = by_name.get(ev.get(key))
                if idx is not None and idx not in touched:
                    touched.append(idx)
        offsets: Dict[str, float] = {}
        for idx in touched:
            rep = self.replicas[idx]
            fetch = getattr(rep, "trace_spans", None)
            spans = fetch(rid) if fetch is not None else None
            if spans is None:  # shares the router's span ring
                continue
            with self._idx_lock:
                off = self._wall_off[idx]
            offsets[rep.name] = round(off, 6)
            if spans:
                parts.append((rep.name, off, spans))
        merged = _trace.merge_tier_spans(parts)
        out: Dict[str, Any] = {
            "id": rid,
            "tracer_enabled": _trace.is_enabled(),
            "sources": [p[0] for p in parts],
            "clock_offset_s": offsets,
            "spans": merged,
        }
        if export_path:
            out["path"] = _trace.export_chrome_spans(
                export_path, merged, label=f"{self.name} {rid}")
        return out

    def flight_snapshot(self) -> Dict[str, Any]:
        """The flight recorder's ``router.json`` section."""
        with self._lock:
            inflight = [
                {"id": rr.id, "replica": rr._replica_idx,
                 "state": (rr._inner.state.value
                           if rr._inner is not None
                           else "transfer:" + str(
                               (rr._transfer or {}).get("phase", "?"))),
                 "resubmits": rr.resubmits,
                 "orphaned": rr._orphaned}
                for rr in self._inflight.values()
            ]
            failed = {self.replicas[i].name: why
                      for i, why in self._failed.items()}
            counts = dict(self.counts)
            draining, closed = self._draining, self._closed
            standby = [self.replicas[i].name for i in self._standby]
            retiring = [self.replicas[i].name for i in self._retiring]
        # ONE snapshot fetch per replica: versions derive from the
        # same snaps (an HTTP replica pays a round-trip per fetch)
        snaps = {self.replicas[i].name: self._safe_snapshot(i)
                 for i in range(len(self.replicas))}
        # tier tracing view (ISSUE 19): sampling config, the per-
        # replica clock-offset estimates, and the merged tier trace of
        # the most recent sampled requests — a crash bundle then
        # carries the cross-process story, not just this process's ring
        with self._lock:
            recent = list(self._recent_traced)[-2:]
        with self._idx_lock:
            wall_off = {self.replicas[i].name: round(self._wall_off[i], 6)
                        for i in range(len(self.replicas))
                        if self._wall_ts[i] > 0.0}
        tier_traces = {}
        for rid in recent:
            try:
                tier_traces[rid] = self.tier_trace(rid)["spans"]
            except Exception:
                pass
        return {
            "draining": draining,
            "closed": closed,
            "failed": failed,
            "counts": counts,
            "standby": standby,
            "retiring": retiring,
            "versions": {name: self._snap_version(s)
                         for name, s in snaps.items()},
            "placements": dict(self.placements),
            "replicas": snaps,
            "inflight": inflight,
            "trace": {
                "enabled": _trace.is_enabled(),
                "sampling": _trace.sampling(),
                "clock_offset_s": wall_off,
                "tier_traces": tier_traces,
            },
        }
