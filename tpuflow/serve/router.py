"""Multi-replica serving tier: the load-aware front router (ISSUE 8).

Everything below this module is ONE scheduler on one process; the
router is the layer that opens horizontal scale (ROADMAP item 3): it
owns N replicas (:class:`~tpuflow.serve.replica.Replica` — in-process
``ServeScheduler`` backends today, HTTP backends later) behind the one
submit/stream/cancel surface the HTTP frontend already speaks, and
turns the observability planes into CONTROL inputs:

- **placement** is least-loaded over each replica's
  ``load_snapshot()`` (queue depth + running rows; free KV pages and
  windowed TTFT p95 ride along for dashboards and external LBs) —
  never a Prometheus text parse;
- **prefix affinity**: the prompt's page-size token chunks are hashed
  exactly the way ``serve/pages.py::PrefixCache`` chunks them
  (:func:`tpuflow.serve.pages.chunk_keys`), and the deepest chain the
  router has seen before pulls the request to the replica that already
  holds those KV pages — shared-system-prompt traffic sticks where its
  prefill is already cached, with a load-slack valve so a hot prefix
  cannot starve the tier down to one replica;
- **backpressure / shedding**: per-replica ``QueueFull`` is retried on
  the next-best replica; when EVERY eligible replica rejects (or all
  KV allocators are dry with backlogs, or the optional tier-wide queue
  bound is hit) the router raises its own ``QueueFull`` carrying the
  MIN across-replica Retry-After — the soonest any capacity frees;
- **failover**: a replica that trips the watchdog or closes without
  draining gets its still-QUEUED (never-admitted) requests resubmitted
  elsewhere; the router pins every request's sampling ``stream_id``
  from ONE tier-global per-bucket counter, so outputs — including
  resubmitted ones — are TOKEN-IDENTICAL to the same trace served by a
  single scheduler;
- **graceful drain**: :meth:`Router.drain` stops admissions (503),
  drains every replica (each finishes its admitted backlog — zero
  truncated streams), flips ``/readyz`` and annotates the flight
  recorder's manifest; wired to SIGTERM by ``python -m tpuflow.serve``
  through train/preempt.py's signal channel and to HTTP via
  ``POST /v1/admin/drain``.

The router is PURE HOST POLICY: it never touches device arrays — all
device work stays on the replica schedulers' threads (a grep guard in
tests/test_serve_router.py pins this boundary the way PR 7's jit-site
guard pins the compile registry).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from tpuflow.serve.pages import chunk_keys
from tpuflow.serve.request import (
    QueueFull,
    Request,
    RequestState,
    SchedulerClosed,
)


class RouterMetrics:
    """Router-tier event log (bounded, same contract as
    :class:`~tpuflow.serve.metrics.ServeMetrics`'s): per-request-id
    placement/shed/failover events, merged with each replica's own
    events on read so ``GET /v1/events/<id>`` tells one story."""

    def __init__(self, max_event_requests: int = 512,
                 max_events_per_request: int = 128):
        self._lock = threading.Lock()
        self._events: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
        self._max_requests = max_event_requests
        self._max_per_request = max_events_per_request
        # read-side merge hooks (the replicas' metrics.events fns)
        self.merge_sources: List[Callable[[str], List[Dict[str, Any]]]] = []

    def event(self, request_id: str, name: str, **detail: Any) -> None:
        rec = {"ts": time.time(), "event": name}
        if detail:
            rec.update(detail)
        with self._lock:
            log = self._events.get(request_id)
            if log is None:
                self._events[request_id] = log = []
                while len(self._events) > self._max_requests:
                    self._events.popitem(last=False)
            log.append(rec)
            if len(log) > self._max_per_request:
                del log[: len(log) - self._max_per_request]

    def events(self, request_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events.get(request_id, []))
        for src in self.merge_sources:
            try:
                out.extend(src(request_id))
            except Exception:
                pass
        out.sort(key=lambda r: r.get("ts", 0.0))
        return out


class RouterRequest:
    """One tier-level request: a stable client handle whose UNDERLYING
    replica request may be swapped by failover. The client surface
    (``wait``/``result``/``summary``/``tokens``/``state``) always
    describes the CURRENT inner request; stream callbacks from a
    superseded inner are dropped, and a replica-shutdown cancellation
    of a never-admitted request is held back from the client until the
    router has had the chance to resubmit it elsewhere."""

    def __init__(self, router: "Router", request_id: str,
                 prompt_ids: np.ndarray, max_new_tokens: int,
                 stream_id: int, bucket: int,
                 deadline_ts: Optional[float],
                 stream_cb: Optional[Callable]):
        self.id = request_id
        self.prompt_ids = prompt_ids
        self.max_new_tokens = int(max_new_tokens)
        self.stream_id = int(stream_id)
        self.bucket = int(bucket)
        self.deadline_ts = deadline_ts
        self.stream_cb = stream_cb
        self.client_cancelled = False
        self.speculate = True  # per-request spec opt-out (ISSUE 9)
        # version pin (ISSUE 15): placement AND failover restricted to
        # replicas serving exactly this model version — the
        # token-identical A/B surface during a rollout
        self.pin_version: Optional[str] = None
        self.resubmits = 0
        self.ts_arrival: Optional[float] = None
        self._router = router
        self._lock = threading.Lock()
        self._gen = 0
        self._inner: Optional[Request] = None
        self._replica_idx: int = -1
        self._done = threading.Event()
        self._orphaned = False  # terminal held back pending failover
        self._error: Optional[str] = None
        # prefill/decode disaggregation (ISSUE 14): a transferred
        # request binds to its decode home IMMEDIATELY (the inner
        # request queues there gated on the transfer id, keeping its
        # FIFO position); _transfer tracks the PREFILL leg — phase
        # 'prefill' (prompt pass in flight on the prefill replica) →
        # 'landing' (claimed by completion/abort) → 'decode' (chunks
        # shipped). Aborts release the inner via fail_transfer.
        self._transfer: Optional[Dict[str, Any]] = None

    # ---- wiring (router-owned) --------------------------------------
    def _make_cb(self) -> Callable:
        """A stream callback bound to the NEXT generation: events from
        any earlier (superseded) inner request are dropped, and the
        replica-shutdown terminal of a failover-eligible request is
        suppressed until :meth:`Router.maintain` decides its fate."""
        with self._lock:
            self._gen += 1
            gen = self._gen

        def cb(inner: Request, new: List[int], finished: bool) -> None:
            with self._lock:
                if gen != self._gen:
                    return  # stale generation: failover superseded it
                if finished and self._failover_candidate(inner):
                    self._orphaned = True
                    return
            if self.stream_cb is not None and (new or finished):
                self.stream_cb(self, list(new), finished)
            if finished:
                self._done.set()
                self._router._on_request_done(self)

        return cb

    def _failover_candidate(self, inner: Request) -> bool:
        """A terminal that should NOT reach the client (yet): the
        replica cancelled a request the CLIENT never cancelled, before
        it was ever admitted and before any token existed — replica
        shutdown, not a request outcome. Token-identity holds across a
        resubmit because nothing was produced."""
        return (inner.state is RequestState.CANCELLED
                and not self.client_cancelled
                and inner.ts_admitted is None
                and not inner.tokens
                and self._router._accepting_failover())

    def _bind(self, replica_idx: int, inner: Request) -> None:
        with self._lock:
            self._inner = inner
            self._replica_idx = replica_idx
            self._orphaned = False

    def _failover_pending(self) -> bool:
        with self._lock:
            inner = self._inner
            if self._done.is_set() or self.client_cancelled:
                return False
            return self._orphaned or (
                inner is not None
                and inner.state is RequestState.QUEUED)

    def _finalize_failed(self, error: str) -> None:
        """No replica left to serve this request: surface the terminal
        the suppression held back."""
        with self._lock:
            if self._done.is_set():
                return
            self._error = error
        if self.stream_cb is not None:
            try:
                self.stream_cb(self, [], True)
            except Exception:
                pass
        self._done.set()
        self._router._on_request_done(self)

    def _claim_transfer(self, from_phase: str, to_phase: str) -> bool:
        """CAS on the transfer phase: exactly one of a prefill
        completion callback and a maintenance-sweep rescue may move
        the request forward."""
        with self._lock:
            if (self._transfer is None
                    or self._transfer.get("phase") != from_phase):
                return False
            self._transfer["phase"] = to_phase
            return True

    # ---- client surface ---------------------------------------------
    @property
    def inner(self) -> Request:
        with self._lock:
            return self._inner

    @property
    def replica(self) -> int:
        with self._lock:
            return self._replica_idx

    @property
    def state(self) -> RequestState:
        inner = self.inner
        if inner is None:  # mid-transfer: not yet bound anywhere
            return (RequestState.CANCELLED if self._done.is_set()
                    else RequestState.QUEUED)
        return inner.state

    @property
    def tokens(self) -> List[int]:
        inner = self.inner
        return [] if inner is None else inner.tokens

    @property
    def error(self) -> Optional[str]:
        inner = self.inner
        return self._error or (None if inner is None else inner.error)

    def timing(self) -> Dict[str, Optional[float]]:
        inner = self.inner
        if inner is None:
            return {"queue_wait_ms": None, "ttft_ms": None,
                    "decode_ms": None, "e2e_ms": None}
        return inner.timing()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} still {self.state.value} after "
                f"{timeout}s"
            )
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        inner = self.inner
        if inner is None:
            out: Dict[str, Any] = {
                "id": self.id, "state": self.state.value,
                "tokens": [], "n_tokens": 0, "error": self._error,
                "metrics": self.timing(),
            }
        else:
            out = inner.summary()
            out["id"] = self.id
            if self._error:
                out["error"] = out["error"] or self._error
        if self.resubmits:
            out["resubmits"] = self.resubmits
        return out


class Router:
    """Front tier over N replicas — one submit/stream/cancel surface
    with load-aware placement, prefix affinity, shedding, failover and
    graceful drain (module docstring has the policy tour). Duck-types
    the scheduler surface :mod:`tpuflow.serve.http` drives, so
    ``start_http_server(router)`` serves the whole tier.

    Drive it online (:meth:`start`: replica loops + a maintenance
    thread that polls health and fails replicas over) or offline
    (:meth:`run_until_idle` steps replicas + maintenance on the
    calling thread — deterministic tests and the virtual-clock
    bench)."""

    def __init__(
        self,
        replicas: Sequence,
        *,
        tokenizer=None,
        affinity: bool = True,
        affinity_slack: int = 4,
        affinity_capacity: int = 65536,
        placement: str = "load",
        max_total_queue: Optional[int] = None,
        shed_on_dry_kv: bool = True,
        clock: Callable[[], float] = time.time,
        name: str = "router",
        transfer_min_tokens: Optional[int] = None,
        transfer_chunk_pages: int = 8,
        standby: Sequence[int] = (),
        tier_directory: bool = False,
    ):
        """``placement='load'`` is the real policy (least-loaded with
        prefix affinity when ``affinity``); ``'spray'`` hashes the
        whole prompt to a replica — the locality-blind control the
        bench A/Bs against. ``affinity_slack`` is the load valve: an
        affinity candidate more than this many requests busier than
        the least-loaded replica is passed over (cache locality is
        worth a short wait, not a hot spot). ``max_total_queue``
        (default: the sum of replica ``max_queue``) sheds at the tier
        level before every replica must be tried; ``shed_on_dry_kv``
        429s immediately when every eligible replica's page allocator
        cannot cover the request AND already has a backlog — the
        all-allocators-dry backpressure contract, with Retry-After =
        the min across replicas (the soonest ANY of them frees
        enough).

        PREFILL/DECODE DISAGGREGATION (ISSUE 14): replicas declaring
        ``replica_class='prefill'`` are excluded from decode placement
        and serve prompt passes only; when at least one prefill- and
        one decode-capable replica exist, the tier is DISAGGREGATED
        and placement is two-phase — the decode home is picked by
        prefix affinity + load + page headroom, and a request whose
        estimated uncached suffix is at least ``transfer_min_tokens``
        (default two pages) prefills on the least-loaded prefill
        replica, whose exported KV page chain streams to the decode
        home in ``transfer_chunk_pages``-page chunks (landing between
        that replica's decode segments — transfer overlap) before the
        request admits there as a prefix hit. Every transfer failure
        (prefill rejected, wire CRC, dead replica) falls back to a
        plain local-prefill submit: tokens are identical either way,
        so disaggregation is purely a placement optimization.

        TIER-GLOBAL PREFIX DIRECTORY (ISSUE 16): ``tier_directory``
        lifts the per-replica affinity table into a tier-wide map from
        chunk-key chains to EVERY replica (and tier — resident page
        tree, host pool, disk) holding them: placement writes feed the
        resident entries, and the maintenance sweep merges each
        replica's ``kv_chain_report()`` (its spilled chains). A
        request whose prompt none of its home's caches cover, but
        which SOME live replica holds ≥ ``transfer_min_tokens``
        deeper, triggers a cross-replica PULL riding the exact
        ``offer_chain``/``await_transfer`` machinery above: the holder
        re-exports (or serves from its spill pool) at its next
        boundary and the chain streams to the home in transfer chunks.
        Every pull fault falls back to a local prefill — like the
        disagg transfer, a pull is purely a work-placement
        optimization and tokens are identical either way."""
        if not replicas:
            raise ValueError("router needs at least one replica")
        if placement not in ("load", "spray"):
            raise ValueError(
                f"placement must be 'load' or 'spray', got {placement!r}"
            )
        self.replicas = list(replicas)
        self.clock = clock
        # flight-provider/gauge identity: a process running SEVERAL
        # router tiers (multi-model serving) must name them apart or
        # the last tier's post-mortem section evicts the first's —
        # the ServeMetrics gauge_prefix rule, one layer up
        self.name = str(name)
        self.metrics = RouterMetrics()
        self.metrics.merge_sources = [
            rep.metrics.events for rep in self.replicas
            if getattr(rep, "metrics", None) is not None
        ]
        self._placement = placement
        self.slots = int(getattr(self.replicas[0], "slots", 1))
        self.max_new_cap = int(
            getattr(self.replicas[0], "max_new_cap", 64))
        self.tokenizer = tokenizer
        if tokenizer is None:
            self.tokenizer = getattr(self.replicas[0], "tokenizer", None)
        ps = getattr(self.replicas[0], "page_size", None)
        self.affinity_ps: Optional[int] = (
            int(ps) if (affinity and ps) else None)
        self.affinity_slack = int(affinity_slack)
        self._affinity: "OrderedDict[bytes, int]" = OrderedDict()
        self._affinity_cap = int(affinity_capacity)
        # replica classes (ISSUE 14): prefill-class replicas never
        # decode; the tier is DISAGGREGATED when both phases exist
        self.classes: List[str] = [
            str(getattr(rep, "replica_class", "mixed") or "mixed")
            for rep in self.replicas]
        self._prefill_set = {i for i, c in enumerate(self.classes)
                             if c == "prefill"}
        self._decode_set = [i for i, c in enumerate(self.classes)
                            if c != "prefill"]
        if not self._decode_set:
            raise ValueError(
                "router needs at least one decode-capable replica "
                "(every replica is prefill-class)")
        # zero-downtime deployment (ISSUE 15): STANDBY replicas are
        # registered (health-polled, swappable) but excluded from
        # placement until a rollout activates them; RETIRING replicas
        # are draining out of an old version (their backlog finishes,
        # no new placements — the blue/green shift)
        self._standby = {int(i) for i in standby}
        bad = [i for i in self._standby
               if not 0 <= i < len(self.replicas)]
        if bad:
            raise ValueError(f"standby indices out of range: {bad}")
        self._retiring: set = set()
        if not [i for i in self._decode_set if i not in self._standby]:
            raise ValueError(
                "router needs at least one ACTIVE decode-capable "
                "replica (every decode replica is standby)")
        # hottest chain heads (bounded): the rollout's prefix-warmth
        # replay source — deepest chunk-chain key → hit count + the
        # covering token prefix (a version bump invalidates cached KV,
        # so warmth is REBUILT by re-prefilling these, not transferred)
        self._hot: "OrderedDict[bytes, Dict[str, Any]]" = OrderedDict()
        self._hot_cap = 512
        # rollout hook: DeploymentManager.tick rides the maintenance
        # cadence through here (online tiers)
        self.on_maintain: List[Callable[[], Any]] = []
        self.disaggregated = bool(self._prefill_set)
        if transfer_min_tokens is None:
            transfer_min_tokens = 2 * int(ps) if ps else 1 << 30
        self.transfer_min_tokens = int(transfer_min_tokens)
        self.transfer_chunk_pages = max(1, int(transfer_chunk_pages))
        # prefill-side affinity: repeated prefixes prefill where their
        # pages already sit in the PREFILL replica's own tree
        self._pf_affinity: "OrderedDict[bytes, int]" = OrderedDict()
        # tier-global prefix directory (ISSUE 16): chunk key →
        # {replica idx: tier} over every holder, resident AND spilled
        # (LRU-capped like the affinity table; staleness is safe — a
        # pull miss fail_transfers into a local prefill)
        self.tier_directory = bool(tier_directory)
        self._directory: "OrderedDict[bytes, Dict[int, str]]" = (
            OrderedDict())
        if max_total_queue is None:
            mq = [self._safe_snapshot(i).get("max_queue")
                  for i in range(len(self.replicas))]
            mq = [int(m) for m in mq if m]
            max_total_queue = sum(mq) if mq else None
        self.max_total_queue = max_total_queue
        self.shed_on_dry_kv = bool(shed_on_dry_kv)
        self._lock = threading.Lock()
        # serializes [read stream counter → place → commit counter]:
        # concurrent submits must get DISTINCT, submission-ordered
        # stream ids (two racers sharing one id would sample from the
        # same stream and desync the single-scheduler parity sequence
        # forever). Never taken from replica callbacks → no inversion
        # against _lock / RouterRequest._lock.
        self._place_lock = threading.Lock()
        self._inflight: Dict[str, RouterRequest] = {}
        self._admit_counts: Dict[int, int] = {}  # tier-global stream ids
        self._failed: Dict[int, str] = {}
        self._seq = 0
        self._draining = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # counters (mirrored onto the obs registry as router.*)
        self.counts: Dict[str, int] = {
            "placed": 0, "affinity_hits": 0, "affinity_spills": 0,
            "shed": 0, "shed_kv": 0, "rejected": 0, "failovers": 0,
            "replicas_failed": 0, "drains": 0,
            "transfers": 0, "transfer_fallbacks": 0,
            "pulls": 0, "pull_fallbacks": 0,
        }
        self.placements: Dict[str, int] = {
            rep.name: 0 for rep in self.replicas}
        # post-mortem: the flight recorder snapshots the tier state
        # (weakly bound, like the scheduler's request provider)
        import weakref

        from tpuflow.obs import flight as _flight

        ref = weakref.ref(self)

        def _provider():
            r = ref()
            return r.flight_snapshot() if r is not None else None

        _flight.add_provider(self.name, _provider)

    # ---- small helpers ----------------------------------------------
    def _safe_snapshot(self, idx: int) -> Dict[str, Any]:
        try:
            return self.replicas[idx].load_snapshot()
        except Exception:
            return {"queue_depth": 0, "running": 0, "closed": True}

    def _count(self, key: str, by: int = 1) -> None:
        from tpuflow.obs.gauges import inc_counter

        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + by
        inc_counter(f"router.{key}_total", by)

    def _directory_put_locked(self, keys: Sequence[bytes], idx: int,
                              tier: str) -> None:
        # caller holds self._lock; LRU-capped alongside the affinity
        # table (same capacity — one knob)
        for k in keys:
            self._directory.setdefault(k, {})[idx] = tier
            self._directory.move_to_end(k)
        while len(self._directory) > self._affinity_cap:
            self._directory.popitem(last=False)

    def _live_indices(self) -> List[int]:
        with self._lock:
            failed = set(self._failed)
        return [i for i in range(len(self.replicas)) if i not in failed]

    def _accepting_failover(self) -> bool:
        with self._lock:
            return not (self._closed or self._draining)

    def _encode(self, prompt) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts need a tokenizer; submit token ids "
                    "or construct the router with one"
                )
            return np.asarray(self.tokenizer.encode(prompt), np.int32)
        return np.asarray(prompt, np.int32).reshape(-1)

    # ---- admission (any thread) -------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        *,
        deadline_s: Optional[float] = None,
        stream_cb: Optional[Callable] = None,
        request_id: Optional[str] = None,
        speculate: bool = True,
        pin_version: Optional[str] = None,
    ) -> RouterRequest:
        """Place one request on the best replica (module docstring has
        the policy). Raises the scheduler taxonomy: ``QueueFull``
        (tier saturated / all allocators dry — Retry-After is the min
        across replicas), :class:`SchedulerClosed` (draining/stopped),
        ``ValueError`` (never servable). ``speculate=False`` pins the
        request to plain decode on speculating replicas (ISSUE 9) and
        survives failover resubmission — tokens identical either
        way. ``pin_version`` (ISSUE 15) restricts placement — and any
        later failover — to replicas whose ``model_version`` label
        matches exactly: with the tier-global stream-id pinning this
        makes a version A/B during a rollout token-identical per
        version; a version nothing live serves raises
        :class:`SchedulerClosed` (503 — go elsewhere, the version is
        gone or not yet rolled)."""
        ids = self._encode(prompt)
        if max_new_tokens is None:
            max_new_tokens = self.max_new_cap
        with self._lock:
            if self._closed or self._draining:
                raise SchedulerClosed(
                    "router is stopped"
                    + (" (draining)" if self._draining else "")
                )
        live = self._live_indices()
        if not live:
            raise SchedulerClosed("router has no live replicas")
        snaps = {i: self._safe_snapshot(i) for i in live}
        # DECODE placement candidates: prefill-class replicas never
        # own a request's decode (ISSUE 14) — they serve prompt passes
        # through _begin_transfer below; standby replicas (ISSUE 15)
        # take no traffic until a rollout activates them
        with self._lock:
            standby = set(self._standby)
        eligible = [i for i in live if not snaps[i].get("closed")
                    and i not in self._prefill_set
                    and i not in standby]
        if not eligible:
            raise SchedulerClosed(
                "every decode-capable replica is draining or closed")
        if pin_version is not None:
            eligible = [i for i in eligible
                        if self._snap_version(snaps[i]) == pin_version]
            if not eligible:
                raise SchedulerClosed(
                    f"model version {pin_version!r} is not served by "
                    f"any live replica")
        depth = sum(int(snaps[i].get("queue_depth", 0)) for i in eligible)

        def _min_retry() -> float:
            vals = []
            for i in eligible:
                try:
                    vals.append(float(self.replicas[i].retry_after_s()))
                except Exception:
                    pass
            return min(vals) if vals else 1.0

        if (self.max_total_queue is not None
                and depth >= self.max_total_queue):
            retry = _min_retry()
            self._count("shed")
            self.metrics.event("-shed-", "shed", kind="queue",
                              depth=depth, retry_after_s=retry)
            raise QueueFull(depth, retry)
        if self.shed_on_dry_kv:
            dry = []
            for i in eligible:
                free = snaps[i].get("kv_pages_free")
                if free is None:
                    dry = []
                    break  # not a paged tier: pages never the gate
                need = self.replicas[i].pages_needed(
                    int(ids.size), int(max_new_tokens))
                dry.append(free < (need or 0)
                           and int(snaps[i].get("queue_depth", 0)) > 0)
            if dry and all(dry):
                retry = _min_retry()
                self._count("shed")
                self._count("shed_kv")
                self.metrics.event("-shed-", "shed", kind="kv",
                                  depth=depth, retry_after_s=retry)
                raise QueueFull(depth, retry)

        # ---- ordering: least-loaded, affinity-first, or spray -------
        scores = {i: int(snaps[i].get("queue_depth", 0))
                  + int(snaps[i].get("running", 0)) for i in eligible}
        # decode placement tie-break on PAGE HEADROOM (ISSUE 14): at
        # equal load, the replica with the most free pages hosts the
        # decode — that is the resource a decode-class replica sells
        order = sorted(
            eligible,
            key=lambda i: (scores[i],
                           -int(snaps[i].get("kv_pages_free") or 0),
                           i))
        affinity_used = False
        keys: List[bytes] = []
        if self._placement == "spray":
            import zlib

            j = zlib.crc32(ids.tobytes()) % len(order)
            order = sorted(eligible)[j:] + sorted(eligible)[:j]
        elif self.affinity_ps is not None and ids.size > 1:
            keys = chunk_keys(ids[: ids.size - 1], self.affinity_ps)
            with self._lock:
                tgt = None
                for j in range(len(keys) - 1, -1, -1):
                    tgt = self._affinity.get(keys[j])
                    if tgt is not None:
                        break
                if keys:
                    # hot-head accounting (ISSUE 15): the deepest
                    # chain this prompt exercises, with its covering
                    # token prefix — what a rollout replays onto a
                    # freshly swapped replica to rebuild prefix warmth
                    head = keys[-1]
                    rec = self._hot.get(head)
                    if rec is None:
                        self._hot[head] = rec = {
                            "count": 0,
                            "tokens": np.asarray(
                                ids[: len(keys) * self.affinity_ps],
                                np.int32),
                        }
                    rec["count"] += 1
                    self._hot.move_to_end(head)
                    while len(self._hot) > self._hot_cap:
                        self._hot.popitem(last=False)
            if tgt is not None and tgt in eligible:
                if scores[tgt] <= scores[order[0]] + self.affinity_slack:
                    order.remove(tgt)
                    order.insert(0, tgt)
                    affinity_used = True
                else:
                    self._count("affinity_spills")

        # ---- two-phase placement (ISSUE 14) -------------------------
        # the decode HOME is order[0] (affinity + load + headroom);
        # whether the PROMPT PASS runs there too is a second decision:
        # when the tier is disaggregated and the home's estimated
        # uncached suffix is long enough to be worth shipping pages,
        # the prefill goes to a prefill-class replica and the chain
        # follows the request to its decode home over the wire
        do_transfer = False
        if self.disaggregated and self._placement != "spray":
            # version fence (ISSUE 15): a chain exported by a replica
            # on a DIFFERENT model version is garbage for the decode
            # home — mid-rollout, transfers only cross same-version
            # pairs; everything else local-prefills (tokens identical)
            home_v = self._snap_version(snaps[order[0]])
            pf_live = [i for i in live if i in self._prefill_set
                       and not snaps[i].get("closed")
                       and i not in standby
                       and self._snap_version(snaps[i]) == home_v]
            if pf_live:
                cached_tokens = 0
                if keys:
                    tgt0 = order[0]
                    with self._lock:
                        for j, k in enumerate(keys):
                            if self._affinity.get(k) != tgt0:
                                break
                            cached_tokens = (j + 1) * self.affinity_ps
                uncached = int(ids.size) - cached_tokens
                do_transfer = uncached >= self.transfer_min_tokens

        # ---- tier-global directory pull (ISSUE 16) ------------------
        # the home is picked as above; when the DIRECTORY knows a
        # different live replica holds the prefix ≥ transfer_min_tokens
        # deeper than anything the home has (resident or spilled), the
        # chain is PULLED from that holder over offer_chain instead of
        # recomputed — the request routes to any replica that can
        # import its chain, not just the one that computed it
        do_pull = False
        pull_src: Optional[int] = None
        pull_tokens: Optional[np.ndarray] = None
        if (self.tier_directory and not do_transfer
                and self._placement != "spray" and keys):
            home0 = order[0]
            home_v = self._snap_version(snaps[home0])
            with self._lock:
                cached_tokens = 0
                for j, k in enumerate(keys):
                    ent = self._directory.get(k)
                    if not (self._affinity.get(k) == home0
                            or (ent is not None and home0 in ent)):
                        break
                    cached_tokens = (j + 1) * self.affinity_ps
                for j in range(len(keys) - 1, -1, -1):
                    covered = (j + 1) * self.affinity_ps
                    if (covered - cached_tokens
                            < self.transfer_min_tokens):
                        break  # shallower coverage only shrinks it
                    ent = self._directory.get(keys[j])
                    if not ent:
                        continue
                    # holders must be live, open, same model version
                    # (a chain under other weights is garbage — the
                    # ISSUE 15 version fence); standby holders DO
                    # donate (alive, just taking no placements)
                    hold = [i for i in sorted(ent)
                            if i != home0 and i in snaps
                            and not snaps[i].get("closed")
                            and self._snap_version(snaps[i]) == home_v]
                    if hold:
                        do_pull = True
                        pull_src = hold[0]
                        pull_tokens = ids[:covered]
                        break

        # ---- place ---------------------------------------------------
        bucket = self.replicas[order[0]].bucket_of(int(ids.size))
        with self._lock:
            self._seq += 1
            rid = request_id or f"rt-{self._seq}"
        last_qf: Optional[QueueFull] = None
        saw_closed = False
        placed: Optional[int] = None
        # counter-read → place → counter-commit is ONE critical
        # section (_place_lock): the tier-global per-bucket stream
        # pinning hands this submission EXACTLY the id a single
        # scheduler with the same slot count would — concurrent
        # submits must serialize here or two racers share an id (same
        # sampling stream) and every later id desyncs from the parity
        # sequence. The counter advances only on successful placement,
        # like the single scheduler's.
        with self._place_lock:
            with self._lock:
                n = self._admit_counts.get(bucket, 0)
            stream_id = n % self.slots
            rr = RouterRequest(
                self, rid, ids, int(max_new_tokens), stream_id, bucket,
                None if deadline_s is None else self.clock() + deadline_s,
                stream_cb,
            )
            rr.speculate = bool(speculate)
            rr.pin_version = (None if pin_version is None
                              else str(pin_version))
            rr.ts_arrival = self.clock()
            # transfer-overlap contract (ISSUE 14): a transferred
            # request submits to its decode home IMMEDIATELY, gated on
            # the transfer id — it keeps its FIFO position there while
            # the prompt pass runs on the prefill replica and the
            # chain's chunks stream in between that replica's decode
            # segments; admission lands the boundary the last chunk
            # does (or falls back to a local prefill if anything on
            # the prefill path breaks — fail_transfer unblocks it)
            await_tid = (f"{rid}.tx" if (do_transfer or do_pull)
                         else None)
            # keyword added only when set: non-transferring tiers keep
            # the PR 8 replica signature (duck-typed backends/fakes)
            extra = ({"await_transfer": await_tid}
                     if await_tid is not None else {})
            for idx in order:
                rep = self.replicas[idx]
                cb = rr._make_cb()
                try:
                    inner = rep.submit(
                        ids, int(max_new_tokens), deadline_s=deadline_s,
                        stream_cb=cb, request_id=rid,
                        stream_id=stream_id, speculate=rr.speculate,
                        **extra,
                    )
                except QueueFull as e:
                    last_qf = e
                    continue
                except SchedulerClosed:
                    saw_closed = True
                    continue
                rr._bind(idx, inner)
                if do_transfer:
                    rr._transfer = {"phase": "prefill", "tid": await_tid,
                                    "prefill": None, "pf_req": None}
                elif do_pull:
                    rr._transfer = {"phase": "pull", "tid": await_tid,
                                    "prefill": pull_src, "pf_req": None}
                with self._lock:
                    self._admit_counts[bucket] = n + 1
                    self._inflight[rid] = rr
                    self.placements[rep.name] = (
                        self.placements.get(rep.name, 0) + 1)
                    if keys:
                        for k in keys:
                            self._affinity[k] = idx
                            self._affinity.move_to_end(k)
                        while len(self._affinity) > self._affinity_cap:
                            self._affinity.popitem(last=False)
                        if self.tier_directory:
                            self._directory_put_locked(keys, idx,
                                                       "resident")
                placed = idx
                break
        if placed is not None:
            self._count("placed")
            if affinity_used and placed == order[0]:
                self._count("affinity_hits")
            self.metrics.event(rid, "placed",
                              replica=self.replicas[placed].name,
                              stream_id=stream_id, bucket=bucket,
                              affinity=bool(affinity_used
                                            and placed == order[0]),
                              transfer=bool(do_transfer),
                              depth=scores.get(placed, 0))
            if do_transfer:
                self._begin_transfer(rr, pf_live, keys)
            elif do_pull:
                self._begin_pull(rr, pull_src, pull_tokens, await_tid)
            return rr
        # every eligible replica said no. If every refusal was a
        # drain/stop that landed after the eligibility snapshot, this
        # is the drain contract's 503 (go elsewhere), NOT a 429
        # (retry here) — a 429 would tell the LB to retry into a
        # draining tier.
        if last_qf is None and saw_closed:
            raise SchedulerClosed("every replica is draining or closed")
        retry = _min_retry()
        if last_qf is not None:
            retry = min(retry, last_qf.retry_after_s)
        self._count("rejected")
        self.metrics.event("-rejected-", "reject", depth=depth,
                          retry_after_s=retry)
        raise QueueFull(depth, retry)

    def cancel(self, request) -> bool:
        """Cancel by :class:`RouterRequest` or id (any replica)."""
        rr = request
        if not isinstance(rr, RouterRequest):
            with self._lock:
                rr = self._inflight.get(str(request))
        if rr is None:
            return False
        with rr._lock:
            rr.client_cancelled = True
            inner, idx = rr._inner, rr._replica_idx
            tx = rr._transfer
        if inner is None or idx < 0:
            if tx is not None:
                # mid-transfer: best-effort cancel of the prefill leg;
                # the transfer machinery surfaces the terminal when it
                # next touches this request (client_cancelled gates
                # every forward step)
                pf_idx, pf_req = tx.get("prefill"), tx.get("pf_req")
                if pf_idx is not None and pf_req is not None:
                    try:
                        self.replicas[pf_idx].cancel(pf_req)
                    except Exception:
                        pass
                return True
            return False
        try:
            return self.replicas[idx].cancel(inner)
        except Exception:
            return False

    def retry_after_s(self) -> float:
        vals = []
        for i in self._live_indices():
            try:
                vals.append(float(self.replicas[i].retry_after_s()))
            except Exception:
                pass
        return min(vals) if vals else 1.0

    def _on_request_done(self, rr: RouterRequest) -> None:
        with self._lock:
            self._inflight.pop(rr.id, None)

    # ---- prefill/decode transfers (ISSUE 14) ------------------------
    def _begin_transfer(self, rr: RouterRequest,
                        pf_candidates: List[int],
                        keys: List[bytes]) -> None:
        """Phase 1: run the prompt pass on a prefill-class replica.
        Prefill placement is its own affinity+load decision (a
        repeated prefix exports from the prefill replica's OWN tree
        without recomputing); every rejection falls through to the
        next candidate, and total rejection falls back to a local
        prefill on the decode home — tokens identical either way."""
        snaps = {i: self._safe_snapshot(i) for i in pf_candidates}
        open_pf = [i for i in pf_candidates
                   if not snaps[i].get("closed")]
        if not open_pf:
            return self._abort_transfer(
                rr, "no open prefill replica", claim=True)
        pf_scores = {i: int(snaps[i].get("queue_depth", 0))
                     + int(snaps[i].get("running", 0))
                     for i in open_pf}
        order = sorted(open_pf, key=lambda i: (pf_scores[i], i))
        if keys:
            with self._lock:
                tgt = None
                for j in range(len(keys) - 1, -1, -1):
                    tgt = self._pf_affinity.get(keys[j])
                    if tgt is not None:
                        break
            if (tgt in pf_scores
                    and pf_scores[tgt] <= pf_scores[order[0]]
                    + self.affinity_slack):
                order.remove(tgt)
                order.insert(0, tgt)

        def on_pf(inner, new, finished):
            if finished:
                self._finish_transfer(rr, inner)

        for idx in order:
            rep = self.replicas[idx]
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer["prefill"] = idx
            try:
                pf_req = rep.submit_prefill(
                    rr.prompt_ids, stream_cb=on_pf,
                    request_id=f"{rr.id}.pf")
            except Exception:
                continue
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer["pf_req"] = pf_req
            with self._lock:
                if keys:
                    for k in keys:
                        self._pf_affinity[k] = idx
                        self._pf_affinity.move_to_end(k)
                    while len(self._pf_affinity) > self._affinity_cap:
                        self._pf_affinity.popitem(last=False)
            self.metrics.event(rr.id, "prefill_placed",
                              replica=rep.name)
            return
        self._abort_transfer(rr, "every prefill replica rejected",
                             claim=True)

    def _finish_transfer(self, rr: RouterRequest, pf_req) -> None:
        """Phase 2 (fires on the prefill replica's completion): stream
        the exported chain to the request's decode home — where it
        already sits QUEUED at its FIFO position, gated on the
        transfer id — in ``transfer_chunk_pages``-page chunks; its
        admission lands the boundary the last chunk does, as a prefix
        hit. Any breakage aborts the transfer instead: the decode home
        runs the prefill locally, tokens identical."""
        from tpuflow.serve.pages import split_chain

        if not rr._claim_transfer("prefill", "landing"):
            return  # a maintenance sweep already aborted this one
        with rr._lock:
            tid = (rr._transfer or {}).get("tid")
        d_idx = rr.replica
        wire = getattr(pf_req, "export", None)
        if (pf_req.state is not RequestState.DONE or wire is None
                or d_idx < 0 or tid is None):
            return self._abort_transfer(
                rr, f"prefill failed: "
                    f"{pf_req.error or pf_req.state.value}")
        rep = self.replicas[d_idx]
        try:
            chunks = split_chain(wire, self.transfer_chunk_pages)
            for j, ch in enumerate(chunks):
                rep.offer_chain(ch, transfer_id=tid,
                                last=(j == len(chunks) - 1))
            if not chunks:
                # nothing cacheable to ship (sub-page prompt): unblock
                # the waiting admission rather than time it out
                return self._abort_transfer(rr, "empty chain")
        except Exception as e:
            return self._abort_transfer(rr, repr(e))
        with rr._lock:
            if rr._transfer is not None:
                rr._transfer["phase"] = "decode"
        self._count("transfers")
        self.metrics.event(
            rr.id, "transfer",
            pages=int(wire.get("n_pages", 0)),
            bytes=sum(len(p) for p in wire.get("payloads", ())),
            to_replica=rep.name)

    def _abort_transfer(self, rr: RouterRequest, reason: str,
                        claim: bool = False) -> None:
        """The prefill path broke (rejected everywhere, dead replica,
        corrupt/empty export): tell the decode home to stop waiting —
        its ``fail_transfer`` releases the request to a LOCAL prefill
        at its next boundary. Purely a lost optimization: the pinned
        stream id makes the tokens identical."""
        if claim and not rr._claim_transfer("prefill", "landing"):
            return
        with rr._lock:
            tid = (rr._transfer or {}).get("tid")
        self._count("transfer_fallbacks")
        self.metrics.event(rr.id, "transfer_fallback", reason=reason)
        d_idx = rr.replica
        if d_idx >= 0 and tid is not None:
            try:
                self.replicas[d_idx].fail_transfer(tid, reason)
            except Exception:
                pass

    # ---- tier-global directory pulls (ISSUE 16) ---------------------
    def _begin_pull(self, rr: RouterRequest, src_idx: int,
                    tokens: np.ndarray, tid: str) -> None:
        """Directory-routed cross-replica pull: ask the holder for its
        chain (answered at ITS next scheduler boundary — resident
        re-export or spill-pool read, whichever is deeper) and stream
        the wire to the request's decode home in transfer chunks over
        the same ``offer_chain``/``await_transfer`` machinery a
        disaggregated prefill transfer rides. The request already sits
        QUEUED at the home gated on ``tid``; any fault on this path
        fail_transfers it into a LOCAL prefill — tokens identical
        either way."""
        from tpuflow.serve.pages import split_chain

        src = self.replicas[src_idx]

        def _fallback(reason: str) -> None:
            self._count("pull_fallbacks")
            self.metrics.event(rr.id, "pull_fallback", reason=reason,
                              from_replica=src.name)
            d = rr.replica
            if d >= 0:
                try:
                    self.replicas[d].fail_transfer(tid, reason)
                except Exception:
                    pass

        def on_ready(wire) -> None:
            if not rr._claim_transfer("pull", "landing"):
                return  # a maintenance sweep already aborted this one
            d_idx = rr.replica
            if wire is None or not wire.get("n_pages"):
                return _fallback("holder had nothing to export")
            if d_idx < 0 or d_idx == src_idx:
                # failover rebound the request onto the holder itself:
                # its own plan() promotes locally, no wire needed
                return _fallback("request landed on the holder")
            try:
                chunks = split_chain(wire, self.transfer_chunk_pages)
                for j, ch in enumerate(chunks):
                    self.replicas[d_idx].offer_chain(
                        ch, transfer_id=tid,
                        last=(j == len(chunks) - 1))
            except Exception as e:
                return _fallback(repr(e))
            with rr._lock:
                if rr._transfer is not None:
                    rr._transfer["phase"] = "decode"
            self._count("pulls")
            with self._lock:
                self._directory_put_locked(
                    [bytes.fromhex(h) for h in
                     wire.get("chunk_keys", ())],
                    d_idx, "resident")
            self.metrics.event(
                rr.id, "pull",
                pages=int(wire.get("n_pages", 0)),
                bytes=sum(len(p) for p in wire.get("payloads", ())),
                from_replica=src.name,
                to_replica=self.replicas[d_idx].name)

        try:
            src.request_chain(tokens, on_ready)
        except Exception as e:
            if rr._claim_transfer("pull", "landing"):
                _fallback(repr(e))

    def directory_sweep(self) -> int:
        """Merge every live replica's spilled-chain report into the
        directory (the resident entries placement already wrote).
        Rides :meth:`maintain`; returns rows merged."""
        merged = 0
        for idx in self._live_indices():
            rep = self.replicas[idx]
            report = getattr(rep, "kv_chain_report", None)
            if report is None:
                continue
            try:
                chains = report()
            except Exception:
                continue
            for ch in chains or ():
                try:
                    keys = [bytes.fromhex(h) for h in ch["keys"]]
                    tier = str(ch.get("tier", "host"))
                except (KeyError, TypeError, ValueError):
                    continue
                with self._lock:
                    self._directory_put_locked(keys, idx, tier)
                merged += 1
        return merged

    # ---- deployment plane (ISSUE 15) --------------------------------
    @staticmethod
    def _snap_version(snap: Dict[str, Any]) -> Optional[str]:
        """The comparable version label out of a load snapshot — ONE
        normalization (serve.deploy.version_label) shared with the
        deployment plane, so pin_version placement and the disagg
        version fence can never drift from what a rollout records."""
        from tpuflow.serve.deploy import version_label

        return version_label(snap.get("model_version"))

    def replica_version(self, idx: int, target: str = "model"):
        """One replica's current model (or draft) version, as its
        load snapshot reports it."""
        snap = self._safe_snapshot(idx)
        return snap.get("draft_version" if target == "draft"
                        else "model_version")

    def versions(self) -> Dict[str, Optional[str]]:
        """``{replica_name: version label}`` across the tier — the
        mid-rollout mix at a glance."""
        return {self.replicas[i].name: self._snap_version(
                    self._safe_snapshot(i))
                for i in range(len(self.replicas))}

    def standby_indices(self) -> List[int]:
        with self._lock:
            return sorted(self._standby)

    def active_indices(self) -> List[int]:
        """Replicas currently taking traffic (live, not standby, not
        retiring) — the set a rollout must move to the new version."""
        with self._lock:
            failed = set(self._failed)
            out = [i for i in range(len(self.replicas))
                   if i not in failed and i not in self._standby
                   and i not in self._retiring]
        return out

    def set_standby(self, idx: int) -> None:
        """Park a replica as standby (no placement until
        :meth:`activate`)."""
        with self._lock:
            self._standby.add(int(idx))

    def activate(self, idx: int) -> None:
        """Standby → active: the replica joins placement (least-
        loaded, so traffic shifts to it naturally) — the blue half of
        the blue/green shift."""
        with self._lock:
            self._standby.discard(int(idx))
            self._retiring.discard(int(idx))
            self._failed.pop(int(idx), None)
        self.metrics.event("-deploy-", "replica_activated",
                           replica=self.replicas[idx].name)

    def begin_retire(self, idx: int) -> None:
        """Active → retiring: drain the replica (its admitted backlog
        finishes — zero truncated streams; new submits already route
        elsewhere because its snapshot reads closed)."""
        with self._lock:
            self._retiring.add(int(idx))
        try:
            self.replicas[idx].drain()
        except Exception:
            pass
        self.metrics.event("-deploy-", "replica_retiring",
                           replica=self.replicas[idx].name)

    def retire(self, idx: int) -> None:
        """Give up on a retiring replica (wedged drain): excluded
        from placement like any failed replica, never recycled."""
        with self._lock:
            self._retiring.discard(int(idx))
        self.mark_failed(idx, reason="retired (deploy)")

    def recycle_as_standby(self, idx: int) -> None:
        """Drained-out replica → the next rollout's standby."""
        with self._lock:
            self._retiring.discard(int(idx))
            self._standby.add(int(idx))
            self._failed.pop(int(idx), None)
        self.metrics.event("-deploy-", "replica_recycled",
                           replica=self.replicas[idx].name)

    def hot_heads(self, n: int = 8) -> List[np.ndarray]:
        """The ``n`` hottest chain-head token prefixes the tier has
        seen (by placement count) — the rollout's replay source: a
        version bump invalidates cached KV, so warmth on the incoming
        replica is rebuilt by RE-PREFILLING these, never by
        transferring stale pages."""
        with self._lock:
            recs = sorted(self._hot.values(),
                          key=lambda r: -int(r["count"]))[: max(0, int(n))]
            return [np.array(r["tokens"], np.int32) for r in recs]

    def is_online(self) -> bool:
        """Whether the online maintenance thread is running (the
        rollout manager starts freshly swapped replicas' loops only
        on online tiers)."""
        return self._thread is not None and self._thread.is_alive()

    # ---- failover (maintenance) -------------------------------------
    def mark_failed(self, replica: "int | str", reason: str = "") -> None:
        """Exclude a replica from placement and make its queued
        requests failover-eligible (also the operator's manual lever —
        the watchdog path calls it from :meth:`maintain`)."""
        idx = replica
        if not isinstance(idx, int):
            idx = next(i for i, r in enumerate(self.replicas)
                       if r.name == replica)
        with self._lock:
            if idx in self._failed:
                return
            self._failed[idx] = reason or "marked failed"
        self._count("replicas_failed")
        self.metrics.event("-failover-", "replica_failed",
                          replica=self.replicas[idx].name, reason=reason)

    def maintain(self) -> bool:
        """One health/failover sweep: poll every live replica's
        :meth:`health`, fail the tripped/closed ones, resubmit their
        never-admitted requests elsewhere. Returns whether anything
        changed. The online maintenance thread calls this on a poll
        interval; offline drivers interleave it with replica steps."""
        progress = False
        for idx in self._live_indices():
            try:
                h = self.replicas[idx].health()
            except Exception as e:
                h = {"failed": True, "error": repr(e)}
            if h.get("failed"):
                self.mark_failed(idx, reason=str(
                    h.get("error")
                    or ("tripped" if h.get("tripped")
                        else "closed" if h.get("closed")
                        else "wedged-loop")))
                progress = True
        with self._lock:
            failed = dict(self._failed)
            pending = [rr for rr in self._inflight.values()
                       if rr._replica_idx in failed]
        for rr in pending:
            if rr._failover_pending():
                progress |= self._failover(rr)
        # ADMITTED work on a DEAD replica (closed / wedged loop — not
        # merely watchdog-tripped, whose loop keeps decoding and will
        # finish its rows) can neither complete nor be replayed
        # token-identically (tokens were already streamed): fail it to
        # the client now instead of hanging result() until the
        # client's own timeout and pinning idle()/drain() open forever
        for rr in pending:
            # re-read the CURRENT home: the failover loop above may
            # have just rebound this request to a healthy replica (and
            # its scheduler may already have admitted it) — acting on
            # the stale pre-failover index would cancel a perfectly
            # good resubmission
            if rr._replica_idx not in failed:
                continue
            why = failed.get(rr._replica_idx, "")
            if "tripped" in why or rr._done.is_set():
                continue
            if rr._failover_pending():
                continue  # queued: the next sweep retries placement
            inner = rr.inner
            if inner is not None and inner.ts_admitted is not None:
                try:
                    self.replicas[rr._replica_idx].cancel(inner)
                except Exception:
                    pass
                rr._finalize_failed(
                    "replica failed with this request mid-decode")
                progress = True
        # disaggregation sweep (ISSUE 14): transfers stranded on a
        # FAILED prefill replica abort, releasing their decode-home
        # admission to a local prefill (the completion callback is the
        # normal path — this is the safety net when a replica dies
        # without finalizing its prefill request)
        with self._lock:
            stranded = [rr for rr in self._inflight.values()
                        if rr._transfer is not None
                        and rr._transfer.get("phase") == "prefill"
                        and rr._transfer.get("prefill") in failed]
            # directory pulls stranded on a failed HOLDER (ISSUE 16):
            # same safety net, same fallback
            stranded_pulls = [rr for rr in self._inflight.values()
                              if rr._transfer is not None
                              and rr._transfer.get("phase") == "pull"
                              and rr._transfer.get("prefill") in failed]
        for rr in stranded:
            self._abort_transfer(rr, "prefill replica failed",
                                 claim=True)
            progress = True
        for rr in stranded_pulls:
            if rr._claim_transfer("pull", "landing"):
                self._count("pull_fallbacks")
                d = rr.replica
                if d >= 0:
                    tid = (rr._transfer or {}).get("tid")
                    try:
                        self.replicas[d].fail_transfer(
                            tid, "pull holder failed")
                    except Exception:
                        pass
                progress = True
        if self.tier_directory:
            self.directory_sweep()
        from tpuflow.obs.gauges import set_gauge

        set_gauge("router.replicas", float(len(self.replicas)))
        set_gauge("router.replicas_failed", float(len(failed)))
        # deployment hook (ISSUE 15): an active rollout's state
        # machine advances on the same cadence as health/failover
        for hook in list(self.on_maintain):
            try:
                hook()
            except Exception:
                pass
        return progress

    def _failover(self, rr: RouterRequest) -> bool:
        """Resubmit one never-admitted request off its failed replica.
        Token-identity: the pinned ``stream_id`` travels with it, and
        nothing had been produced (the candidate test guarantees it)."""
        with rr._lock:
            old_idx, old_inner = rr._replica_idx, rr._inner
        # decode-capable candidates only: a prefill-class replica must
        # never inherit a decode through failover either; standby
        # replicas take no traffic, and a version-pinned request only
        # moves to a replica serving exactly that version (ISSUE 15)
        with self._lock:
            standby = set(self._standby)
        candidates = [i for i in self._live_indices()
                      if i != old_idx and i not in self._prefill_set
                      and i not in standby]
        snaps = {i: self._safe_snapshot(i) for i in candidates}
        if rr.pin_version is not None:
            candidates = [i for i in candidates
                          if self._snap_version(snaps[i])
                          == rr.pin_version]
        order = sorted(
            (i for i in candidates if not snaps[i].get("closed")),
            key=lambda i: (int(snaps[i].get("queue_depth", 0))
                           + int(snaps[i].get("running", 0)), i),
        )
        if not order:
            if not self._accepting_failover() or not candidates:
                rr._finalize_failed(
                    "replica failed and no replica left to resubmit to")
            return False
        now = self.clock()
        deadline_s = (None if rr.deadline_ts is None
                      else max(0.0, rr.deadline_ts - now))
        for idx in order:
            rep = self.replicas[idx]
            cb = rr._make_cb()  # invalidates the old generation FIRST
            try:
                inner = rep.submit(
                    rr.prompt_ids, rr.max_new_tokens,
                    deadline_s=deadline_s, stream_cb=cb,
                    request_id=rr.id, stream_id=rr.stream_id,
                    speculate=rr.speculate,
                )
            except (QueueFull, SchedulerClosed):
                continue
            if rr.ts_arrival is not None:
                inner.ts_arrival = rr.ts_arrival
            rr._bind(idx, inner)
            rr.resubmits += 1
            with self._lock:
                self.placements[rep.name] = (
                    self.placements.get(rep.name, 0) + 1)
            self._count("failovers")
            self.metrics.event(rr.id, "failover",
                              from_replica=self.replicas[old_idx].name,
                              to_replica=rep.name,
                              stream_id=rr.stream_id)
            if old_inner is not None:
                try:  # best-effort: the old home may be long dead
                    self.replicas[old_idx].cancel(old_inner)
                except Exception:
                    pass
            return True
        return False  # nowhere to go right now; retried next sweep

    # ---- drain / lifecycle ------------------------------------------
    def drain(self, wait_s: Optional[float] = None) -> None:
        """Tier-wide graceful drain: 503 new submits, drain every
        replica (each finishes its admitted backlog), flip ``/readyz``,
        annotate the flight manifest. Non-blocking unless ``wait_s``."""
        with self._lock:
            first = not self._draining
            self._draining = True
        if first:
            from tpuflow.obs import flight as _flight
            from tpuflow.obs.gauges import set_gauge

            self._count("drains")
            set_gauge("router.draining", 1.0)
            depth = sum(int(self._safe_snapshot(i).get("queue_depth", 0))
                        for i in self._live_indices())
            self.metrics.event("-router-", "drain", queue_depth=depth)
            _flight.annotate("router.drain", {
                "ts": self.clock(),
                "queue_depth": depth,
                "inflight": len(self._inflight),
                "replicas": [self.replicas[i].name
                             for i in self._live_indices()],
            })
            for i in self._live_indices():
                try:
                    self.replicas[i].drain()
                except Exception:
                    pass
        if wait_s is not None:
            deadline = time.time() + wait_s
            while not self.idle() and time.time() < deadline:
                time.sleep(0.01)

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        return self._draining and self.idle()

    def idle(self) -> bool:
        with self._lock:
            if self._inflight:
                return False
        return all(self.replicas[i].idle() for i in self._live_indices())

    def start(self, poll_s: float = 0.25) -> None:
        """Online drive: start every replica's loop plus the router's
        maintenance thread (health polling → failover)."""
        for i in self._live_indices():
            self.replicas[i].start()
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.is_set():
                try:
                    self.maintain()
                except Exception:
                    pass
                self._stop_evt.wait(poll_s)

        self._thread = threading.Thread(
            target=loop, name="tpuflow-router", daemon=True)
        self._thread.start()

    def run_until_idle(self) -> None:
        """Offline drive: step every live replica and the maintenance
        sweep on the calling thread until nothing makes progress (the
        single-scheduler ``run_until_idle`` contract, tier-wide)."""
        while True:
            progress = False
            for i in self._live_indices():
                rep = self.replicas[i]
                if not rep.idle():
                    progress |= bool(rep.step())
            progress |= self.maintain()
            if not progress:
                return

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        deadline = time.time() + timeout
        if drain:
            self.drain(wait_s=timeout)
        with self._lock:
            self._closed = True
            self._draining = True
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=max(0.1, deadline - time.time()))
        for i in range(len(self.replicas)):
            try:
                self.replicas[i].stop(
                    drain=drain,
                    timeout=max(0.1, deadline - time.time()))
            except Exception:
                pass
        with self._lock:
            leftovers = list(self._inflight.values())
        for rr in leftovers:
            rr._finalize_failed("router stopped")

    # ---- introspection ----------------------------------------------
    def readiness(self) -> Dict[str, Any]:
        """Tier ``/readyz``: ready while the router is open and at
        least one live replica is ready; per-replica detail rides in
        the body so the probe's reason is in the probe."""
        per: Dict[str, Any] = {}
        ready_n = 0
        depth = 0
        with self._lock:
            failed = dict(self._failed)
            draining, closed = self._draining, self._closed
            standby = set(self._standby)
            retiring = set(self._retiring)
        for i, rep in enumerate(self.replicas):
            try:
                r = rep.readiness()
            except Exception as e:
                r = {"ready": False, "error": repr(e)}
            snap = self._safe_snapshot(i)
            depth += int(snap.get("queue_depth", 0))
            ok = bool(r.get("ready")) and i not in failed
            ready_n += ok
            per[rep.name] = {
                "ready": ok,
                "failed": failed.get(i),
                "class": self.classes[i],
                "standby": i in standby,
                "retiring": i in retiring,
                "model_version": self._snap_version(snap),
                "queue_depth": snap.get("queue_depth"),
                "running": snap.get("running"),
                "draining": snap.get("draining"),
            }
        # a disaggregated tier with only its prefill replicas ready
        # cannot serve a single token — readiness needs a DECODE home
        # that is actually TAKING traffic (standby replicas don't)
        decode_ready = sum(
            1 for i, rep in enumerate(self.replicas)
            if i in set(self._decode_set)
            and i not in standby
            and per[rep.name]["ready"])
        return {
            "ready": bool(decode_ready) and not (draining or closed),
            "closed": closed,
            "draining": draining,
            "replicas_ready": ready_n,
            "queue_depth": depth,
            "running": sum(int(p.get("running") or 0)
                           for p in per.values()),
            "replicas": per,
        }

    def snapshot(self) -> Dict[str, float]:
        """Router-tier gauges/counters as a flat dotted dict."""
        with self._lock:
            out = {f"router.{k}": float(v) for k, v in self.counts.items()}
            out["router.inflight"] = float(len(self._inflight))
            out["router.replicas"] = float(len(self.replicas))
            out["router.replicas_live"] = float(
                len(self.replicas) - len(self._failed))
            out["router.replicas_standby"] = float(len(self._standby))
            out["router.replicas_retiring"] = float(len(self._retiring))
            out["router.affinity_table"] = float(len(self._affinity))
            if self.tier_directory:
                out["router.directory_table"] = float(
                    len(self._directory))
            for name, n in self.placements.items():
                out[f"router.placements.{name}"] = float(n)
        return out

    def metrics_snapshot(self) -> Dict[str, Any]:
        """The tier's ``/v1/metrics`` body: every replica's snapshot
        (their per-replica gauge prefixes keep them apart) plus the
        router's own counters and the aggregate queue depth."""
        snap: Dict[str, Any] = {}
        for i in range(len(self.replicas)):
            try:
                snap.update(self.replicas[i].metrics_snapshot())
            except Exception:
                pass
        snap.update(self.snapshot())
        snap["router.queue_depth"] = float(sum(
            int(self._safe_snapshot(i).get("queue_depth", 0))
            for i in self._live_indices()))
        return snap

    def load_snapshot(self) -> Dict[str, Any]:
        """Tier-aggregate load sensor (an LB in front of SEVERAL
        routers composes the same way replicas compose under one)."""
        per = {i: self._safe_snapshot(i) for i in self._live_indices()}
        with self._lock:
            closed, draining = self._closed, self._draining
        out: Dict[str, Any] = {
            "queue_depth": sum(int(s.get("queue_depth", 0))
                               for s in per.values()),
            "running": sum(int(s.get("running", 0))
                           for s in per.values()),
            "closed": closed,
            "draining": draining,
            "replicas": {self.replicas[i].name: s
                         for i, s in per.items()},
        }
        frees = [s.get("kv_pages_free") for s in per.values()]
        if frees and all(f is not None for f in frees):
            out["kv_pages_free"] = int(sum(frees))
        return out

    def flight_snapshot(self) -> Dict[str, Any]:
        """The flight recorder's ``router.json`` section."""
        with self._lock:
            inflight = [
                {"id": rr.id, "replica": rr._replica_idx,
                 "state": (rr._inner.state.value
                           if rr._inner is not None
                           else "transfer:" + str(
                               (rr._transfer or {}).get("phase", "?"))),
                 "resubmits": rr.resubmits,
                 "orphaned": rr._orphaned}
                for rr in self._inflight.values()
            ]
            failed = {self.replicas[i].name: why
                      for i, why in self._failed.items()}
            counts = dict(self.counts)
            draining, closed = self._draining, self._closed
            standby = [self.replicas[i].name for i in self._standby]
            retiring = [self.replicas[i].name for i in self._retiring]
        # ONE snapshot fetch per replica: versions derive from the
        # same snaps (an HTTP replica pays a round-trip per fetch)
        snaps = {self.replicas[i].name: self._safe_snapshot(i)
                 for i in range(len(self.replicas))}
        return {
            "draining": draining,
            "closed": closed,
            "failed": failed,
            "counts": counts,
            "standby": standby,
            "retiring": retiring,
            "versions": {name: self._snap_version(s)
                         for name, s in snaps.items()},
            "placements": dict(self.placements),
            "replicas": snaps,
            "inflight": inflight,
        }
