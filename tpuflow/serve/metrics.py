"""Serving observability: per-request latency, pool efficiency, events.

Three surfaces, all fed by the scheduler thread:

- **latency histograms** — TTFT, queue wait, decode latency, end-to-end
  per finished request, summarized as p50/p95/p99 (the numbers
  ``bench.py --serve`` A/Bs against wave draining). These are
  :class:`tpuflow.obs.gauges.Histogram` instances (ISSUE 4): fixed
  log-spaced buckets, O(1) memory forever — the per-module percentile
  math and sliding sample windows this file used to carry are gone;
- **pool gauges** — slot occupancy and batch efficiency (live rows /
  slot rows per decode segment: the fraction of the fixed-shape batch
  doing useful work — the quantity slot-level scheduling exists to
  raise), published through :mod:`tpuflow.obs.gauges` so
  ``sample_system_metrics`` and run-metric logging pick them up like
  any host/device metric;
- **a structured event log per request id** — submit/admit/first-token/
  finish/reject/cancel/expire with timestamps, bounded to the most
  recent requests (a server must not grow without limit). Request ids
  double as TRACE ids in :mod:`tpuflow.obs.trace`, so these events and
  the request's spans describe the same lifecycle.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from tpuflow.obs.gauges import (
    Histogram,
    inc_counter,
    register_histogram,
    set_gauge,
)
from tpuflow.serve.request import Request


# SLO phase-attribution vector (ISSUE 19): every finished request folds
# its stamped timeline into exactly these phases (Request.phases()), so
# the per-phase histograms partition e2e latency — summing the phase
# means reconstructs the mean e2e, and a fault in one stage (slow
# transfer wire, placement stall) shows up as ITS phase dominating.
PHASES = ("queue_wait", "place", "transfer", "prefill",
          "first_decode", "decode_steady")
# The pre-first-token subset: these phases partition TTFT the same way
# (serve.ttft_breakdown.* — the sensor ROADMAP item 3's control loop
# reads to learn WHICH phase is burning the TTFT budget).
TTFT_PHASES = ("queue_wait", "place", "transfer", "prefill",
               "first_decode")


def percentiles(values: List[float],
                pcts=(50.0, 95.0, 99.0)) -> Dict[str, float]:
    """EXACT nearest-rank percentiles of a concrete sample list, keyed
    ``p50``/``p95``/... (empty input → empty dict). The aggregate
    histograms above quote bucket-resolution percentiles; this helper
    stays for callers holding the raw samples (bench's A/B)."""
    if not values:
        return {}
    import math

    s = sorted(values)
    out = {}
    for p in pcts:
        rank = max(0, min(len(s) - 1, math.ceil(p / 100.0 * len(s)) - 1))
        out[f"p{p:g}"] = s[rank]
    return out


def register_router_metrics() -> None:
    """Eagerly materialize the router hot-path histogram (ISSUE 17):
    ``router.place_ms`` is in the registry — hence on ``/metrics``
    and in the snapshot ring — from router CONSTRUCTION, not from the
    first placement, so a freshly deployed tier's dashboards don't
    read as a missing series. Idempotent: re-registering would zero
    an existing instance's counts, so one is kept if present."""
    from tpuflow.obs.gauges import get_histogram

    if get_histogram("router.place_ms") is None:
        register_histogram("router.place_ms", Histogram())


def _bounded_append(lst: list, value, cap: int) -> None:
    """Append keeping only the most recent ``cap`` entries — every
    per-request series here is a sliding window, never an unbounded
    log (the 'a server must not grow without limit' contract)."""
    lst.append(value)
    if len(lst) > cap:
        del lst[: len(lst) - cap]


def _safe_version_label(version) -> Optional[str]:
    """The served version as a registry-name-safe label, or None for a
    versionless scheduler. :func:`tpuflow.serve.deploy.version_label`
    already emits a safe alphabet (``step<N>-<crc8hex>``); anything
    else is sanitized so a hand-set version can't corrupt registry
    names or the Prometheus ``version=`` fold."""
    import re as _re

    label = (version.get("label") if isinstance(version, dict)
             else version)
    if label in (None, ""):
        return None
    return _re.sub(r"[^A-Za-z0-9_\-]", "-", str(label))


class _VersionCut:
    """One model version's metric cut (ISSUE 20): the hot
    request-outcome families recorded a SECOND time under
    ``<prefix>.version.<label>.*`` — TTFT/ITL, the phase vector,
    error/fallback counts, tokens served — so blue and green are
    directly comparable mid-rollout. Registered like the uncut
    families (Prometheus folds the marker into ``version="<label>"``,
    the snapshot ring windows them); counter mirrors feed
    :meth:`ServeMetrics.version_snapshot` for the canary scorer."""

    __slots__ = ("label", "prefix", "ttft_ms", "itl_ms", "phase_hists",
                 "requests_done", "requests_failed",
                 "transfer_fallbacks", "tokens_out")

    def __init__(self, base_prefix: str, label: str):
        self.label = label
        self.prefix = f"{base_prefix}.version.{label}"
        self.ttft_ms = register_histogram(
            f"{self.prefix}.ttft_ms", Histogram())
        self.itl_ms = register_histogram(
            f"{self.prefix}.itl_ms", Histogram())
        self.phase_hists = {
            ph: register_histogram(
                f"{self.prefix}.req_phase_ms.{ph}", Histogram())
            for ph in PHASES
        }
        self.requests_done = 0
        self.requests_failed = 0
        self.transfer_fallbacks = 0
        self.tokens_out = 0

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative counters + raw histogram states — the wire shape
        :meth:`Router.version_snapshot` sums across replicas and the
        canary scorer delta-differences per window."""
        return {
            "requests": self.requests_done + self.requests_failed,
            "done": self.requests_done,
            "failed": self.requests_failed,
            "transfer_fallbacks": self.transfer_fallbacks,
            "tokens_out": self.tokens_out,
            "hists": {
                "ttft_ms": self.ttft_ms.state(),
                "itl_ms": self.itl_ms.state(),
                **{f"req_phase_ms.{ph}": h.state()
                   for ph, h in self.phase_hists.items()},
            },
        }


class ServeMetrics:
    """Aggregate + per-request serving metrics (thread-safe).

    Memory is bounded on every axis: the latency histograms are
    fixed-bucket (O(#buckets) regardless of request count — no sliding
    window to tune), the event log keeps ``max_event_requests`` request
    ids and ``max_events_per_request`` entries per id — so shared ids
    (the ``-http-`` access log, a chatty client reusing one id) cannot
    grow without limit either.

    The histograms accumulate over the PROCESS lifetime and are
    REGISTERED in the process gauge registry (``<prefix>.ttft_ms``
    etc.), so the metrics plane's consumers all read the same
    instances: the Prometheus exposition (``GET /metrics``) renders
    their cumulative ``le`` buckets, the :mod:`tpuflow.obs.timeseries`
    snapshot ring delta-differences them into *windowed* percentiles,
    and :meth:`snapshot` quotes those windowed numbers as its primary
    ``_p50/_p95/_p99`` keys (cumulative kept under a ``_cum`` suffix)
    — closing the cumulative-vs-windowed trade this docstring used to
    document as the consumer's problem. Without a ticking ring the
    windowed view degenerates to cumulative (same keys, same values);
    :meth:`reset_latency` stays for hard restarts."""

    def __init__(self, max_event_requests: int = 512,
                 gauge_prefix: str = "serve",
                 max_events_per_request: int = 128,
                 max_version_cuts: int = 4):
        self._lock = threading.Lock()
        self.prefix = gauge_prefix
        self.max_events_per_request = max_events_per_request
        self.counts: Dict[str, int] = {
            "submitted": 0, "rejected": 0, "admitted": 0, "done": 0,
            "cancelled": 0, "expired": 0,
        }
        # request-FAILURE terminals (ISSUE 20): a finish with
        # ``req.error`` set — watchdog cancels, transfer aborts,
        # un-resumable evictions — as distinct from plain client
        # cancels. Feeds the windowed error rate placement and the
        # canary scorer read.
        self.requests_failed = 0
        # per-model_version metric cuts (ISSUE 20): bounded OrderedDict
        # label → _VersionCut, oldest evicted (registry names dropped)
        # beyond max_version_cuts — a long-lived server sees many
        # versions but only blue/green are ever comparands.
        self.version_label: Optional[str] = None
        self._active_cut: Optional[_VersionCut] = None
        self._version_cuts: "OrderedDict[str, _VersionCut]" = (
            OrderedDict())
        self._max_version_cuts = max(1, int(max_version_cuts))
        self.ttft_ms = register_histogram(
            f"{gauge_prefix}.ttft_ms", Histogram())
        self.queue_wait_ms = register_histogram(
            f"{gauge_prefix}.queue_wait_ms", Histogram())
        self.decode_ms = register_histogram(
            f"{gauge_prefix}.decode_ms", Histogram())
        self.e2e_ms = register_histogram(
            f"{gauge_prefix}.e2e_ms", Histogram())
        # inter-token latency (ISSUE 13): per-row segment-boundary
        # deltas normalized per emitted token — the metric the chunked-
        # prefill SLO knob (prefill_budget_tokens) trades the long
        # prompt's TTFT against. Registered like the others: Prometheus
        # buckets, /v1/metrics windowed p95, load_snapshot().
        self.itl_ms = register_histogram(
            f"{gauge_prefix}.itl_ms", Histogram())
        # KV-page wire transfers (ISSUE 14, prefill/decode
        # disaggregation): per-transfer wall (export serialize or
        # import verify+land), registered like the others — Prometheus
        # buckets, /v1/metrics windowed percentiles, load_snapshot()
        self.kv_transfer_ms = register_histogram(
            f"{gauge_prefix}.kv_transfer_ms", Histogram())
        # SLO phase attribution (ISSUE 19): one histogram per phase of
        # the fixed vector — every finished request observes into ALL
        # of them (0ms when a phase didn't apply), so the per-phase
        # counts stay aligned and the families partition e2e / TTFT.
        # Registered like the others: Prometheus buckets (folded under
        # a phase= label by obs/prom.py), windowed /v1/metrics
        # percentiles, and load_snapshot() p95s for the router.
        self.phase_hists = {
            ph: register_histogram(
                f"{gauge_prefix}.req_phase_ms.{ph}", Histogram())
            for ph in PHASES
        }
        self.ttft_breakdown = {
            ph: register_histogram(
                f"{gauge_prefix}.ttft_breakdown.{ph}", Histogram())
            for ph in TTFT_PHASES
        }
        self.tokens_out = 0
        self.segments = 0
        self.segment_live_rows = 0
        self.segment_slot_rows = 0
        self.queue_depth = 0
        # paged-KV counters (ISSUE 6): prefix-cache hit accounting and
        # prefill tokens the cache saved (KV positions NOT recomputed)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefill_tokens_saved = 0
        self.page_waits = 0
        # incremental page allocation (ISSUE 11): per-segment plan
        # growth events and out-of-pages mid-decode evictions (a row
        # requeued with its prefix published — the churn signal a
        # too-small store shows before anything actually fails)
        self.page_extends = 0
        self.mid_decode_evictions = 0
        # chunked prefill + ring offload (ISSUE 13)
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.ring_prefills = 0
        # KV-page wire transfers (ISSUE 14): pages/bytes shipped in
        # either direction, chain exports/imports, and verify failures
        # (CRC / header / gap / dry — each one a clean local-prefill
        # fallback, so a nonzero steady rate means a corrupting
        # transport, not corrupted outputs)
        self.kv_transfer_pages = 0
        self.kv_transfer_bytes = 0
        self.kv_exports = 0
        self.kv_imports = 0
        self.kv_transfer_failures = 0
        # tiered KV hierarchy (ISSUE 16): cumulative demote/promote
        # counts mirrored off the store's spill pool at each boundary
        # (on_kv delta-publishes them as registry counters too)
        self.kv_demotes = 0
        self.kv_promotes = 0
        # expert-parallel MoE serving (ISSUE 18): latest per-expert
        # segment load (list, gauge-mirrored), cumulative routed-token
        # count, hot-expert share, and admissions held by the
        # capacity gate — the three-surface contract (snapshot() →
        # /v1/metrics, registry gauges → Prometheus, and the
        # scheduler's load_snapshot() → router) all read these
        self.moe_expert_load: List[float] = []
        self.moe_tokens_routed = 0
        self.moe_hot_expert_frac = 0.0
        self.moe_capacity_waits = 0
        # live weight hot-swaps (ISSUE 15): model + draft combined;
        # the per-kind split lives on the registry counters
        self.weight_swaps = 0
        # speculative decoding (ISSUE 9): cumulative draft/accept
        # counters plus a sliding window of recent rounds — the
        # windowed accept-rate gauge is what a dashboard watches for
        # ACCEPTANCE COLLAPSE (a drifting draft silently turning the
        # speedup into pure overhead)
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self._spec_window: "deque[tuple]" = deque(maxlen=128)
        self._events: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
        self._max_event_requests = max_event_requests

    # ---- event log --------------------------------------------------
    def event(self, request_id: str, name: str, **detail: Any) -> None:
        rec = {"ts": time.time(), "event": name}
        if detail:
            rec.update(detail)
        with self._lock:
            log = self._events.get(request_id)
            if log is None:
                self._events[request_id] = log = []
                while len(self._events) > self._max_event_requests:
                    self._events.popitem(last=False)
            _bounded_append(log, rec, self.max_events_per_request)

    def events(self, request_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events.get(request_id, []))

    # ---- lifecycle hooks (scheduler thread) -------------------------
    def on_submit(self, req: Request) -> None:
        with self._lock:
            self.counts["submitted"] += 1
        self.event(req.id, "submit", prompt_tokens=int(req.prompt_ids.size),
                   max_new_tokens=req.max_new_tokens, bucket=req.bucket)

    def on_reject(self, depth: int, retry_after_s: float) -> None:
        with self._lock:
            self.counts["rejected"] += 1
        inc_counter(f"{self.prefix}.rejected_total")
        self.event("-rejected-", "reject", depth=depth,
                   retry_after_s=retry_after_s)

    def on_admit(self, req: Request) -> None:
        with self._lock:
            self.counts["admitted"] += 1
        if req.ts_admitted is not None:
            self.queue_wait_ms.observe(
                (req.ts_admitted - req.ts_arrival) * 1e3
            )
        self.event(req.id, "admit", slot=req.slot, stream_id=req.stream_id)

    def on_first_token(self, req: Request) -> None:
        if req.ts_first_token is not None:
            ttft = (req.ts_first_token - req.ts_arrival) * 1e3
            self.ttft_ms.observe(ttft)
            cut = self._active_cut
            if cut is not None:
                cut.ttft_ms.observe(ttft)
        self.event(req.id, "first_token")

    def on_finish(self, req: Request) -> None:
        key = {"done": "done", "cancelled": "cancelled",
               "expired": "expired"}.get(req.state.value)
        t = req.timing()
        # failure terminal := finished WITH an error recorded — a
        # watchdog cancel, transfer abort, un-resumable eviction —
        # never a plain client cancel or a clean completion
        failed = bool(req.error)
        cut = self._active_cut
        with self._lock:
            if key:
                self.counts[key] += 1
            if failed:
                self.requests_failed += 1
            self.tokens_out += len(req.tokens)
            if cut is not None:
                if failed:
                    cut.requests_failed += 1
                elif req.state.value == "done":
                    cut.requests_done += 1
                cut.tokens_out += len(req.tokens)
        if req.state.value == "done":
            if t["decode_ms"] is not None:
                self.decode_ms.observe(t["decode_ms"])
            if t["e2e_ms"] is not None:
                self.e2e_ms.observe(t["e2e_ms"])
        inc_counter(f"{self.prefix}.requests_{req.state.value}_total")
        if failed:
            inc_counter(f"{self.prefix}.requests_failed_total")
        if cut is not None:
            inc_counter(f"{cut.prefix}.requests_{req.state.value}_total")
            if failed:
                inc_counter(f"{cut.prefix}.requests_failed_total")
            if req.tokens:
                inc_counter(f"{cut.prefix}.tokens_out_total",
                            len(req.tokens))
        self.event(req.id, "finish", state=req.state.value,
                   n_tokens=len(req.tokens), error=req.error, **t)

    def on_phases(self, req: Request) -> None:
        """Fold a finished request's stamped timeline into the fixed
        SLO phase vector (ISSUE 19). Called by the scheduler right
        after the terminal transition stamps ``ts_done`` — by
        construction the observed phases sum to the client-observed
        e2e latency exactly (see :meth:`Request.phases`)."""
        ph = req.phases()
        for name, hist in self.phase_hists.items():
            hist.observe(ph[name])
        for name, hist in self.ttft_breakdown.items():
            hist.observe(ph[name])
        cut = self._active_cut
        if cut is not None:
            for name, hist in cut.phase_hists.items():
                hist.observe(ph[name])

    def on_segment(self, live_rows: int, slot_rows: int) -> None:
        with self._lock:
            self.segments += 1
            self.segment_live_rows += live_rows
            self.segment_slot_rows += slot_rows
            eff = (self.segment_live_rows / self.segment_slot_rows
                   if self.segment_slot_rows else 0.0)
        set_gauge(f"{self.prefix}.slot_occupancy",
                  live_rows / slot_rows if slot_rows else 0.0)
        set_gauge(f"{self.prefix}.batch_efficiency", eff)

    def on_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth
        set_gauge(f"{self.prefix}.queue_depth", float(depth))

    # ---- paged-KV hooks (scheduler thread, kv='paged' only) ---------
    def on_prefix(self, req: Request, plan) -> None:
        """One admission's prefix-cache outcome: hit/miss counters +
        prefill tokens saved (= KV positions served from shared pages
        instead of recomputed) + the rolling hit-rate gauge."""
        with self._lock:
            if plan.hit:
                self.prefix_hits += 1
            else:
                self.prefix_misses += 1
            self.prefill_tokens_saved += plan.matched_tokens
            hits, misses = self.prefix_hits, self.prefix_misses
        inc_counter(f"{self.prefix}.prefix_cache_"
                    f"{'hits' if plan.hit else 'misses'}_total")
        if plan.matched_tokens:
            inc_counter(f"{self.prefix}.prefix_tokens_saved_total",
                        plan.matched_tokens)
        set_gauge(f"{self.prefix}.prefix_hit_rate",
                  hits / (hits + misses) if hits + misses else 0.0)
        self.event(req.id, "prefix_match", hit=plan.hit,
                   matched_tokens=plan.matched_tokens,
                   cow_forks=len(plan.forks))

    def on_page_wait(self, bucket: int) -> None:
        """The allocator could not cover the queue head this boundary:
        the request stays QUEUED until pages free (the admission-
        control fix — the contiguous path's only answer was horizon
        math)."""
        with self._lock:
            self.page_waits += 1
        inc_counter(f"{self.prefix}.kv_page_waits_total")
        self.event("-pages-", "page_wait", bucket=bucket)

    def on_page_extends(self, n_events: int) -> None:
        """``n_events`` rows grew their page plan at this boundary
        (incremental allocation) — allocation-churn accounting: a
        steadily climbing rate at stable traffic means segments are
        long relative to the page size (each extend is host work plus
        an allocator walk, though never a device copy)."""
        with self._lock:
            self.page_extends += int(n_events)
        inc_counter(f"{self.prefix}.kv_page_extends_total",
                    int(n_events))

    def on_mid_decode_eviction(self, bucket: int,
                               resumable: bool = True) -> None:
        """A running row ran the store dry mid-decode and was evicted
        back to the queue (prefix published, pages released) — or, for
        ``resumable=False``, failed because its transcript outgrew
        every bucket. Nonzero at steady state means the store is
        undersized for the offered concurrency."""
        with self._lock:
            self.mid_decode_evictions += 1
        inc_counter(f"{self.prefix}.kv_mid_decode_evictions_total")
        self.event("-pages-", "mid_decode_eviction", bucket=bucket,
                   resumable=resumable)

    def on_itl(self, req: Request, delta_ms: float, n_new: int) -> None:
        """One row's segment-boundary delta: ``delta_ms`` since this
        request's previous token-producing boundary, over the
        ``n_new`` tokens this boundary emitted — observed as per-token
        ITL. Scheduler thread, once per (row, boundary): O(1)."""
        per_tok = delta_ms / max(1, int(n_new))
        self.itl_ms.observe(per_tok)
        cut = self._active_cut
        if cut is not None:
            cut.itl_ms.observe(per_tok)

    def on_prefill_chunk(self, bucket: int, tokens: int,
                         completed: bool) -> None:
        """One chunked-prefill dispatch (ISSUE 13): ``tokens`` KV
        positions prefilled this boundary; ``completed`` = the row's
        prompt finished and it decodes from the next segment."""
        with self._lock:
            self.prefill_chunks += 1
            self.prefill_chunk_tokens += int(tokens)
        inc_counter(f"{self.prefix}.prefill_chunks_total")
        inc_counter(f"{self.prefix}.prefill_chunk_tokens_total",
                    int(tokens))
        if completed:
            inc_counter(f"{self.prefix}.prefill_chunked_joins_total")

    def on_ring_prefill(self, req: Request, tokens: int,
                        n_shards: int) -> None:
        """One ring-attention prefill offload (ISSUE 13): ``tokens``
        prompt positions prefilled sequence-parallel over ``n_shards``
        devices, KV landed into pages."""
        with self._lock:
            self.ring_prefills += 1
        inc_counter(f"{self.prefix}.ring_prefills_total")
        inc_counter(f"{self.prefix}.ring_prefill_tokens_total",
                    int(tokens))
        self.event(req.id, "ring_prefill", tokens=int(tokens),
                   n_shards=int(n_shards))

    # ---- KV-page wire transfers (ISSUE 14) --------------------------
    def _on_kv_transfer(self, pages: int, nbytes: int,
                        ms: float) -> None:
        with self._lock:
            self.kv_transfer_pages += int(pages)
            self.kv_transfer_bytes += int(nbytes)
        inc_counter(f"{self.prefix}.kv_transfer_pages_total",
                    int(pages))
        inc_counter(f"{self.prefix}.kv_transfer_bytes_total",
                    int(nbytes))
        self.kv_transfer_ms.observe(float(ms))

    def on_kv_export(self, req: Request, pages: int, nbytes: int,
                     ms: float) -> None:
        """One prefill-only request's page chain serialized to the
        wire format (``ms`` = gather + serialize + CRC wall)."""
        with self._lock:
            self.kv_exports += 1
        inc_counter(f"{self.prefix}.kv_exports_total")
        self._on_kv_transfer(pages, nbytes, ms)
        self.event(req.id, "kv_export", pages=int(pages),
                   bytes=int(nbytes))

    def on_kv_import(self, transfer_id: str, pages: int, nbytes: int,
                     ms: float) -> None:
        """One inbound chunk verified and landed (``pages`` excludes
        chunks the prefix tree already held — transfer dedup)."""
        with self._lock:
            self.kv_imports += 1
        inc_counter(f"{self.prefix}.kv_imports_total")
        self._on_kv_transfer(pages, nbytes, ms)
        self.event(f"-transfer-{transfer_id}-", "kv_import",
                   pages=int(pages), bytes=int(nbytes))

    def on_kv_transfer_failure(self, transfer_id: str, error: str,
                               kind: str = "verify") -> None:
        """A transfer failed and its waiting request falls back to a
        LOCAL prefill — correctness is never at stake. Two counters so
        an operator can tell a CORRUPTING TRANSPORT from routine
        fallbacks: ``kind='verify'`` (CRC/header/gap/dry — the wire
        payload itself failed import) additionally counts on
        ``kv_transfer_crc_failures_total``; ``'timeout'``/``'abort'``
        (chain never arrived, prefill side broke) count only on the
        generic ``kv_transfer_failures_total``."""
        cut = self._active_cut
        with self._lock:
            self.kv_transfer_failures += 1
            if cut is not None:
                cut.transfer_fallbacks += 1
        inc_counter(f"{self.prefix}.kv_transfer_failures_total")
        if kind == "verify":
            inc_counter(f"{self.prefix}.kv_transfer_crc_failures_total")
        if cut is not None:
            inc_counter(f"{cut.prefix}.kv_transfer_failures_total")
        self.event(f"-transfer-{transfer_id}-", "kv_transfer_failure",
                   error=error, kind=kind)

    # ---- expert-parallel MoE serving (ISSUE 18) ---------------------
    def on_moe_load(self, loads) -> None:
        """One decode segment's per-expert routed-token harvest
        (scheduler thread, once per MoE segment): publish the
        per-expert gauges (``moe_expert_load_e{j}``), the hot-expert
        share gauge, and the monotone routed-token counter. The gauges
        carry the LATEST segment — expert load is a placement/admission
        signal, not an accumulation."""
        vals = [float(x) for x in loads]
        total = sum(vals)
        hot = (max(vals) / total) if (vals and total > 0) else 0.0
        with self._lock:
            self.moe_expert_load = vals
            self.moe_tokens_routed += int(round(total))
            self.moe_hot_expert_frac = hot
        for j, v in enumerate(vals):
            set_gauge(f"{self.prefix}.moe_expert_load_e{j}", v)
        set_gauge(f"{self.prefix}.moe_hot_expert_frac", hot)
        if total > 0:
            inc_counter(f"{self.prefix}.moe_tokens_routed_total",
                        int(round(total)))

    def on_moe_capacity_wait(self, bucket: int) -> None:
        """The hot-expert admission gate held this bucket's queue head
        at a boundary (``moe_overflow='queue'``) — the hot spot
        degraded ADMISSION latency while the in-flight batch kept
        decoding. A climbing steady-state rate means the routing is
        skewed relative to moe_capacity_factor (retrain the router,
        raise the factor, or spread load via the router's
        expert-affinity signal)."""
        with self._lock:
            self.moe_capacity_waits += 1
        inc_counter(f"{self.prefix}.moe_capacity_waits_total")
        self.event("-moe-", "moe_capacity_wait", bucket=bucket)

    # ---- live weight hot-swap (ISSUE 15) ----------------------------
    def on_model_version(self, version) -> None:
        """Publish the served model version: the ``<prefix>.
        model_version`` info gauge carries the manifest STEP (the
        numeric a dashboard can plot/alert on; -1 = versionless), and
        the full ``{step, digest, label}`` rides the JSON surfaces
        (load_snapshot, /v1/metrics via this gauge + the event log,
        flight bundles via the gauges section + the deploy note)."""
        step = None
        if isinstance(version, dict):
            step = version.get("step")
        set_gauge(f"{self.prefix}.model_version",
                  float(-1 if step is None else step))
        self.set_version_cut(_safe_version_label(version))
        self.event("-deploy-", "model_version",
                   version=(version.get("label")
                            if isinstance(version, dict) else version))

    def set_version_cut(self, label: Optional[str]) -> None:
        """Point the per-version metric cut (ISSUE 20) at ``label`` —
        every request-outcome hook from here on records into that
        version's families too. ``None`` (versionless) disables
        cutting. Cuts beyond ``max_version_cuts`` evict oldest-first,
        dropping their registry names so a long-lived server's
        registry stays bounded."""
        with self._lock:
            if label is None:
                self.version_label = None
                self._active_cut = None
                return
            cut = self._version_cuts.get(label)
            if cut is None:
                cut = _VersionCut(self.prefix, label)
                self._version_cuts[label] = cut
            else:
                self._version_cuts.move_to_end(label)
            evicted = []
            while len(self._version_cuts) > self._max_version_cuts:
                _, old = self._version_cuts.popitem(last=False)
                evicted.append(old)
            self.version_label = label
            self._active_cut = cut
        if evicted:
            from tpuflow.obs.gauges import clear_gauges

            for old in evicted:
                clear_gauges(f"{old.prefix}.")

    def version_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-version cumulative cuts: ``{label: {done, failed,
        transfer_fallbacks, tokens_out, hists: {name: state}}}`` — the
        comparand the canary scorer delta-differences per evaluation
        window and the Router sums across replicas (ISSUE 20)."""
        with self._lock:
            cuts = list(self._version_cuts.values())
        return {c.label: c.snapshot() for c in cuts}

    def windowed_error_rate(self, window_s: Optional[float] = None):
        """``(rate, errors, requests)`` over the default snapshot-ring
        window (ISSUE 20 satellite): errors = request-failure
        terminals + KV-transfer fallbacks, requests = done + failed.
        Without a ticking ring this degrades to the cumulative view
        (PR 5 semantics) — same keys, all-time values."""
        from tpuflow.obs import timeseries

        with self._lock:
            cum = {
                f"{self.prefix}.requests_failed_total":
                    float(self.requests_failed),
                f"{self.prefix}.kv_transfer_failures_total":
                    float(self.kv_transfer_failures),
                f"{self.prefix}.requests_done_total":
                    float(self.counts["done"]),
            }

        def _inc(name: str) -> float:
            d = timeseries.windowed_counter_increase(name, window_s)
            return cum[name] if d is None else d

        failed = _inc(f"{self.prefix}.requests_failed_total")
        fallbacks = _inc(f"{self.prefix}.kv_transfer_failures_total")
        done = _inc(f"{self.prefix}.requests_done_total")
        errors = failed + fallbacks
        requests = done + failed
        return ((errors / requests if requests else 0.0),
                errors, requests)

    def on_weight_swap(self, version, ms: float, *, draft: bool,
                       cleared_pages: int = 0) -> None:
        """One completed in-place weight swap (standby restore or
        recycle): wall time, prefix pages invalidated (a version bump
        invalidates cached KV), target-vs-draft counters."""
        with self._lock:
            self.weight_swaps += 1
        inc_counter(f"{self.prefix}."
                    f"{'draft_' if draft else ''}weight_swaps_total")
        self.event("-deploy-", "weight_swap",
                   version=(version.get("label")
                            if isinstance(version, dict) else version),
                   draft=bool(draft), ms=round(float(ms), 3),
                   cleared_pages=int(cleared_pages))

    def on_spec_round(self, drafted: int, accepted: int) -> None:
        """One speculative round's outcome: ``drafted`` proposals
        (k per live speculative row), ``accepted`` of them matched the
        oracle. Counters land in the registry (→ /v1/metrics +
        Prometheus); the gauge is the WINDOWED accept rate over the
        last rounds."""
        with self._lock:
            self.spec_rounds += 1
            self.spec_drafted += int(drafted)
            self.spec_accepted += int(accepted)
            self._spec_window.append((int(drafted), int(accepted)))
            rate = self._spec_rate_locked()
        inc_counter(f"{self.prefix}.spec_rounds_total")
        inc_counter(f"{self.prefix}.spec_drafted_total", int(drafted))
        # unconditional: total acceptance collapse must export a
        # flat-zero series, not a MISSING one (rate() over an absent
        # counter is no-data — the exact dashboard this metric feeds)
        inc_counter(f"{self.prefix}.spec_accepted_total", int(accepted))
        set_gauge(f"{self.prefix}.spec_accept_rate", rate)

    def _spec_rate_locked(self) -> float:
        """Windowed accept rate over the recent-rounds deque. Caller
        holds ``self._lock`` (non-reentrant — the one reason the three
        consumers share this helper instead of a public method)."""
        wd = sum(d for d, _ in self._spec_window)
        wa = sum(a for _, a in self._spec_window)
        return wa / wd if wd else 0.0

    def spec_accept_rate_windowed(self) -> float:
        with self._lock:
            return self._spec_rate_locked()

    def spec_totals(self):
        """One consistent (rounds, drafted, accepted, windowed_rate)
        read — snapshot consumers must not interleave with a
        mid-``on_spec_round`` update (accepted > drafted reads)."""
        with self._lock:
            return (self.spec_rounds, self.spec_drafted,
                    self.spec_accepted, self._spec_rate_locked())

    def on_kv(self, kv_state) -> None:
        """Publish the page store's occupancy gauges (fed once per
        scheduler boundary; Prometheus/v1/metrics/flight all read the
        same registry entries)."""
        a = kv_state.allocator
        set_gauge(f"{self.prefix}.kv_pages_total", float(a.total))
        set_gauge(f"{self.prefix}.kv_pages_in_use", float(a.in_use()))
        set_gauge(f"{self.prefix}.kv_bytes_in_use",
                  float(kv_state.bytes_in_use()))
        set_gauge(f"{self.prefix}.kv_bytes_total",
                  float(kv_state.bytes_total()))
        tier = getattr(kv_state, "tier", None)
        if tier is not None:
            st = tier.stats()
            set_gauge(f"{self.prefix}.kv_host_bytes",
                      float(st["host_bytes_used"]))
            set_gauge(f"{self.prefix}.kv_host_chains",
                      float(st["host_chains"]))
            set_gauge(f"{self.prefix}.kv_disk_bytes",
                      float(st["disk_bytes_used"]))
            set_gauge(f"{self.prefix}.kv_disk_chains",
                      float(st["disk_chains"]))
            # delta-publish the pool's cumulative counters so the
            # registry's *_total counters stay monotone across
            # boundaries (the mirror fields feed snapshot())
            with self._lock:
                d = int(st["demotes"]) - self.kv_demotes
                p = int(st["promotes"]) - self.kv_promotes
                self.kv_demotes = int(st["demotes"])
                self.kv_promotes = int(st["promotes"])
            if d > 0:
                inc_counter(f"{self.prefix}.kv_demotes_total", d)
            if p > 0:
                inc_counter(f"{self.prefix}.kv_promotes_total", p)

    def reset_latency(self) -> None:
        """Start a fresh accumulation window for every latency
        histogram (counts/events/gauges untouched) — the windowed-
        percentile hook for long-lived servers (see class docstring)."""
        for h in (self.ttft_ms, self.queue_wait_ms, self.decode_ms,
                  self.e2e_ms, self.itl_ms, self.kv_transfer_ms):
            h.reset()

    # ---- export -----------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat dotted-key snapshot (run-metric loggable as-is).
        Latency percentiles are WINDOWED when the timeseries default
        ring is ticking (``_cum`` carries all-time); without a ring
        both views are the cumulative values (see class docstring)."""
        from tpuflow.obs import timeseries

        # ONE windowed pass over this prefix's histograms (summaries
        # filters before the expensive delta-differencing)
        windowed = timeseries.windowed_summaries(f"{self.prefix}.")
        with self._lock:
            m: Dict[str, float] = {
                f"{self.prefix}.{k}": float(v) for k, v in self.counts.items()
            }
            m[f"{self.prefix}.queue_depth"] = float(self.queue_depth)
            m[f"{self.prefix}.requests_failed"] = float(
                self.requests_failed)
            m[f"{self.prefix}.prefix_hits"] = float(self.prefix_hits)
            m[f"{self.prefix}.prefix_misses"] = float(self.prefix_misses)
            m[f"{self.prefix}.prefix_hit_rate"] = (
                self.prefix_hits / (self.prefix_hits + self.prefix_misses)
                if self.prefix_hits + self.prefix_misses else 0.0
            )
            m[f"{self.prefix}.prefill_tokens_saved"] = float(
                self.prefill_tokens_saved)
            m[f"{self.prefix}.kv_page_extends"] = float(
                self.page_extends)
            m[f"{self.prefix}.kv_mid_decode_evictions"] = float(
                self.mid_decode_evictions)
            m[f"{self.prefix}.prefill_chunks"] = float(
                self.prefill_chunks)
            m[f"{self.prefix}.prefill_chunk_tokens"] = float(
                self.prefill_chunk_tokens)
            m[f"{self.prefix}.ring_prefills"] = float(self.ring_prefills)
            m[f"{self.prefix}.kv_transfer_pages"] = float(
                self.kv_transfer_pages)
            m[f"{self.prefix}.kv_transfer_bytes"] = float(
                self.kv_transfer_bytes)
            m[f"{self.prefix}.kv_exports"] = float(self.kv_exports)
            m[f"{self.prefix}.kv_imports"] = float(self.kv_imports)
            m[f"{self.prefix}.kv_transfer_failures"] = float(
                self.kv_transfer_failures)
            m[f"{self.prefix}.kv_demotes"] = float(self.kv_demotes)
            m[f"{self.prefix}.kv_promotes"] = float(self.kv_promotes)
            m[f"{self.prefix}.weight_swaps"] = float(self.weight_swaps)
            for j, v in enumerate(self.moe_expert_load):
                m[f"{self.prefix}.moe_expert_load_e{j}"] = float(v)
            if self.moe_expert_load or self.moe_tokens_routed:
                m[f"{self.prefix}.moe_tokens_routed"] = float(
                    self.moe_tokens_routed)
                m[f"{self.prefix}.moe_hot_expert_frac"] = float(
                    self.moe_hot_expert_frac)
                m[f"{self.prefix}.moe_capacity_waits"] = float(
                    self.moe_capacity_waits)
            m[f"{self.prefix}.spec_rounds"] = float(self.spec_rounds)
            m[f"{self.prefix}.spec_drafted"] = float(self.spec_drafted)
            m[f"{self.prefix}.spec_accepted"] = float(self.spec_accepted)
            # PR 5 key convention: the PRIMARY key is WINDOWED (it
            # matches the registry gauge of the same name — one name,
            # one semantics across /v1/metrics, Prometheus and flight
            # bundles), all-time lives under `_cum`
            m[f"{self.prefix}.spec_accept_rate"] = (
                self._spec_rate_locked())
            m[f"{self.prefix}.spec_accept_rate_cum"] = (
                self.spec_accepted / self.spec_drafted
                if self.spec_drafted else 0.0
            )
            m[f"{self.prefix}.tokens_out"] = float(self.tokens_out)
            m[f"{self.prefix}.segments"] = float(self.segments)
            m[f"{self.prefix}.batch_efficiency"] = (
                self.segment_live_rows / self.segment_slot_rows
                if self.segment_slot_rows else 0.0
            )
        for name, hist in (("ttft_ms", self.ttft_ms),
                           ("queue_wait_ms", self.queue_wait_ms),
                           ("decode_ms", self.decode_ms),
                           ("e2e_ms", self.e2e_ms),
                           ("itl_ms", self.itl_ms),
                           ("kv_transfer_ms", self.kv_transfer_ms)):
            cum = hist.percentiles()
            win = windowed.get(f"{self.prefix}.{name}")
            prim = (win["percentiles"] if win else {}) or cum
            for pk, pv in prim.items():
                m[f"{self.prefix}.{name}_{pk}"] = round(pv, 3)
            for pk, pv in cum.items():
                m[f"{self.prefix}.{name}_{pk}_cum"] = round(pv, 3)
        # SLO phase attribution (ISSUE 19): windowed percentiles per
        # phase of the two breakdown families. Primary-keys-only (no
        # _cum mirror) — 11 member histograms would double the
        # snapshot's key count for a view the Prometheus buckets
        # already carry cumulatively.
        for fam, hists in (("req_phase_ms", self.phase_hists),
                           ("ttft_breakdown", self.ttft_breakdown)):
            for phname, hist in hists.items():
                cum = hist.percentiles()
                if not cum:
                    continue  # no finished requests yet
                win = windowed.get(f"{self.prefix}.{fam}.{phname}")
                prim = (win["percentiles"] if win else {}) or cum
                for pk, pv in prim.items():
                    m[f"{self.prefix}.{fam}.{phname}_{pk}"] = round(pv, 3)
        return m
