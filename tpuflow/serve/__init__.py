"""tpuflow.serve — online serving runtime.

The request-lifecycle layer the offline batch path (infer.batch /
packaging.lm) lacks: slot-level continuous batching over the decode
engine's segment-resume + per-slot-prefill primitives
(tpuflow.infer.generate), a bounded admission queue with backpressure,
per-request deadlines/cancellation/streaming, serving metrics exported
through tpuflow.obs, and a thin stdlib HTTP frontend
(``python -m tpuflow.serve``).
"""

from tpuflow.serve.metrics import ServeMetrics, percentiles  # noqa: F401
from tpuflow.serve.pages import (  # noqa: F401
    PagedKV,
    PagedKVSpec,
    PageAllocator,
    PrefixCache,
)
from tpuflow.serve.request import (  # noqa: F401
    QueueFull,
    Request,
    RequestState,
)
from tpuflow.serve.scheduler import ServeScheduler, serve_texts  # noqa: F401
from tpuflow.serve.slots import PagedSlotPool, SlotPool  # noqa: F401
