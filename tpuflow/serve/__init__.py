"""tpuflow.serve — online serving runtime.

The request-lifecycle layer the offline batch path (infer.batch /
packaging.lm) lacks: slot-level continuous batching over the decode
engine's segment-resume + per-slot-prefill primitives
(tpuflow.infer.generate), a bounded admission queue with backpressure,
per-request deadlines/cancellation/streaming, serving metrics exported
through tpuflow.obs, a thin stdlib HTTP frontend
(``python -m tpuflow.serve``), and — above all of it — the
multi-replica router tier (``python -m tpuflow.serve --replicas N``):
load-aware placement over replica ``load_snapshot()`` sensors, prefix
affinity aligned with the paged KV cache's chunking, tier-level
shedding/backpressure, failover of never-admitted requests, and
graceful drain on SIGTERM or ``POST /v1/admin/drain``.

Long-context serving (ISSUE 13): prefill is a schedulable, budget-
bounded resource — ``prefill_budget_tokens`` chunks a long prompt's
join across scheduler boundaries interleaved with decode segments
(the ``--prefill-slo`` TTFT-vs-ITL knob; ``serve.itl_ms`` measures
the ITL side), and ``ring_prefill=N`` runs prompts beyond one
device's budget sequence-parallel over causal ring attention with
the K/V landed straight into pages. Token-identical either way.

Prefill/decode disaggregation (ISSUE 14): the tier splits into
replica CLASSES — ``replica_class='prefill'`` replicas run prompt
passes and export KV page chains over the wire (per-page CRC32,
``serve/pages.py`` wire format), ``'decode'`` replicas import them
and own the decode slots — with out-of-process replicas
(``HTTPReplica`` over the ``/v1/worker/*`` endpoints, or
``--connect host:port,...``) so decode throughput scales beyond one
host's HBM. Every transfer failure falls back to a local prefill:
token-identical either way.

Zero-downtime deployment (ISSUE 15): a ``ModelWatcher`` polls a
checkpoint namespace for newly published sharded manifests (publish
is atomic — manifest existence IS the promotion signal) and the
``DeploymentManager`` blue/greens them through the tier: restore into
a STANDBY replica's device buffers (same config ⇒ same executables —
no recompile; config drift is refused loudly), replay the hottest
prefix-chain heads onto it (a version bump invalidates cached KV),
activate it, drain one old-version replica and recycle it as the
next standby. Every replica carries a ``model_version`` and
``submit(pin_version=)`` gives token-identical per-version A/B
mid-rollout. CLI: ``--watch-checkpoints DIR --standby``.
"""

from tpuflow.serve.deploy import (  # noqa: F401
    DeploymentManager,
    DeployError,
    ModelWatcher,
    SwapMismatchError,
    manifest_version,
)
from tpuflow.serve.metrics import ServeMetrics, percentiles  # noqa: F401
from tpuflow.serve.pages import (  # noqa: F401
    PagedKV,
    PagedKVSpec,
    PageAllocator,
    PageWireError,
    PrefixCache,
    split_chain,
    wire_from_json,
    wire_to_json,
)
from tpuflow.serve.replica import (  # noqa: F401
    HTTPReplica,
    InProcessReplica,
    Replica,
    launch_worker,
)
from tpuflow.serve.request import (  # noqa: F401
    QueueFull,
    Request,
    RequestState,
    SchedulerClosed,
)
from tpuflow.serve.router import (  # noqa: F401
    Router,
    RouterMetrics,
    RouterRequest,
)
from tpuflow.serve.scheduler import ServeScheduler, serve_texts  # noqa: F401
from tpuflow.serve.slots import PagedSlotPool, SlotPool  # noqa: F401
