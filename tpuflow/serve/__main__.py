"""``python -m tpuflow.serve`` — serve a packaged LM over HTTP.

Loads a packaged LM directory / ``runs:/`` / ``models:/`` URI
(tpuflow.packaging.lm), builds the slot-level continuous-batching
scheduler around it — or, with ``--replicas N``, a whole multi-replica
tier (ISSUE 8): N schedulers behind the load-aware router with prefix
affinity, shedding and failover — and exposes the stdlib HTTP
frontend::

  python -m tpuflow.serve --model /path/to/packaged_lm --port 8000 \
      --replicas 2 --kv paged --slots 4 --max-new 64

  curl -s localhost:8000/v1/generate -d '{"prompt": "the cat"}'
  curl -s localhost:8000/v1/metrics
  curl -s -X POST localhost:8000/v1/admin/drain   # graceful drain

SIGTERM drains gracefully (train/preempt.py's signal channel): stop
admitting (503), finish every admitted request, flip ``/readyz``, then
exit — a rolling restart truncates zero streams. ``--drain-timeout``
bounds the wait.

Zero-downtime deployment (ISSUE 15): ``--standby`` adds one idle
replica and ``--watch-checkpoints DIR`` polls a checkpoint namespace —
every newly published sharded manifest (atomic, manifest-last) is
blue/greened through the tier with no restart and no truncated
stream::

  python -m tpuflow.serve --model pkg --replicas 2 --kv paged \
      --standby --watch-checkpoints /ckpts

Equivalent entry point: ``python -m tpuflow.cli.serve``.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tpuflow.serve", description=__doc__)
    p.add_argument("--model", default=None,
                   help="packaged LM directory or runs:/ / models:/ "
                        "URI (required unless --connect fronts remote "
                        "workers that loaded their own)")
    p.add_argument("--connect", default=None, metavar="ADDR[,ADDR...]",
                   help="front EXISTING out-of-process workers "
                        "(host:port of other `python -m tpuflow.serve` "
                        "instances) through the router instead of "
                        "loading a model locally — the prefill/decode "
                        "disaggregation deployment shape (ISSUE 14): "
                        "each worker declares its --replica-class and "
                        "the router does two-phase placement, shipping "
                        "KV page chains from prefill- to decode-class "
                        "replicas over the wire")
    p.add_argument("--replica-class", default="mixed",
                   metavar="CLASS[,CLASS...]",
                   help="mixed | prefill | decode — this server's "
                        "class (worker mode), or a comma list "
                        "assigning one class per in-process replica "
                        "(--replicas N): e.g. --replicas 3 "
                        "--replica-class prefill,decode,decode builds "
                        "a disaggregated tier in one process. "
                        "Non-mixed classes require --kv paged")
    p.add_argument("--transfer-min-tokens", type=int, default=None,
                   metavar="TOKENS",
                   help="disaggregated tiers: route a request through "
                        "a prefill-class replica only when its "
                        "estimated UNCACHED suffix is at least this "
                        "long (default 2 pages) — shorter suffixes "
                        "prefill locally on the decode replica, "
                        "cheaper than shipping pages")
    p.add_argument("--transfer-chunk-pages", type=int, default=8,
                   metavar="PAGES",
                   help="split exported page chains into chunks of at "
                        "most this many pages: chunks land one "
                        "scheduler boundary at a time, interleaved "
                        "with decode segments (transfer overlap)")
    p.add_argument("--watch-checkpoints", default=None, metavar="DIR",
                   help="zero-downtime deployment (ISSUE 15): poll "
                        "DIR for newly published sharded-checkpoint "
                        "manifests (publish is atomic, so a verified "
                        "manifest IS the promotion signal) and "
                        "blue/green each one through the tier — "
                        "restore into the standby replica (same "
                        "config, no recompile; config drift is "
                        "refused loudly), replay hot prefix heads, "
                        "shift traffic, drain + recycle the old "
                        "replica. Requires --standby")
    p.add_argument("--standby", action="store_true",
                   help="add one STANDBY replica to the tier (takes "
                        "no traffic until a rollout activates it): "
                        "with --replicas N the process runs N active "
                        "+ 1 standby schedulers; with --connect the "
                        "LAST listed worker is the standby. The cost "
                        "of zero-downtime swaps is this one idle "
                        "replica's memory")
    p.add_argument("--deploy-poll", type=float, default=2.0,
                   metavar="S",
                   help="--watch-checkpoints: poll interval")
    p.add_argument("--slo", action="append", default=None,
                   metavar="SPEC",
                   help="install an SLO objective (repeatable): "
                        "'[name=]metric:pP<T@W' (latency) or "
                        "'[name=]bad/total<B@Ws/Wl[xF]' (error "
                        "budget); 'default' installs the stock "
                        "serving objectives. Verdicts ride /v1/slo, "
                        "load_snapshot and flight bundles")
    p.add_argument("--canary", action="store_true",
                   help="score the first rotation of every rollout "
                        "as a canary (new-vs-old version cuts) and "
                        "auto-roll-back on a breach; needs --standby")
    p.add_argument("--canary-windows", type=int, default=3,
                   metavar="N",
                   help="--canary: clean evaluation windows before "
                        "full rotation")
    p.add_argument("--canary-window", type=float, default=15.0,
                   metavar="SECS",
                   help="--canary: evaluation window length")
    p.add_argument("--canary-min-requests", type=int, default=8,
                   metavar="N",
                   help="--canary: per-version per-window request "
                        "floor below which a window is inconclusive")
    p.add_argument("--deploy-replay", type=int, default=8,
                   metavar="N",
                   help="--watch-checkpoints: hottest prefix-chain "
                        "heads replayed (re-prefilled) onto a freshly "
                        "swapped replica before traffic shifts — a "
                        "version bump invalidates cached KV, so "
                        "warmth is rebuilt, never transferred")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 binds an ephemeral port (printed on start)")
    p.add_argument("--replicas", type=int, default=1,
                   help="scheduler replicas behind the front router "
                        "(1 = the single-scheduler path, no router; "
                        ">1 = load-aware placement + prefix affinity "
                        "+ failover across N in-process replicas "
                        "sharing the loaded weights)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots per prompt-length bucket "
                        "(per replica)")
    p.add_argument("--seg", type=int, default=8,
                   help="decode steps between scheduler boundaries")
    p.add_argument("--rounds", type=int, default=3,
                   help="decode-budget multiples in each pool's horizon")
    p.add_argument("--max-new", type=int, default=64,
                   help="per-request max_new_tokens cap")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission queue bound PER REPLICA (429 beyond "
                        "it; the router also sheds at the tier-wide "
                        "sum)")
    p.add_argument("--request-timeout", type=float, default=120.0)
    p.add_argument("--drain-timeout", type=float, default=60.0,
                   metavar="S",
                   help="max seconds a SIGTERM drain waits for the "
                        "admitted backlog before exiting anyway")
    p.add_argument("--kv", choices=("contiguous", "paged"),
                   default="contiguous",
                   help="KV memory model: 'paged' = fixed-size pages "
                        "+ per-slot page tables + copy-on-write "
                        "prefix sharing (KV bytes scale with live "
                        "tokens, shared system prompts skip prefill); "
                        "'contiguous' = the per-bucket slab cache")
    p.add_argument("--kv-pages", type=int, default=None,
                   help="--kv paged: physical page count of the store "
                        "(default sizes for ~4x slots concurrent "
                        "worst-case requests), per replica")
    p.add_argument("--kv-page-size", type=int, default=16,
                   help="--kv paged: tokens per page")
    p.add_argument("--kv-quant", choices=("int8",), default=None,
                   help="--kv paged: store pages as int8 with "
                        "per-page scale vectors (~2x more capacity "
                        "on bf16 models, ~4x on f32)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="--kv paged: disable shared-prefix page reuse")
    p.add_argument("--no-kv-prefix-insert-generated", action="store_true",
                   help="--kv paged: do NOT publish finished requests' "
                        "GENERATED pages into the prefix cache "
                        "(default ON: multi-turn follow-ups "
                        "prompt+completion+... hit past the original "
                        "prompt; completion pages stay in the tree "
                        "until LRU pressure evicts them)")
    p.add_argument("--kv-host-bytes", type=int, default=0,
                   metavar="BYTES",
                   help="--kv paged: tiered KV (ISSUE 16) — spill "
                        "warm prefix chains evicted from the device "
                        "page store into a host-RAM pool of at most "
                        "BYTES, and promote them back (import, no "
                        "recompute) when a later prompt hits the "
                        "spilled prefix. 0 disables the tier. Size it "
                        "to a few times the device store: bytes per "
                        "page = page_size * 2 * layers * heads * "
                        "head_dim * dtype bytes")
    p.add_argument("--kv-disk-path", default=None, metavar="DIR",
                   help="--kv-host-bytes: second tier — when the host "
                        "pool overflows, spill host-LRU chains to "
                        "mmap'd files under DIR instead of dropping "
                        "them (CRC-checked on load; corruption falls "
                        "back to recompute)")
    p.add_argument("--kv-tier-directory", action="store_true",
                   help="--replicas>1: tier-global prefix directory — "
                        "the router tracks which replica (and tier) "
                        "holds each chunk-key chain and, on an "
                        "affinity miss, pulls the chain from any "
                        "holder onto the placed replica instead of "
                        "recomputing the prefix")
    p.add_argument("--prefill-slo", type=int, default=None,
                   metavar="TOKENS",
                   help="--kv paged: chunked-prefill SLO knob (ISSUE "
                        "13) — a join whose uncached prompt suffix "
                        "exceeds TOKENS is prefilled in chunks of at "
                        "most TOKENS KV positions, one per scheduler "
                        "boundary, interleaved with decode segments: "
                        "one long prompt stops stalling every "
                        "in-flight row's ITL (serve.itl_ms measures "
                        "it). Smaller = flatter concurrent ITL, "
                        "longer long-prompt TTFT; outputs are "
                        "token-identical either way")
    p.add_argument("--ring-prefill", type=int, default=None, metavar="N",
                   help="--kv paged: prefill long prompts "
                        "sequence-parallel over N devices (causal "
                        "ring attention, striped layout) with the "
                        "K/V landed directly into pages — per-device "
                        "prefill residency drops to O(p/N), so "
                        "prompts beyond one device's budget become "
                        "servable. N a power of two in [2, 8]; "
                        "excludes --kv-quant and --speculate-k")
    p.add_argument("--ring-prefill-min", type=int, default=512,
                   metavar="TOKENS",
                   help="--ring-prefill: prompts at or above this "
                        "length take the ring path (shorter ones "
                        "prefill single-device as usual)")
    p.add_argument("--speculate-k", type=int, default=0, metavar="K",
                   help="draft-model speculative decoding (ISSUE 9): "
                        "a small draft LM proposes K tokens per round "
                        "and the target verifies all K+1 positions in "
                        "one blockwise pass with oracle-parity "
                        "acceptance — outputs are token-identical to "
                        "plain decode, throughput scales with the "
                        "draft's acceptance rate. Requires --kv paged "
                        "and --draft-config. K+1 a power of two "
                        "aligns the verify window with the join "
                        "width menu (K=3 default choice)")
    p.add_argument("--draft-config", default=None, metavar="PATH",
                   help="--speculate-k: packaged LM directory (or "
                        "runs:/ / models:/ URI) for the DRAFT model — "
                        "must share the target's vocabulary; replicas "
                        "share the loaded draft weights")
    p.add_argument("--no-affinity", action="store_true",
                   help="--replicas>1: disable prefix-affinity "
                        "placement (pure least-loaded)")
    p.add_argument("--snapshot-cache", action="store_true",
                   help="--replicas>1: serve placements off the "
                        "cached snapshot plane (refreshed on the "
                        "maintenance cadence, corrected by local "
                        "deltas) instead of re-snapshotting every "
                        "replica per request — the fleet-scale mode; "
                        "staleness is bounded by the maintain poll "
                        "interval and visible as the "
                        "router.snapshot_staleness_s gauge")
    p.add_argument("--trace-spans", action="store_true",
                   help="enable the tpuflow.obs.trace span tracer "
                        "(request ids become trace ids; inspect via "
                        "GET /v1/trace/<id>)")
    p.add_argument("--trace-sample", type=int, default=None,
                   metavar="N",
                   help="with --trace-spans: head-sample 1-in-N "
                        "requests for full span recording (default 1 "
                        "= every request). The hash is over the "
                        "request id, so the router and every worker "
                        "vote identically per request")
    p.add_argument("--trace-tail-slow-ms", type=float, default=None,
                   metavar="MS",
                   help="with --trace-spans: tail-keep head-dropped "
                        "traces whose request errored or whose "
                        "latency is >= MS or >= the windowed p95 — "
                        "the outliers you want are kept even at a "
                        "low head rate")
    p.add_argument("--stall-timeout", type=float, default=None,
                   metavar="S",
                   help="arm the stall watchdog: trip (latched; fail "
                        "/readyz) when S seconds pass without a decode "
                        "segment completing, once one ever has. Set S "
                        "above the worst-case first-touch pool compile "
                        "of a NEW bucket — that window pauses segments "
                        "legitimately. (/readyz itself also reports "
                        "not-ready during such pauses and self-heals; "
                        "only the watchdog latches.)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the flight recorder: dump a post-mortem "
                        "bundle under DIR on watchdog trip, unhandled "
                        "exception or SIGTERM (inspect via python -m "
                        "tpuflow.cli.obs postmortem DIR); a graceful "
                        "drain dumps a final 'drain complete' bundle "
                        "whose manifest notes carry the drain")
    raw_argv = sys.argv[1:] if argv is None else list(argv)
    if any(a == "--kv-prefix-insert-generated"
           or a.startswith("--kv-prefix-insert-generated=")
           for a in raw_argv):
        # removed in r16: it had been a no-op since the r11 A/B
        # verdict made generated-page insertion the default
        p.error("--kv-prefix-insert-generated was removed: "
                "generated-page insertion is the default; drop the "
                "flag, or pass --no-kv-prefix-insert-generated to "
                "turn it OFF")
    args = p.parse_args(argv)

    if not args.model and not args.connect:
        p.error("--model is required (or --connect to front remote "
                "workers)")
    if args.watch_checkpoints and not args.standby:
        p.error("--watch-checkpoints needs --standby (the rollout "
                "restores into the standby replica's buffers)")
    if args.canary and not args.standby:
        p.error("--canary needs --standby (scoring judges the "
                "blue/green window a rollout opens)")
    if args.standby and not args.connect and args.kv != "paged":
        # hot prefix replay and prefix invalidation are paged-KV
        # concepts; the swap itself would work, but an un-warmed
        # contiguous tier mid-rollout is not the documented contract
        print("note: --standby without --kv paged skips prefix "
              "replay (no prefix cache to warm)", flush=True)
    classes = [c.strip() for c in str(args.replica_class).split(",")]
    for c in classes:
        if c not in ("mixed", "prefill", "decode"):
            p.error(f"--replica-class must be mixed|prefill|decode, "
                    f"got {c!r}")
    if args.connect is None:
        n_for_classes = max(1, int(args.replicas))
        if len(classes) == 1:
            classes = classes * n_for_classes
        if len(classes) != n_for_classes:
            p.error(f"--replica-class lists {len(classes)} classes "
                    f"for --replicas {n_for_classes}")
        if any(c != "mixed" for c in classes) and args.kv != "paged":
            p.error("--replica-class prefill/decode requires --kv "
                    "paged (KV pages are the wire format)")
        if any(c != "mixed" for c in classes) and args.speculate_k:
            p.error("--replica-class prefill/decode does not combine "
                    "with --speculate-k")
    if args.prefill_slo is not None and args.kv != "paged":
        p.error("--prefill-slo (chunked prefill) requires --kv paged")
    if (args.kv_host_bytes or args.kv_disk_path) and args.kv != "paged":
        p.error("--kv-host-bytes / --kv-disk-path (tiered KV) require "
                "--kv paged")
    if args.kv_disk_path and not args.kv_host_bytes:
        p.error("--kv-disk-path needs --kv-host-bytes (the disk tier "
                "backs the host pool's overflow)")
    if (args.kv_host_bytes or args.kv_disk_path) and args.no_prefix_cache:
        p.error("tiered KV spills the prefix tree's evictions; it "
                "cannot combine with --no-prefix-cache")
    if (args.kv_tier_directory and args.connect is None
            and max(1, int(args.replicas)) == 1 and not args.standby):
        p.error("--kv-tier-directory is router policy: it needs "
                "--replicas > 1, --standby or --connect")
    if args.prefill_slo is not None and args.prefill_slo < 1:
        p.error("--prefill-slo must be >= 1 (omit it for atomic joins)")
    if args.ring_prefill is not None:
        n = args.ring_prefill
        if args.kv != "paged":
            p.error("--ring-prefill requires --kv paged")
        if args.kv_quant is not None:
            p.error("--ring-prefill does not combine with --kv-quant "
                    "(the harvest lands unquantized KV)")
        if args.speculate_k:
            p.error("--ring-prefill does not combine with "
                    "--speculate-k (the draft store has no ring "
                    "harvest)")
        if n < 2 or n & (n - 1) or n > 8:
            p.error(f"--ring-prefill must be a power of two in "
                    f"[2, 8], got {n}")

    if args.trace_sample is not None and not args.trace_spans:
        p.error("--trace-sample requires --trace-spans")
    if args.trace_tail_slow_ms is not None and not args.trace_spans:
        p.error("--trace-tail-slow-ms requires --trace-spans")
    if args.trace_spans:
        from tpuflow.obs import trace as _trace

        _trace.enable()
        if (args.trace_sample is not None
                or args.trace_tail_slow_ms is not None):
            _trace.configure_sampling(
                head_n=args.trace_sample or 1,
                tail_slow_ms=args.trace_tail_slow_ms)
    # SIGTERM channel FIRST (train/preempt.py): the flag handler must
    # be innermost so flight.install (which CHAINS the previous
    # handler) dumps its bundle and then still flips the drain flag
    from tpuflow.train.preempt import sigterm_preempt_flag

    with sigterm_preempt_flag(True) as term_flag:
        # memory-and-compile plane (ISSUE 7): a long-lived server
        # always arms the executable registry — recompile storms
        # (bucket-menu explosion) must trip /readyz, not read as
        # mysterious latency. Per-call cost while armed is one C-level
        # cache-size read.
        from tpuflow.obs import executables as _executables

        _executables.enable()
        if args.flight_dir:
            from tpuflow.obs import flight as _flight
            from tpuflow.obs.health import default_watchdog

            _flight.install(args.flight_dir, signals=True)
            default_watchdog().on_trip.append(
                _flight.trip_dumper(args.flight_dir)
            )

        from tpuflow.packaging.lm import load_packaged_lm
        from tpuflow.serve.http import start_http_server
        from tpuflow.serve.metrics import ServeMetrics
        from tpuflow.serve.scheduler import ServeScheduler

        kw = dict(
            slots=args.slots, seg=args.seg, rounds=args.rounds,
            max_new_cap=args.max_new, max_queue=args.max_queue,
            kv=args.kv, kv_pages=args.kv_pages,
            kv_page_size=args.kv_page_size, kv_quant=args.kv_quant,
            kv_prefix_cache=not args.no_prefix_cache,
            kv_prefix_insert_generated=(
                not args.no_kv_prefix_insert_generated),
            kv_host_bytes=args.kv_host_bytes,
            kv_disk_path=args.kv_disk_path,
            prefill_budget_tokens=args.prefill_slo,
            ring_prefill=args.ring_prefill,
            ring_prefill_min_tokens=args.ring_prefill_min,
        )
        if args.speculate_k and not args.connect:
            # speculative decoding (ISSUE 9): load the draft package
            # ONCE — with --replicas N every replica's scheduler
            # shares the same draft device weights, and the router's
            # tier-global stream-id pinning keeps tier outputs
            # token-identical to a single scheduler with speculation
            # on OR off (oracle-parity acceptance)
            if not args.draft_config:
                p.error("--speculate-k needs --draft-config "
                        "(a packaged LM directory for the draft)")
            if args.kv != "paged":
                p.error("--speculate-k requires --kv paged")
            draft = load_packaged_lm(args.draft_config)
            kw.update(speculate_k=args.speculate_k,
                      draft_model=draft.model,
                      draft_params=draft.params)
        n_rep = max(1, int(args.replicas))
        router_kw = dict(
            affinity=not args.no_affinity,
            transfer_chunk_pages=args.transfer_chunk_pages,
            tier_directory=args.kv_tier_directory,
            snapshot_cache=args.snapshot_cache,
        )
        if args.transfer_min_tokens is not None:
            router_kw["transfer_min_tokens"] = args.transfer_min_tokens
        if args.connect:
            # front EXISTING out-of-process workers (ISSUE 14): no
            # local model load at all — each worker owns its weights,
            # device state, watchdog and blast radius; the router is
            # pure host policy over their /v1/worker/* surfaces
            from tpuflow.serve.replica import HTTPReplica
            from tpuflow.serve.router import Router

            addrs = [a.strip() for a in args.connect.split(",")
                     if a.strip()]
            if args.standby:
                # the LAST listed worker is the standby: out-of-
                # process rollouts swap it over /v1/worker/
                # swap_weights (shared checkpoint namespace)
                if len(addrs) < 2:
                    p.error("--standby with --connect needs at least "
                            "2 workers (the last one is the standby)")
                router_kw["standby"] = (len(addrs) - 1,)
            front = Router([HTTPReplica(a) for a in addrs],
                           **router_kw)
            schedulers = []
        elif n_rep == 1 and not args.standby:
            kw["replica_class"] = classes[0]
            front = sched = ServeScheduler.from_packaged(args.model, **kw)
            schedulers = [sched]
        else:
            # load the package ONCE: every replica shares the weights
            # (device buffers) and tokenizer; each gets its own
            # scheduler thread, slot pools, KV store, a
            # serve.replica<i> metrics namespace (→ replica="<i>"
            # labels in the Prometheus exposition) AND its own
            # watchdog (ISSUE 14: a trip fails over one replica, not
            # the whole in-process tier)
            from tpuflow.obs.health import Watchdog
            from tpuflow.serve.replica import InProcessReplica
            from tpuflow.serve.router import Router

            lm = load_packaged_lm(args.model)
            if args.standby:
                # one extra scheduler, parked as standby (ISSUE 15):
                # it shares the loaded weights until the first
                # rollout swaps its own in. Mixed-class so it can
                # stand in for any retiring replica.
                classes = classes + ["mixed"]
                router_kw["standby"] = (n_rep,)
            schedulers = []
            for i in range(len(classes)):
                schedulers.append(ServeScheduler.from_packaged(
                    lm,
                    metrics=ServeMetrics(
                        gauge_prefix=f"serve.replica{i}"),
                    replica_class=classes[i],
                    watchdog=Watchdog(),
                    **kw,
                ))
            front = Router(
                [InProcessReplica(s, name=f"replica{i}")
                 for i, s in enumerate(schedulers)],
                **router_kw,
            )
        if args.stall_timeout:
            from tpuflow.obs.health import StallDetector

            detector = StallDetector(float(args.stall_timeout))
            for sched in schedulers:
                sched.stall_after_s = float(args.stall_timeout)
                # watch SEGMENTS, not the loop: the loop heartbeat
                # goes quiet during a first-touch pool compile too,
                # and a latched trip on a cold start would 503
                # /readyz forever. The segment name only starts
                # counting once a segment has ever completed
                # (require=False), so the cold-compile window cannot
                # false-trip; a pre-first-segment wedge is still
                # caught by /readyz's (non-latching) loop-age
                # fallback.
                detector.watch(f"{sched.metrics.prefix}.segment",
                               active=(lambda s=sched: not s.idle()))
            detector.start()
        server = start_http_server(front, args.host, args.port,
                                   request_timeout_s=args.request_timeout)
        if args.slo:
            # SLO plane (ISSUE 20): objectives evaluate as multiwindow
            # burn rates over the snapshot ring the frontend already
            # ticks; verdicts ride /v1/slo, load_snapshot and flight
            from tpuflow.obs.slo import (
                SLObjective,
                SLOEvaluator,
                default_objectives,
                install as install_slo,
            )

            objectives = []
            for spec in args.slo:
                if spec.strip() == "default":
                    objectives.extend(default_objectives())
                else:
                    try:
                        objectives.append(SLObjective.parse(spec))
                    except ValueError as e:
                        p.error(str(e))
            install_slo(SLOEvaluator(objectives))
            print(f"SLO objectives installed: "
                  f"{', '.join(o.name for o in objectives)} "
                  f"(GET /v1/slo)", flush=True)
        watcher = None
        if args.watch_checkpoints:
            # zero-downtime deployment (ISSUE 15): poll the namespace;
            # each verified new manifest runs a full blocking rollout
            # on the watcher's own daemon thread (swap standby →
            # replay hot heads → shift → drain+recycle)
            from tpuflow.serve.deploy import (
                DeploymentManager,
                ModelWatcher,
            )

            canary_policy = None
            if args.canary:
                from tpuflow.serve.canary import CanaryPolicy

                canary_policy = CanaryPolicy(
                    windows=args.canary_windows,
                    window_s=args.canary_window,
                    min_requests=args.canary_min_requests)
            manager = DeploymentManager(
                front, replay_hot=args.deploy_replay,
                drain_timeout_s=max(60.0, 2 * args.drain_timeout),
                canary=canary_policy)
            if hasattr(front, "on_maintain"):
                # rollouts also advance on the router's maintenance
                # cadence (tick() serializes against the watcher's
                # own blocking deploy loop), so a rotation never
                # stalls behind a slow poll interval
                front.on_maintain.append(manager.tick)
            watcher = ModelWatcher(
                args.watch_checkpoints,
                lambda mpath, version: manager.deploy(mpath),
                poll_s=args.deploy_poll)
            watcher.start()
            print(f"watching {args.watch_checkpoints} for published "
                  f"checkpoints (poll {args.deploy_poll:g}s, "
                  f"standby=replica{len(front.replicas) - 1})",
                  flush=True)
        what = args.model or f"workers[{args.connect}]"
        print(f"serving {what} on http://{args.host}:{server.port} "
              f"(replicas={n_rep}"
              f"{'+standby' if args.standby else ''} "
              f"slots={args.slots} seg={args.seg} "
              f"max_new={args.max_new} queue<={args.max_queue} "
              f"kv={args.kv} class={','.join(classes)})", flush=True)
        try:
            while not term_flag["hit"]:
                time.sleep(0.2)
            # graceful drain (ISSUE 8): SIGTERM = stop admitting,
            # finish everything admitted, then exit — a rolling
            # restart truncates zero streams. The flight SIGTERM hook
            # (if armed) already dumped the moment-of-signal bundle;
            # a second bundle below records the drain's outcome.
            print("SIGTERM: draining (new submits get 503)", flush=True)
            front.drain(wait_s=args.drain_timeout)
            drained = front.drained() if hasattr(front, "drained") else True
            print(f"drain {'complete' if drained else 'TIMED OUT'}",
                  flush=True)
            if args.flight_dir:
                from tpuflow.obs import flight as _flight

                _flight.dump(args.flight_dir, "drain complete"
                             if drained else "drain timeout")
        except KeyboardInterrupt:
            print("shutting down", flush=True)
        finally:
            if watcher is not None:
                watcher.stop()
            server.shutdown()
            front.stop(drain=False, timeout=10.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
