"""``python -m tpuflow.serve`` — serve a packaged LM over HTTP.

Loads a packaged LM directory / ``runs:/`` / ``models:/`` URI
(tpuflow.packaging.lm), builds the slot-level continuous-batching
scheduler around it, and exposes the stdlib HTTP frontend::

  python -m tpuflow.serve --model /path/to/packaged_lm --port 8000 \
      --slots 4 --max-new 64

  curl -s localhost:8000/v1/generate -d '{"prompt": "the cat"}'
  curl -s localhost:8000/v1/metrics

Equivalent entry point: ``python -m tpuflow.cli.serve``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tpuflow.serve", description=__doc__)
    p.add_argument("--model", required=True,
                   help="packaged LM directory or runs:/ / models:/ URI")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 binds an ephemeral port (printed on start)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots per prompt-length bucket")
    p.add_argument("--seg", type=int, default=8,
                   help="decode steps between scheduler boundaries")
    p.add_argument("--rounds", type=int, default=3,
                   help="decode-budget multiples in each pool's horizon")
    p.add_argument("--max-new", type=int, default=64,
                   help="per-request max_new_tokens cap")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission queue bound (429 beyond it)")
    p.add_argument("--request-timeout", type=float, default=120.0)
    p.add_argument("--kv", choices=("contiguous", "paged"),
                   default="contiguous",
                   help="KV memory model: 'paged' = fixed-size pages "
                        "+ per-slot page tables + copy-on-write "
                        "prefix sharing (KV bytes scale with live "
                        "tokens, shared system prompts skip prefill); "
                        "'contiguous' = the per-bucket slab cache")
    p.add_argument("--kv-pages", type=int, default=None,
                   help="--kv paged: physical page count of the store "
                        "(default sizes for ~4x slots concurrent "
                        "worst-case requests)")
    p.add_argument("--kv-page-size", type=int, default=16,
                   help="--kv paged: tokens per page")
    p.add_argument("--kv-quant", choices=("int8",), default=None,
                   help="--kv paged: store pages as int8 with "
                        "per-page scale vectors (~2x more capacity "
                        "on bf16 models, ~4x on f32)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="--kv paged: disable shared-prefix page reuse")
    p.add_argument("--trace-spans", action="store_true",
                   help="enable the tpuflow.obs.trace span tracer "
                        "(request ids become trace ids; inspect via "
                        "GET /v1/trace/<id>)")
    p.add_argument("--stall-timeout", type=float, default=None,
                   metavar="S",
                   help="arm the stall watchdog: trip (latched; fail "
                        "/readyz) when S seconds pass without a decode "
                        "segment completing, once one ever has. Set S "
                        "above the worst-case first-touch pool compile "
                        "of a NEW bucket — that window pauses segments "
                        "legitimately. (/readyz itself also reports "
                        "not-ready during such pauses and self-heals; "
                        "only the watchdog latches.)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the flight recorder: dump a post-mortem "
                        "bundle under DIR on watchdog trip, unhandled "
                        "exception or SIGTERM (inspect via python -m "
                        "tpuflow.cli.obs postmortem DIR)")
    args = p.parse_args(argv)

    if args.trace_spans:
        from tpuflow.obs import trace as _trace

        _trace.enable()
    # memory-and-compile plane (ISSUE 7): a long-lived server always
    # arms the executable registry — recompile storms (bucket-menu
    # explosion) must trip /readyz, not read as mysterious latency.
    # Per-call cost while armed is one C-level cache-size read.
    from tpuflow.obs import executables as _executables

    _executables.enable()
    if args.flight_dir:
        from tpuflow.obs import flight as _flight
        from tpuflow.obs.health import default_watchdog

        _flight.install(args.flight_dir, signals=True)
        default_watchdog().on_trip.append(
            _flight.trip_dumper(args.flight_dir)
        )

    from tpuflow.serve.http import start_http_server
    from tpuflow.serve.scheduler import ServeScheduler

    sched = ServeScheduler.from_packaged(
        args.model, slots=args.slots, seg=args.seg, rounds=args.rounds,
        max_new_cap=args.max_new, max_queue=args.max_queue,
        kv=args.kv, kv_pages=args.kv_pages,
        kv_page_size=args.kv_page_size, kv_quant=args.kv_quant,
        kv_prefix_cache=not args.no_prefix_cache,
    )
    if args.stall_timeout:
        from tpuflow.obs.health import StallDetector

        sched.stall_after_s = float(args.stall_timeout)
        # watch SEGMENTS, not the loop: the loop heartbeat goes quiet
        # during a first-touch pool compile too, and a latched trip on
        # a cold start would 503 /readyz forever. The segment name
        # only starts counting once a segment has ever completed
        # (require=False), so the cold-compile window cannot false-
        # trip; a pre-first-segment wedge is still caught by /readyz's
        # (non-latching) loop-age fallback.
        detector = StallDetector(float(args.stall_timeout))
        detector.watch(f"{sched.metrics.prefix}.segment",
                       active=lambda: not sched.idle())
        detector.start()
    server = start_http_server(sched, args.host, args.port,
                               request_timeout_s=args.request_timeout)
    print(f"serving {args.model} on http://{args.host}:{server.port} "
          f"(slots={args.slots} seg={args.seg} max_new={args.max_new} "
          f"queue<={args.max_queue} kv={args.kv})", flush=True)
    try:
        import threading

        threading.Event().wait()  # serve until interrupted
    except KeyboardInterrupt:
        print("shutting down", flush=True)
    finally:
        server.shutdown()
        sched.stop(drain=False, timeout=10.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
