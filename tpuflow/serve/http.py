"""Stdlib HTTP frontend for the serving scheduler.

Deliberately thin — demo + integration-test surface, not a production
gateway: ``http.server.ThreadingHTTPServer`` (one handler thread per
connection) over a running :class:`~tpuflow.serve.scheduler.
ServeScheduler` — or a :class:`~tpuflow.serve.router.Router`, which
duck-types the same surface, so ``python -m tpuflow.serve --replicas N``
serves a whole multi-replica tier through this one frontend; every
request is a thread-safe ``submit``/``cancel`` into the scheduler
thread(s), so the device never sees HTTP concurrency.

Endpoints::

  POST /v1/generate   {"prompt": str|[ids], "max_new_tokens"?, "stream"?,
                       "deadline_s"?, "id"?}
      → 200 JSON {id, state, text?, tokens, n_tokens, metrics}
      → stream=true: chunked NDJSON — one {"tokens": [...]} line per
        decode segment, then a final {"done": true, ...} summary line
      → 429 + Retry-After on admission-queue backpressure (QueueFull)
      → 400 on never-servable requests (too long, bad budget)
      → 503 once a drain/stop began (SchedulerClosed — new work must
        go elsewhere; the admitted backlog still finishes)
  POST /v1/cancel     {"id": ...} → {"cancelled": bool}
  POST /v1/admin/drain  graceful drain (ISSUE 8): stop admitting,
                      finish everything admitted, flip /readyz →
                      {"draining": true, "drained": bool, ...}
  GET  /v1/metrics    scheduler + gauge snapshot (JSON; windowed
                      percentiles primary, cumulative under _cum —
                      incl. serve.itl_ms, the per-row inter-token
                      latency the chunked-prefill SLO knob trades
                      against, and the prefill_chunks / ring_prefills
                      counters; ISSUE 13)
  GET  /metrics       Prometheus/OpenMetrics text exposition of the
                      whole gauge registry (tpuflow.obs.prom)
  GET  /v1/events/ID  structured event log for one request id
  GET  /v1/trace/ID   spans of one request (trace id == request id —
                      tpuflow.obs.trace; [] unless the tracer is
                      enabled: TPUFLOW_TRACE_SPANS=1 or --trace-spans)
                      merged with the event log as instant events
                      (ISSUE 19) — and when this frontend serves a
                      Router, the TIER view: spans fanned out from
                      every replica that touched the request, clock-
                      offset corrected and merged into one timeline
  GET  /healthz       LIVENESS: {"ok": true, ...} whenever the process
                      answers — never consults scheduler progress
  GET  /readyz        READINESS: 200 only while the scheduler is open,
                      unwedged and watchdog-clean; 503 + the reason
                      otherwise (wire THIS one to the load balancer —
                      a wedged scheduler keeps passing /healthz)

Worker endpoints (ISSUE 14 — what an :class:`~tpuflow.serve.replica.
HTTPReplica` speaks, making any serve instance an OUT-OF-PROCESS
replica of a router tier; single-scheduler servers only)::

  GET  /v1/worker/config         replica shape facts (slots, caps,
                                 page_size, replica_class, tokenizer)
  GET  /v1/worker/load_snapshot  the placement sensor, verbatim
  GET  /v1/worker/health         the failover input (scheduler.health)
  GET  /v1/worker/retry_after    {"retry_after_s": ...}
  GET  /v1/worker/chain_report   tiered-KV chunk-key inventory (ISSUE
                                 16; feeds the router's tier-global
                                 prefix directory)
  POST /v1/worker/fetch_chain    deepest exportable chain covering the
                                 posted tokens (resident pages or the
                                 host/disk tier) — the donor half of a
                                 directory-routed cross-replica pull
  POST /v1/worker/encode|decode  tokenizer proxy (router-side string
                                 prompts without local weights)
  POST /v1/worker/submit         raw-token submit with stream_id /
                                 speculate / await_transfer → chunked
                                 NDJSON ({"tokens": [...]} per
                                 boundary, then a {"done": true}
                                 summary line)
  POST /v1/worker/prefill        prefill-only request → the exported
                                 KV page chain (serve/pages.py wire
                                 format, base64 payloads)
  POST /v1/worker/offer_chain    land a wire chunk into this
                                 replica's page store / prefix tree
  POST /v1/worker/swap_weights   hot-swap weights from a sharded
                                 manifest in the shared checkpoint
                                 namespace (ISSUE 15; quiescent
                                 workers only — 400 on config
                                 mismatch, loudly)
  POST /v1/worker/reopen         re-admit after a drain (the recycle
                                 half of a blue/green rotation)
  POST /v1/worker/stop           stop the scheduler (drain optional)

``POST /v1/generate`` additionally accepts ``pin_version`` (ISSUE
15): serve this request on exactly that model version (router tiers
place on matching replicas; a single scheduler 503s a mismatch) —
the token-identical A/B surface during a rollout.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from tpuflow.serve.request import QueueFull, RequestState, SchedulerClosed


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # required for chunked streaming
    server_version = "tpuflow-serve/0.1"

    # ---- plumbing ---------------------------------------------------
    def log_message(self, fmt, *args):  # route access noise to events
        self.server.scheduler.metrics.event(
            "-http-", "access", line=(fmt % args)
        )

    def _json(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b"{}"
        try:
            body = json.loads(raw.decode() or "{}")
        except json.JSONDecodeError as e:
            raise ValueError(f"invalid JSON body: {e}")
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        return body

    def _chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

    def _end_chunks(self) -> None:
        self.wfile.write(b"0\r\n\r\n")

    def _text_of(self, req) -> Optional[str]:
        tok = self.server.scheduler.tokenizer
        if tok is None:
            return None
        import numpy as np

        full = np.concatenate(
            [req.prompt_ids, np.asarray(req.tokens, np.int32)]
        ) if req.tokens else req.prompt_ids
        return tok.decode(full).decode("utf-8", "replace")

    # ---- routes -----------------------------------------------------
    def do_GET(self):
        sched = self.server.scheduler
        if self.path == "/healthz":
            # liveness ONLY: answering at all is the signal (progress
            # lives in /readyz). `ok` is kept for old callers.
            self._json(200, {"ok": True, "live": True,
                             "idle": sched.idle()})
        elif self.path == "/readyz":
            r = sched.readiness()
            self._json(200 if r["ready"] else 503, r)
        elif self.path == "/metrics":
            from tpuflow.obs.prom import CONTENT_TYPE, render

            body = render().encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/v1/metrics":
            # scalars + counters only: metrics_snapshot already carries
            # the latency percentiles (windowed + _cum), and a full
            # snapshot_gauges would re-walk every registry histogram's
            # windowed delta just to overwrite those keys with equal
            # values
            from tpuflow.obs.gauges import counters, scalar_gauges

            snap = sched.metrics_snapshot()
            snap.update(scalar_gauges("serve"))
            snap.update(counters("serve"))
            # router-tier counters when this frontend serves a Router
            # (empty prefixes cost one dict walk each otherwise)
            snap.update(scalar_gauges("router"))
            snap.update(counters("router"))
            self._json(200, snap)
        elif self.path == "/v1/slo":
            # SLO verdicts (ISSUE 20): the process default evaluator's
            # report — objective-by-objective ok/margins, latency
            # percentiles vs thresholds, multiwindow burn rates
            from tpuflow.obs import slo as _slo

            ev = _slo.default_evaluator()
            if ev is None:
                return self._json(404, {
                    "error": "no SLO objectives installed "
                             "(start the frontend with --slo)"})
            self._json(200, ev.report())
        elif self.path.startswith("/v1/worker/"):
            if not hasattr(sched, "submit_prefill"):
                return self._json(404, {
                    "error": "worker endpoints front a single "
                             "scheduler, not a router tier"})
            if self.path == "/v1/worker/config":
                spec = getattr(sched, "kv_spec", None)
                self._json(200, {
                    "name": sched.metrics.prefix,
                    "replica_class": getattr(sched, "replica_class",
                                             "mixed"),
                    "slots": sched.slots,
                    "max_new_cap": sched.max_new_cap,
                    "max_queue": sched.max_queue,
                    "page_size": (None if spec is None
                                  else spec.page_size),
                    "speculate_k": getattr(sched, "speculate_k", 0),
                    "model_version": getattr(sched, "model_version",
                                             None),
                    "has_tokenizer": sched.tokenizer is not None,
                })
            elif self.path == "/v1/worker/load_snapshot":
                self._json(200, sched.load_snapshot())
            elif self.path == "/v1/worker/health":
                self._json(200, sched.health())
            elif self.path == "/v1/worker/retry_after":
                self._json(200,
                           {"retry_after_s": sched.retry_after_s()})
            elif self.path == "/v1/worker/chain_report":
                self._json(200, {"chains": sched.kv_chain_report()})
            elif self.path == "/v1/worker/version_snapshot":
                # per-version metric cuts (ISSUE 20): the canary
                # scorer's comparand for a worker fronted over HTTP
                self._json(200, sched.version_snapshot())
            else:
                self._json(404, {"error": f"no route {self.path}"})
        elif self.path.startswith("/v1/events/"):
            rid = self.path[len("/v1/events/"):]
            self._json(200, {"id": rid,
                             "events": sched.metrics.events(rid)})
        elif self.path.startswith("/v1/trace/"):
            from tpuflow.obs import trace

            rid = self.path[len("/v1/trace/"):]
            if hasattr(sched, "tier_trace"):
                # router frontend (ISSUE 19): fan out to every replica
                # that touched this request and return ONE merged,
                # offset-corrected tier trace
                return self._json(200, sched.tier_trace(rid))
            spans = trace.spans_for(rid)
            # merge the structured event log as instant events (ISSUE
            # 19 satellite): one endpoint tells the full per-replica
            # story — spans for durations, events for the lifecycle
            # edges (submit/admit/first_token/finish) between them
            for ev in sched.metrics.events(rid):
                attrs = {k: v for k, v in ev.items()
                         if k not in ("ts", "event")}
                spans.append({
                    "name": f"event:{ev.get('event')}",
                    "span_id": None, "parent_id": None, "thread": None,
                    "start_s": round(float(ev.get("ts", 0.0)), 6),
                    "dur_ms": 0.0, "instant": True, "attrs": attrs,
                })
            spans.sort(key=lambda s: s["start_s"])
            self._json(200, {"id": rid,
                             "tracer_enabled": trace.is_enabled(),
                             "spans": spans})
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        sched = self.server.scheduler
        self._response_started = False
        try:
            body = self._read_body()
            if self.path == "/v1/generate":
                return self._generate(sched, body)
            if self.path == "/v1/cancel":
                rid = body.get("id")
                if not rid:
                    raise ValueError("cancel needs an 'id'")
                return self._json(200, {"id": rid,
                                        "cancelled": sched.cancel(rid)})
            if self.path.startswith("/v1/worker/"):
                return self._worker_post(sched, body)
            if self.path == "/v1/admin/drain":
                # graceful drain over HTTP (the SIGTERM channel's
                # twin): stop admitting, finish the admitted backlog,
                # flip /readyz — callers poll "drained" or /readyz
                sched.drain()
                return self._json(200, {
                    "draining": True,
                    "drained": bool(sched.drained()),
                    "readiness": sched.readiness(),
                })
            return self._json(404, {"error": f"no route {self.path}"})
        except SchedulerClosed as e:
            # draining/stopped: new work must go elsewhere — the LB
            # watching /readyz already stopped sending; stragglers get
            # the drain contract's 503 instead of a queue slot
            self._json(503, {"error": str(e)})
        except QueueFull as e:
            # backpressure telemetry: quote the current HBM headroom
            # (and refresh the mem.hbm_headroom_bytes gauge) so a
            # shedding client — or the operator reading 429 bodies —
            # can tell queue pressure from memory pressure
            from tpuflow.obs import memory as _memory
            from tpuflow.obs.gauges import set_gauge

            headroom = _memory.hbm_headroom_bytes()
            if headroom is not None:
                set_gauge("mem.hbm_headroom_bytes", float(headroom))
            self._json(
                429,
                {"error": "queue full", "retry_after_s": e.retry_after_s,
                 "hbm_headroom_bytes": headroom},
                headers={"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )
        except ValueError as e:
            self._json(400, {"error": str(e)})
        except Exception as e:  # pragma: no cover - defensive
            if self._response_started:
                # headers already on the wire (streaming): a second
                # send_response would corrupt the connection — drop it
                self.close_connection = True
            else:
                import traceback

                self._json(500, {
                    "error": f"{type(e).__name__}: {e}",
                    # last frames only: enough to locate the fault
                    # from a worker's 500 body without shipping logs
                    "trace": traceback.format_exc().splitlines()[-6:],
                })

    def _worker_post(self, sched, body: Dict[str, Any]) -> None:
        """POST half of the worker surface (ISSUE 14) — see module
        docstring. Exceptions propagate to do_POST's taxonomy mapping
        (QueueFull→429, SchedulerClosed→503, ValueError→400), which
        the HTTPReplica un-maps back into the same exceptions."""
        if not hasattr(sched, "submit_prefill"):
            return self._json(404, {
                "error": "worker endpoints front a single scheduler, "
                         "not a router tier"})
        if self.path == "/v1/worker/encode":
            if sched.tokenizer is None:
                raise ValueError("worker has no tokenizer")
            ids = sched.tokenizer.encode(str(body.get("text", "")))
            import numpy as np

            return self._json(200, {
                "ids": np.asarray(ids, np.int32).reshape(-1).tolist()})
        if self.path == "/v1/worker/decode":
            if sched.tokenizer is None:
                raise ValueError("worker has no tokenizer")
            import numpy as np

            raw = sched.tokenizer.decode(
                np.asarray(body.get("ids", []), np.int32))
            return self._json(200, {
                "text": raw.decode("utf-8", "replace")})
        if self.path == "/v1/worker/submit":
            return self._worker_submit(sched, body)
        if self.path == "/v1/worker/prefill":
            from tpuflow.serve.pages import wire_to_json

            prompt = body.get("prompt")
            if prompt is None:
                raise ValueError("prefill needs a 'prompt'")
            kw: Dict[str, Any] = {}
            if body.get("deadline_s") is not None:
                kw["deadline_s"] = float(body["deadline_s"])
            if body.get("id"):
                kw["request_id"] = str(body["id"])
            if isinstance(body.get("trace_ctx"), dict):
                kw["trace_ctx"] = dict(body["trace_ctx"])
            req = sched.submit_prefill(prompt, **kw)
            timeout = float(body.get("timeout_s")
                            or self.server.request_timeout_s)
            try:
                summary = req.result(timeout=timeout)
            except TimeoutError:
                sched.cancel(req)
                req.wait(timeout=5.0)
                summary = req.summary()
                summary["error"] = summary["error"] or "server timeout"
            summary["wire"] = (None if req.export is None
                               else wire_to_json(req.export))
            code = 200 if req.export is not None else 504
            return self._json(code, summary)
        if self.path == "/v1/worker/offer_chain":
            from tpuflow.serve.pages import wire_from_json

            wire = body.get("wire")
            if not isinstance(wire, dict):
                raise ValueError("offer_chain needs a 'wire' object")
            tctx = (dict(body["trace_ctx"])
                    if isinstance(body.get("trace_ctx"), dict) else None)
            tid = sched.offer_chain(
                wire_from_json(wire),
                transfer_id=body.get("transfer_id"),
                last=bool(body.get("last", True)),
                trace_ctx=tctx)
            return self._json(200, {"transfer_id": tid, "ok": True})
        if self.path == "/v1/worker/fetch_chain":
            # directory pull donor (ISSUE 16): answer with this
            # worker's deepest coverage of the prefix (resident tree
            # re-export or spilled chain). The scheduler answers at
            # its next boundary; this handler thread blocks, the
            # decode loop does not.
            from tpuflow.serve.pages import wire_to_json

            tokens = body.get("tokens")
            if tokens is None:
                raise ValueError("fetch_chain needs 'tokens'")
            timeout = float(body.get("timeout_s")
                            or self.server.request_timeout_s)
            wire = sched.fetch_chain(tokens, timeout=timeout)
            return self._json(200, {
                "wire": None if wire is None else wire_to_json(wire)})
        if self.path == "/v1/worker/fail_transfer":
            tid = body.get("transfer_id")
            if not tid:
                raise ValueError("fail_transfer needs a 'transfer_id'")
            sched.fail_transfer(str(tid),
                                str(body.get("reason", "failed")))
            return self._json(200, {"transfer_id": str(tid),
                                    "ok": True})
        if self.path == "/v1/worker/swap_weights":
            # zero-downtime deployment (ISSUE 15): hot-swap this
            # worker's weights from a manifest in the shared
            # checkpoint namespace. A config mismatch surfaces as the
            # SwapMismatchError -> ValueError -> 400 taxonomy (loud
            # reject, nothing moved); a busy worker (not drained /
            # not standby) is the RuntimeError -> 500 path.
            mpath = body.get("manifest")
            if not mpath:
                raise ValueError("swap_weights needs a 'manifest' "
                                 "path")
            version = sched.swap_from_manifest(
                str(mpath), draft=bool(body.get("draft", False)))
            return self._json(200, {
                "ok": True,
                "model_version": getattr(sched, "model_version", None),
                "swapped": version,
                "draft": bool(body.get("draft", False)),
            })
        if self.path == "/v1/worker/reopen":
            sched.reopen()
            return self._json(200, {"ok": True,
                                    "readiness": sched.readiness()})
        if self.path == "/v1/worker/stop":
            sched.stop(drain=bool(body.get("drain", True)),
                       timeout=float(body.get("timeout", 30.0)))
            return self._json(200, {"stopped": True})
        return self._json(404, {"error": f"no route {self.path}"})

    def _worker_submit(self, sched, body: Dict[str, Any]) -> None:
        """Raw-token streaming submit — the HTTPReplica transport:
        every scheduler kwarg the router pins (stream_id, speculate,
        await_transfer) crosses the wire, tokens stream as NDJSON at
        segment boundaries, and the final line carries the terminal
        summary (authoritative token list included, so a reader that
        missed a line still converges)."""
        prompt = body.get("prompt")
        if prompt is None:
            raise ValueError("submit needs a 'prompt'")
        kwargs: Dict[str, Any] = {}
        if body.get("max_new_tokens") is not None:
            kwargs["max_new_tokens"] = int(body["max_new_tokens"])
        if body.get("deadline_s") is not None:
            kwargs["deadline_s"] = float(body["deadline_s"])
        if body.get("id"):
            kwargs["request_id"] = str(body["id"])
        if body.get("stream_id") is not None:
            kwargs["stream_id"] = int(body["stream_id"])
        if body.get("speculate") is not None:
            kwargs["speculate"] = bool(body["speculate"])
        if body.get("await_transfer") is not None:
            kwargs["await_transfer"] = str(body["await_transfer"])
        if isinstance(body.get("trace_ctx"), dict):
            # distributed-trace adoption (ISSUE 19): the router's
            # trace id / parent span ride the RPC so this worker's
            # spans join the SAME trace the router opened
            kwargs["trace_ctx"] = dict(body["trace_ctx"])
        timeout = float(body.get("timeout_s")
                        or self.server.request_timeout_s)
        events: "queue.Queue" = queue.Queue()
        req = sched.submit(
            prompt,
            stream_cb=lambda r, new, fin: events.put((list(new), fin)),
            **kwargs,
        )
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self._response_started = True
        try:
            self._chunk(json.dumps({"id": req.id}).encode() + b"\n")
            finished = False
            while not finished:
                try:
                    new, finished = events.get(timeout=timeout)
                except queue.Empty:
                    sched.cancel(req)
                    break
                if new:
                    self._chunk(
                        json.dumps({"tokens": new}).encode() + b"\n")
            req.wait(timeout=5.0)
            final = {
                "done": True,
                "state": req.state.value,
                "tokens": list(req.tokens),
                "error": req.error,
                "ts_admitted": req.ts_admitted,
            }
            self._chunk(json.dumps(final).encode() + b"\n")
            self._end_chunks()
        except OSError:
            sched.cancel(req)
            self.close_connection = True

    def _generate(self, sched, body: Dict[str, Any]) -> None:
        prompt = body.get("prompt")
        if prompt is None:
            raise ValueError("generate needs a 'prompt'")
        kwargs: Dict[str, Any] = {}
        if body.get("max_new_tokens") is not None:
            kwargs["max_new_tokens"] = int(body["max_new_tokens"])
        if body.get("deadline_s") is not None:
            kwargs["deadline_s"] = float(body["deadline_s"])
        if body.get("id"):
            kwargs["request_id"] = str(body["id"])
        if body.get("speculate") is not None:
            # per-request opt-out of speculative decoding (ISSUE 9) —
            # tokens are identical either way (oracle-parity
            # acceptance); a no-op on non-speculating servers
            kwargs["speculate"] = bool(body["speculate"])
        if body.get("pin_version") is not None:
            # version pin (ISSUE 15): token-identical A/B during a
            # rollout. A router tier places on matching replicas;
            # a single scheduler either IS that version or 503s —
            # the pin means "this version or nothing", never "some
            # other weights that happen to be loaded".
            pv = str(body["pin_version"])
            if hasattr(sched, "replicas"):
                kwargs["pin_version"] = pv
            else:
                mv = getattr(sched, "model_version", None) or {}
                label = mv.get("label") if isinstance(mv, dict) else mv
                if label != pv:
                    raise SchedulerClosed(
                        f"model version {pv!r} is not served here "
                        f"(loaded: {label!r})")
        timeout = float(self.server.request_timeout_s
                        if body.get("timeout_s") is None
                        else body["timeout_s"])

        if body.get("stream"):
            events: "queue.Queue" = queue.Queue()
            req = sched.submit(
                prompt, stream_cb=lambda r, new, fin:
                    events.put((list(new), fin)),
                **kwargs,
            )
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._response_started = True
            try:
                self._chunk(json.dumps({"id": req.id}).encode() + b"\n")
                finished = False
                while not finished:
                    try:
                        new, finished = events.get(timeout=timeout)
                    except queue.Empty:
                        sched.cancel(req)
                        break
                    if new:
                        self._chunk(
                            json.dumps({"tokens": new}).encode() + b"\n"
                        )
                req.wait(timeout=1.0)
                summary = req.summary()
                summary["done"] = True
                summary["text"] = self._text_of(req)
                self._chunk(json.dumps(summary).encode() + b"\n")
                self._end_chunks()
            except OSError:
                # client went away mid-stream: free the decode slot
                # instead of burning it on a request nobody is reading
                # (the connection is dead — no error response possible)
                sched.cancel(req)
                self.close_connection = True
            return

        req = sched.submit(prompt, **kwargs)
        try:
            summary = req.result(timeout=timeout)
        except TimeoutError:
            sched.cancel(req)
            req.wait(timeout=5.0)
            summary = req.summary()
            summary["error"] = summary["error"] or "server timeout"
        summary["text"] = self._text_of(req)
        code = 200 if req.state is RequestState.DONE else 504
        self._json(code, summary)


class ServeHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer bound to one scheduler."""

    daemon_threads = True

    def __init__(self, scheduler, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 120.0):
        super().__init__((host, port), _Handler)
        self.scheduler = scheduler
        self.request_timeout_s = request_timeout_s

    @property
    def port(self) -> int:
        return self.server_address[1]

    def shutdown(self):
        # drop this frontend's reference on the process snapshot ring:
        # the last surface out stops it (no leaked ticker thread), and
        # another live surface's reference keeps it ticking. Guarded
        # so repeated shutdown() calls (a natural finally-block
        # pattern) release exactly the one reference we acquired.
        if getattr(self, "_ring_ref", False):
            from tpuflow.obs import timeseries

            self._ring_ref = False
            timeseries.release()
        super().shutdown()


def start_http_server(scheduler, host: str = "127.0.0.1", port: int = 0,
                      request_timeout_s: float = 120.0) -> ServeHTTPServer:
    """Start the scheduler loop (if needed) and an HTTP server thread;
    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    Stop with ``server.shutdown()`` (scheduler stays up — stop it via
    ``scheduler.stop()``). Starts the metrics-plane snapshot ring so
    ``/v1/metrics`` percentiles are windowed for a long-lived server
    (one daemon thread, one registry walk per tick)."""
    from tpuflow.obs import timeseries

    scheduler.start()
    # bind FIRST: acquiring the ring reference before a failing bind
    # (EADDRINUSE) would leak the ref and its ticker thread
    server = ServeHTTPServer(scheduler, host, port, request_timeout_s)
    timeseries.ensure()  # released in server.shutdown()
    server._ring_ref = True
    threading.Thread(target=server.serve_forever, name="tpuflow-serve-http",
                     daemon=True).start()
    return server
