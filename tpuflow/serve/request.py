"""Request lifecycle types for the online serving runtime.

A :class:`Request` is the unit the scheduler moves through the
pipeline::

    submit() ──► QUEUED ──► RUNNING ──► DONE
                   │            │
                   │            ├──► CANCELLED   (cancel() frees the slot)
                   │            └──► EXPIRED     (deadline hit mid-decode)
                   ├──► CANCELLED                (cancel() while queued)
                   └──► EXPIRED                  (deadline hit in queue)
    submit() ──► QueueFull raised               (admission backpressure)

Every terminal transition sets the request's done event, so
:meth:`Request.result` unblocks exactly once; per-stage timestamps
(arrival → admitted → first token → done) are recorded here and turned
into TTFT / queue-wait / decode-latency metrics by
:mod:`tpuflow.serve.metrics`.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class RequestState(str, Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"
    EXPIRED = "expired"


class QueueFull(RuntimeError):
    """Admission rejected: the bounded queue is at capacity.

    Carries ``retry_after_s`` — the backpressure contract (the HTTP
    frontend maps this to 429 + ``Retry-After``; a well-behaved client
    backs off instead of hammering a saturated server)."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"admission queue full ({depth} queued); retry after "
            f"{retry_after_s:.2f}s"
        )
        self.depth = depth
        self.retry_after_s = retry_after_s


class SchedulerClosed(RuntimeError):
    """Submit rejected: the scheduler (or router) is stopped or
    draining. The graceful-drain contract (ISSUE 8): everything already
    admitted finishes, NEW work must go elsewhere — the HTTP frontend
    maps this to 503 so a load balancer watching ``/readyz`` fails the
    instance over instead of retrying into it."""


_req_counter = itertools.count()


@dataclass(eq=False)  # identity equality: requests hold numpy fields
class Request:
    """One in-flight generation request (scheduler-owned mutable state).

    ``stream_cb(request, new_token_ids, finished)`` fires on the
    scheduler thread at every decode-segment boundary that produced
    tokens for this request — the streaming surface; exceptions from it
    are swallowed into the event log, never into the decode loop.
    """

    prompt_ids: np.ndarray
    max_new_tokens: int
    id: str = ""
    deadline_ts: Optional[float] = None  # absolute time.time() deadline
    stream_cb: Optional[Callable[["Request", List[int], bool], None]] = None

    # lifecycle (scheduler-owned)
    state: RequestState = RequestState.QUEUED
    bucket: int = 0
    stream_id: int = 0  # per-request sampling stream (infer._sample row_ids)
    # speculative decoding (ISSUE 9): False pins this request to plain
    # one-token-per-round decode even on a speculating scheduler — it
    # shares the batch with speculative rows (the acceptance kernel
    # forces its accepted count to 0), tokens unchanged either way
    speculate: bool = True
    # prefill/decode disaggregation (ISSUE 14): a PREFILL-ONLY request
    # runs its prompt pass, exports the resulting page chain to the
    # wire format (``export`` — see serve/pages.py) and finalizes DONE
    # with zero tokens; ``await_transfer`` holds a submitted request
    # QUEUED until the named inbound page-chain transfer lands (or
    # fails, when it falls back to a local prefill)
    prefill_only: bool = False
    await_transfer: Optional[str] = None
    export: Optional[Dict[str, Any]] = None
    slot: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    error: Optional[str] = None
    cancel_requested: bool = False

    # timestamps (time.time)
    ts_arrival: float = 0.0
    ts_admitted: Optional[float] = None
    ts_first_token: Optional[float] = None
    # last token-producing segment boundary — the previous edge of the
    # per-row ITL delta (serve.itl_ms, ISSUE 13); scheduler-stamped
    ts_last_tokens: Optional[float] = None
    ts_done: Optional[float] = None
    # SLO phase-attribution stamps (ISSUE 19): when the awaited
    # inbound transfer settled (landed OR failed — either way the
    # request stops charging the transfer phase), and when the prompt
    # pass finished (chunked or atomic) — the prefill/first-decode
    # boundary. None collapses the phase into its neighbor.
    ts_transfer: Optional[float] = None
    ts_prefill_done: Optional[float] = None

    def phases(self) -> Dict[str, float]:
        """The fixed SLO phase vector (ms): adjacent differences over
        the stamped timeline arrival → transfer settled → admitted →
        prefill done → first token → done, each clamped ≥ 0 — so the
        phases SUM to the client-observed e2e latency exactly (the
        attribution identity the tier's breakdown histograms pin).
        ``place`` is the router's phase, 0 at the replica."""
        t_arr = self.ts_arrival
        t_done = self.ts_done if self.ts_done is not None else t_arr

        def clamp(t, lo, hi):
            return lo if t is None else min(max(t, lo), hi)

        t_tx = clamp(self.ts_transfer, t_arr, t_done)
        t_adm = clamp(self.ts_admitted, t_tx, t_done)
        t_pf = clamp(self.ts_prefill_done, t_adm, t_done)
        t_ft = clamp(self.ts_first_token, t_pf, t_done)
        return {
            "transfer": (t_tx - t_arr) * 1e3,
            "queue_wait": (t_adm - t_tx) * 1e3,
            "place": 0.0,
            "prefill": (t_pf - t_adm) * 1e3,
            "first_decode": (t_ft - t_pf) * 1e3,
            "decode_steady": (t_done - t_ft) * 1e3,
        }

    _done_event: threading.Event = field(default_factory=threading.Event,
                                         repr=False)

    def __post_init__(self):
        if not self.id:
            self.id = f"req-{next(_req_counter)}"
        if self.ts_arrival == 0.0:
            self.ts_arrival = time.time()
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size < 1:
            raise ValueError("prompt must have at least one token")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )

    # ---- blocking result surface (caller threads) -------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done_event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Block until terminal, then return a summary dict. Raises
        ``TimeoutError`` if the request is still in flight after
        ``timeout`` seconds."""
        if not self._done_event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} still {self.state.value} after "
                f"{timeout}s"
            )
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "state": self.state.value,
            "tokens": list(self.tokens),
            "n_tokens": len(self.tokens),
            "error": self.error,
            "metrics": self.timing(),
        }

    def timing(self) -> Dict[str, Optional[float]]:
        """Per-request latency breakdown in milliseconds."""
        def ms(a, b):
            return None if a is None or b is None else round((b - a) * 1e3, 3)

        return {
            "queue_wait_ms": ms(self.ts_arrival, self.ts_admitted),
            "ttft_ms": ms(self.ts_arrival, self.ts_first_token),
            "decode_ms": ms(self.ts_first_token, self.ts_done),
            "e2e_ms": ms(self.ts_arrival, self.ts_done),
        }

    # ---- scheduler-side helpers -------------------------------------
    def expired(self, now: float) -> bool:
        return self.deadline_ts is not None and now > self.deadline_ts

    def effective_prompt(self) -> np.ndarray:
        """Prompt + already-generated tokens — what a mid-decode-
        evicted request (paged pool ran dry, ISSUE 11) re-joins with:
        the next sample's logical position and RNG key are then
        exactly where the uninterrupted run's would be, so the retry
        completes token-identically (and the published prefix pages
        make the re-prefill a cache hit)."""
        if not self.tokens:
            return self.prompt_ids
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.tokens, np.int32)])

    def effective_len(self) -> int:
        """``effective_prompt().size`` without materializing the
        concatenation (hint/accounting paths that only need the
        length)."""
        return int(self.prompt_ids.size) + len(self.tokens)

    def remaining_new(self) -> int:
        """Decode budget still unspent (= ``max_new_tokens`` for a
        fresh request)."""
        return self.max_new_tokens - len(self.tokens)

    def finalize(self, state: RequestState,
                 error: Optional[str] = None) -> None:
        """Terminal transition (scheduler thread): idempotent — the
        first terminal state wins."""
        if self._done_event.is_set():
            return
        self.state = state
        self.error = error
        if self.ts_done is None:  # the scheduler stamps with ITS clock
            self.ts_done = time.time()
        self._done_event.set()
