"""Slot pool: the device-state half of slot-level continuous batching.

One :class:`SlotPool` owns the fixed-shape decode state for a (prompt
bucket, slot count) pair — KV cache, token buffer, and the per-row
bookkeeping vectors — and drives it through the two compiled
executables from :mod:`tpuflow.infer.generate`:

- ``join``: admit requests into freed rows at a segment boundary via a
  per-slot prefill merged into the shared cache;
- ``segment``: advance ALL rows ``seg`` decode steps, then hand the
  newly written token block back to the host.

The pool is deliberately policy-free: WHICH requests join, deadline and
cancellation sweeps, and metric accounting live in
:mod:`tpuflow.serve.scheduler`. Everything here is shape discipline:

- segments stay on the grid ``t ∈ {bucket-1 + k·seg}`` and never run
  past ``length-1`` (``lax.dynamic_update_slice`` clamps out-of-range
  writes, so an unaligned tail would silently corrupt the last column);
  the horizon is therefore rounded UP to whole segments at build time;
- a request may join at boundary ``t`` only if its whole budget fits
  the remaining horizon (``t + max_new <= length-1``);
- when the horizon is exhausted and every row has drained, ``reset()``
  rewinds to a fresh round WITHOUT zeroing device buffers — stale KV
  is unreachable by construction (masked below each row's pads, and
  above the live cache index).

NOT thread-safe: exactly one thread (the scheduler's) may touch a pool.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

from tpuflow.obs import trace
from tpuflow.serve.request import Request


class SlotPool:
    """Fixed pool of decode slots over one shared KV cache."""

    def __init__(
        self,
        model,
        params,
        bucket: int,
        slots: int,
        max_new_cap: int,
        seg: int = 8,
        rounds: int = 3,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ):
        import jax

        from tpuflow.infer.generate import (
            serve_join_fn,
            serve_pool_arrays,
            serve_segment_fn,
        )

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_new_cap < 1:
            raise ValueError(f"max_new_cap must be >= 1, got {max_new_cap}")
        if rounds < 1:
            raise ValueError(f"rounds must be >= 1, got {rounds}")
        self.bucket = int(bucket)
        self.slots = int(slots)
        self.seg = max(1, int(seg))
        self.max_new_cap = int(max_new_cap)
        # decode horizon: ``rounds`` budgets of room past the bucket,
        # rounded up to whole segments so the step grid ends exactly at
        # length-1 (the no-clamped-writes invariant)
        decode_room = math.ceil(rounds * self.max_new_cap / self.seg) * self.seg
        self.length = self.bucket + decode_room
        self.eos_id = eos_id
        self.params = params
        self._rng = jax.random.key(int(seed))
        self._join = serve_join_fn(model, self.slots, self.length, self.bucket)
        self._segment = serve_segment_fn(
            model, self.slots, self.length, self.seg, float(temperature),
            top_k, top_p, eos_id,
        )
        self.cache, self.out = serve_pool_arrays(model, self.slots,
                                                 self.length)
        self.pad_lens = np.zeros((self.slots,), np.int32)
        self.stream_ids = np.zeros((self.slots,), np.int32)
        self.last_pos = np.zeros((self.slots,), np.int32)
        self.done = np.ones((self.slots,), bool)
        self.occupants: List[Optional[Request]] = [None] * self.slots
        self.t = self.bucket - 1
        self.rounds_started = 0
        self.segments_run = 0

    # ---- capacity queries ------------------------------------------
    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.occupants) if r is None]

    def has_live(self) -> bool:
        return any(r is not None for r in self.occupants)

    def live_count(self) -> int:
        return sum(r is not None for r in self.occupants)

    def can_admit(self, max_new_tokens: int) -> bool:
        """Whether a request with this budget can join at the CURRENT
        boundary and still finish inside the horizon."""
        return (max_new_tokens <= self.max_new_cap
                and self.t + max_new_tokens <= self.length - 1)

    def can_step(self) -> bool:
        return self.t + self.seg <= self.length - 1

    def reset(self) -> None:
        """Start a fresh round (only valid with every slot free). The
        device buffers are NOT zeroed: stale KV/tokens are masked out
        of every attention read and never re-read by the host."""
        if self.has_live():
            raise RuntimeError("reset() with occupied slots would drop "
                               "in-flight requests")
        self.t = self.bucket - 1
        self.done[:] = True
        self.last_pos[:] = 0
        self.rounds_started += 1

    # ---- the two device transitions --------------------------------
    def join(self, admits: List[Tuple[int, Request]]) -> None:
        """Admit ``(slot, request)`` pairs at the current boundary: one
        per-slot prefill pass, merged into the live cache only for the
        joining rows."""
        import jax.numpy as jnp

        if not admits:
            return
        prompts = np.zeros((self.slots, self.bucket), np.int32)
        mask = np.zeros((self.slots,), bool)
        for slot, req in admits:
            if self.occupants[slot] is not None:
                raise RuntimeError(f"slot {slot} is occupied")
            p = int(req.prompt_ids.size)
            if not 1 <= p <= self.bucket:
                raise ValueError(
                    f"prompt length {p} outside (0, bucket={self.bucket}]"
                )
            if not self.can_admit(req.max_new_tokens):
                raise RuntimeError(
                    f"request {req.id} (max_new={req.max_new_tokens}) "
                    f"does not fit the horizon at t={self.t}"
                )
            prompts[slot, self.bucket - p:] = req.prompt_ids
            mask[slot] = True
            self.pad_lens[slot] = self.t - p + 1
            self.stream_ids[slot] = req.stream_id
            self.last_pos[slot] = self.t + req.max_new_tokens
            self.done[slot] = False
            self.occupants[slot] = req
            req.slot = slot
        # one span per prefill-join pass — the serve-side "prefill
        # chunk"; request ids ride as attrs so the pass is attributable
        with trace.span("serve.prefill_join", phase="prefill",
                        bucket=self.bucket, n=len(admits), t=self.t,
                        requests=",".join(r.id for _, r in admits)):
            self.cache, self.out = self._join(
                self.params, self.cache, self.out,
                jnp.asarray(self.pad_lens), jnp.asarray(prompts),
                jnp.asarray(mask), self.t,
            )

    def evict(self, slot: int) -> Optional[Request]:
        """Free a slot WITHOUT waiting for its row to finish
        (cancellation / deadline expiry): the row is marked done so the
        next segment stops sampling it, and the slot is immediately
        joinable."""
        req = self.occupants[slot]
        self.occupants[slot] = None
        self.done[slot] = True
        self.last_pos[slot] = 0
        return req

    def run_segment(self):
        """Advance ``seg`` steps. Returns ``(events, live_before)``
        where events is ``[(slot, request, new_token_ids, finished)]``
        per occupied slot (``new_token_ids`` excludes the EOS token and
        anything past the request's budget — the text-surface trimming
        contract of packaging.lm.generate_text)."""
        import jax.numpy as jnp

        if not self.can_step():
            raise RuntimeError(
                f"segment would overrun the horizon (t={self.t}, "
                f"seg={self.seg}, length={self.length})"
            )
        t0 = self.t
        live_before = self.live_count()
        # the decode-segment span covers dispatch AND the host fetch of
        # done/toks — i.e. the real wall cost of seg decode steps
        with trace.span("serve.decode_segment", phase="decode",
                        bucket=self.bucket, t0=t0, seg=self.seg,
                        live=live_before):
            self.cache, self.out, done_dev, toks = self._segment(
                self.params, self.cache, self.out, jnp.asarray(self.done),
                jnp.asarray(self.pad_lens), jnp.asarray(self.stream_ids),
                jnp.asarray(self.last_pos), self._rng, t0,
            )
            self.t = t0 + self.seg
            self.segments_run += 1
            was_done = self.done
            self.done = np.array(done_dev)
            toks = np.asarray(toks)
        events = []
        for slot, req in enumerate(self.occupants):
            if req is None or was_done[slot]:
                continue
            budget = int(self.last_pos[slot]) - t0  # row steps remaining
            new: List[int] = []
            finished = bool(self.done[slot])
            for tok in toks[slot][: max(0, min(self.seg, budget))]:
                if self.eos_id is not None and int(tok) == self.eos_id:
                    break
                new.append(int(tok))
            events.append((slot, req, new, finished))
        return events, live_before
