"""Slot pool: the device-state half of slot-level continuous batching.

One :class:`SlotPool` owns the fixed-shape decode state for a (prompt
bucket, slot count) pair — KV cache, token buffer, and the per-row
bookkeeping vectors — and drives it through the two compiled
executables from :mod:`tpuflow.infer.generate`:

- ``join``: admit requests into freed rows at a segment boundary via a
  per-slot prefill merged into the shared cache;
- ``segment``: advance ALL rows ``seg`` decode steps, then hand the
  newly written token block back to the host.

The pool is deliberately policy-free: WHICH requests join, deadline and
cancellation sweeps, and metric accounting live in
:mod:`tpuflow.serve.scheduler`. Everything here is shape discipline:

- segments stay on the grid ``t ∈ {bucket-1 + k·seg}`` and never run
  past ``length-1`` (``lax.dynamic_update_slice`` clamps out-of-range
  writes, so an unaligned tail would silently corrupt the last column);
  the horizon is therefore rounded UP to whole segments at build time;
- a request may join at boundary ``t`` only if its whole budget fits
  the remaining horizon (``t + max_new <= length-1``);
- when the horizon is exhausted and every row has drained, ``reset()``
  rewinds to a fresh round WITHOUT zeroing device buffers — stale KV
  is unreachable by construction (masked below each row's pads, and
  above the live cache index).

NOT thread-safe: exactly one thread (the scheduler's) may touch a pool.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

import numpy as np

from tpuflow.obs import memory as _mem
from tpuflow.obs import trace
from tpuflow.serve.request import Request


class SlotPool:
    """Fixed pool of decode slots over one shared KV cache."""

    def __init__(
        self,
        model,
        params,
        bucket: int,
        slots: int,
        max_new_cap: int,
        seg: int = 8,
        rounds: int = 3,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ):
        import jax

        from tpuflow.infer.generate import (
            serve_join_fn,
            serve_pool_arrays,
            serve_segment_fn,
        )

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_new_cap < 1:
            raise ValueError(f"max_new_cap must be >= 1, got {max_new_cap}")
        if rounds < 1:
            raise ValueError(f"rounds must be >= 1, got {rounds}")
        self.bucket = int(bucket)
        self.slots = int(slots)
        self.seg = max(1, int(seg))
        self.max_new_cap = int(max_new_cap)
        # decode horizon: ``rounds`` budgets of room past the bucket,
        # rounded up to whole segments so the step grid ends exactly at
        # length-1 (the no-clamped-writes invariant)
        decode_room = math.ceil(rounds * self.max_new_cap / self.seg) * self.seg
        self.length = self.bucket + decode_room
        self.eos_id = eos_id
        self.params = params
        self._rng = jax.random.key(int(seed))
        self._join = serve_join_fn(model, self.slots, self.length, self.bucket)
        self._segment = serve_segment_fn(
            model, self.slots, self.length, self.seg, float(temperature),
            top_k, top_p, eos_id,
        )
        self.cache, self.out = serve_pool_arrays(model, self.slots,
                                                 self.length)
        self.pad_lens = np.zeros((self.slots,), np.int32)
        self.stream_ids = np.zeros((self.slots,), np.int32)
        self.last_pos = np.zeros((self.slots,), np.int32)
        self.done = np.ones((self.slots,), bool)
        self.occupants: List[Optional[Request]] = [None] * self.slots
        self.t = self.bucket - 1
        self.rounds_started = 0
        self.segments_run = 0

    # ---- capacity queries ------------------------------------------
    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.occupants) if r is None]

    def has_live(self) -> bool:
        return any(r is not None for r in self.occupants)

    def live_count(self) -> int:
        return sum(r is not None for r in self.occupants)

    def decode_live(self) -> bool:
        """Contiguous joins are atomic — every occupant decodes (the
        chunked-prefill distinction exists only on the paged pool)."""
        return self.has_live()

    def can_admit(self, max_new_tokens: int) -> bool:
        """Whether a request with this budget can join at the CURRENT
        boundary and still finish inside the horizon."""
        return (max_new_tokens <= self.max_new_cap
                and self.t + max_new_tokens <= self.length - 1)

    def can_step(self) -> bool:
        return self.t + self.seg <= self.length - 1

    def reset(self) -> None:
        """Start a fresh round (only valid with every slot free). The
        device buffers are NOT zeroed: stale KV/tokens are masked out
        of every attention read and never re-read by the host."""
        if self.has_live():
            raise RuntimeError("reset() with occupied slots would drop "
                               "in-flight requests")
        self.t = self.bucket - 1
        self.done[:] = True
        self.last_pos[:] = 0
        self.rounds_started += 1

    # ---- the two device transitions --------------------------------
    def join(self, admits: List[Tuple[int, Request]]) -> None:
        """Admit ``(slot, request)`` pairs at the current boundary: one
        per-slot prefill pass, merged into the live cache only for the
        joining rows."""
        import jax.numpy as jnp

        if not admits:
            return
        prompts = np.zeros((self.slots, self.bucket), np.int32)
        mask = np.zeros((self.slots,), bool)
        for slot, req in admits:
            if self.occupants[slot] is not None:
                raise RuntimeError(f"slot {slot} is occupied")
            p = int(req.prompt_ids.size)
            if not 1 <= p <= self.bucket:
                raise ValueError(
                    f"prompt length {p} outside (0, bucket={self.bucket}]"
                )
            if not self.can_admit(req.max_new_tokens):
                raise RuntimeError(
                    f"request {req.id} (max_new={req.max_new_tokens}) "
                    f"does not fit the horizon at t={self.t}"
                )
            prompts[slot, self.bucket - p:] = req.prompt_ids
            mask[slot] = True
            self.pad_lens[slot] = self.t - p + 1
            self.stream_ids[slot] = req.stream_id
            self.last_pos[slot] = self.t + req.max_new_tokens
            self.done[slot] = False
            self.occupants[slot] = req
            req.slot = slot
        # one span per prefill-join pass — the serve-side "prefill
        # chunk"; request ids ride as attrs so the pass is attributable
        with trace.span("serve.prefill_join", phase="prefill",
                        bucket=self.bucket, n=len(admits), t=self.t,
                        requests=",".join(r.id for _, r in admits)):
            self.cache, self.out = self._join(
                self.params, self.cache, self.out,
                jnp.asarray(self.pad_lens), jnp.asarray(prompts),
                jnp.asarray(mask), self.t,
            )
        # functional update replaced the buffers: keep the ledger's
        # kv_pages tag on the LIVE arrays (the old ones just died)
        _mem.tag("kv_pages", (self.cache, self.out))

    def evict(self, slot: int) -> Optional[Request]:
        """Free a slot WITHOUT waiting for its row to finish
        (cancellation / deadline expiry): the row is marked done so the
        next segment stops sampling it, and the slot is immediately
        joinable."""
        req = self.occupants[slot]
        self.occupants[slot] = None
        self.done[slot] = True
        self.last_pos[slot] = 0
        return req

    def warm(self) -> None:
        """Pre-compile the pool's executables with a throwaway request
        (join one, decode one segment, rewind) — server-startup work,
        not first-request TTFT. No-op on a pool that has already run."""
        if self.segments_run or self.has_live():
            return
        self.join([(0, Request(prompt_ids=np.ones(1, np.int32),
                               max_new_tokens=1))])
        self.run_segment()
        self.evict(0)
        self.reset()

    def run_segment(self):
        """Advance ``seg`` steps. Returns ``(events, live_before)``
        where events is ``[(slot, request, new_token_ids, finished)]``
        per occupied slot (``new_token_ids`` excludes the EOS token and
        anything past the request's budget — the text-surface trimming
        contract of packaging.lm.generate_text)."""
        import jax.numpy as jnp

        if not self.can_step():
            raise RuntimeError(
                f"segment would overrun the horizon (t={self.t}, "
                f"seg={self.seg}, length={self.length})"
            )
        t0 = self.t
        live_before = self.live_count()
        # the decode-segment span covers dispatch AND the host fetch of
        # done/toks — i.e. the real wall cost of seg decode steps
        with trace.span("serve.decode_segment", phase="decode",
                        bucket=self.bucket, t0=t0, seg=self.seg,
                        live=live_before):
            self.cache, self.out, done_dev, toks = self._segment(
                self.params, self.cache, self.out, jnp.asarray(self.done),
                jnp.asarray(self.pad_lens), jnp.asarray(self.stream_ids),
                jnp.asarray(self.last_pos), self._rng, t0,
            )
            self.t = t0 + self.seg
            self.segments_run += 1
            was_done = self.done
            self.done = np.array(done_dev)
            toks = np.asarray(toks)
        _mem.tag("kv_pages", (self.cache, self.out))
        events = []
        for slot, req in enumerate(self.occupants):
            if req is None or was_done[slot]:
                continue
            budget = int(self.last_pos[slot]) - t0  # row steps remaining
            new: List[int] = []
            finished = bool(self.done[slot])
            for tok in toks[slot][: max(0, min(self.seg, budget))]:
                if self.eos_id is not None and int(tok) == self.eos_id:
                    break
                new.append(int(tok))
            events.append((slot, req, new, finished))
        return events, live_before


class PagedSlotPool:
    """Slot pool over the PAGED KV store (ISSUE 6): same scheduler-
    facing contract as :class:`SlotPool` (free_slots / join /
    run_segment / evict / warm), completely different memory model.

    - KV lives in the scheduler-wide :class:`tpuflow.serve.pages.
      PagedKV` page store; this pool owns only the per-slot
      bookkeeping (page tables, positions) and a (slots, length) token
      buffer. Admission capacity is PAGES, not slot-shaped slabs — the
      scheduler plans pages per request (``PagedKV.plan``) before
      handing the plan to :meth:`join`.
    - rows live at their LOGICAL positions with per-row write indexes:
      no left-pads, no shared horizon, no reset/rounds machinery — a
      freed slot restarts at position 0, so ``can_admit`` never
      depends on how far other rows have decoded (the decoupling from
      bucket quantization the contiguous pool cannot offer).
    - the join is WIDTH-BUCKETED: prefix-cache hits prefill only their
      uncached suffix through the narrowest compiled window, and a
      full-prefix hit skips the model pass entirely (width 1 = token
      write only).

    NOT thread-safe: exactly one thread (the scheduler's) may touch a
    pool — and all pools of one scheduler share one PagedKV, so that
    single thread owns the allocator and device store too.
    """

    def __init__(
        self,
        model,
        params,
        kv,  # tpuflow.serve.pages.PagedKV (shared across pools)
        bucket: int,
        slots: int,
        max_new_cap: int,
        seg: int = 8,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
        spec_k: int = 0,
        draft_model=None,
        draft_params=None,
    ):
        import jax
        import jax.numpy as jnp

        from tpuflow.infer.generate import (
            paged_join_fn,
            paged_segment_fn,
            spec_draft_fn,
            spec_verify_fn,
        )

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_new_cap < 1:
            raise ValueError(f"max_new_cap must be >= 1, got {max_new_cap}")
        if spec_k and (draft_model is None or draft_params is None
                       or kv.draft_cache is None):
            raise ValueError(
                "spec_k > 0 needs a draft model AND its params AND a "
                "PagedKV built with draft_model= (the draft page store)"
            )
        self.bucket = int(bucket)
        self.slots = int(slots)
        self.seg = max(1, int(seg))
        self.max_new_cap = int(max_new_cap)
        self.kv = kv
        self.eos_id = eos_id
        self.params = params
        # MoE load harvest (ISSUE 18): segment fns of an MoE model
        # return an extra (n_experts,) routed-token count; the latest
        # harvest is stashed for the scheduler's gauges + admission gate
        self.n_experts = int(getattr(model, "n_experts", 0) or 0)
        self.last_expert_load: Optional[np.ndarray] = None
        ps = kv.spec.page_size
        # token horizon: a row's final token index is p + max_new - 1
        # <= bucket + cap - 1; its KV never exceeds p + max_new - 1
        # positions. Each row's horizon is ITS OWN — nothing here
        # depletes as other rows decode.
        self.length = self.bucket + self.max_new_cap
        self.n_row_pages = math.ceil((self.length - 1) / ps)
        self._rng = jax.random.key(int(seed))
        # hoisted dense-window segments (ISSUE 11): gather the rows'
        # pages ONCE per segment into per-row dense windows and run
        # the steps contiguous-style, with a pow2 TABLE-WIDTH menu so
        # young rows attend over short windows. Disabled for int8
        # stores (the window would need requantization on the way
        # back) and when the fused decode kernel is active (the kernel
        # IS the per-step fast path and reads pages directly).
        kernel_on = getattr(kv.spec, "kernel", None)
        if kernel_on is None:
            from tpuflow.core.hw import is_tpu_backend

            kernel_on = is_tpu_backend()
        # no hoisted menu for speculative pools: run_segment routes to
        # _run_spec_round (draft + verify dispatches) before ever
        # consulting a plain segment, so the menu would only be built
        # and warmed for nothing
        self._hoist = (kv.spec.quant is None and not kernel_on
                       and not int(spec_k))
        # three width classes, not a full pow2 ladder: each class is a
        # compiled executable (per sampling config per bucket), and the
        # win concentrates at the bottom — brand-new rows (w=1..2)
        # attend over a tiny window while full-budget rows pay the
        # whole horizon anyway. {1, 2, NP} keeps the compile budget at
        # 3x the old single class.
        wmenu = [w for w in (1, 2) if w < self.n_row_pages]
        wmenu.append(self.n_row_pages)
        self._seg_widths = wmenu
        if self._hoist:
            self._segment = {
                wd: paged_segment_fn(
                    model, kv.spec, self.slots, self.length,
                    self.n_row_pages, self.seg, float(temperature),
                    top_k, top_p, eos_id, table_width=wd)
                for wd in wmenu
            }
        else:
            self._segment = {None: paged_segment_fn(
                model, kv.spec, self.slots, self.length,
                self.n_row_pages, self.seg, float(temperature),
                top_k, top_p, eos_id)}
        # width menu (powers of two + the full bucket): the suffix a
        # join must write is width = p - matched <= bucket tokens; the
        # narrowest compiled window that fits is used, so prefix hits
        # genuinely skip prefill compute (width 1 = no model pass)
        menu = [1]
        w = 2
        while w < self.bucket:
            menu.append(w)
            w *= 2
        menu.append(self.bucket)
        self._join = {
            wd: paged_join_fn(model, kv.spec, self.slots, self.length,
                              self.n_row_pages, wd)
            for wd in menu
        }
        self._widths = menu
        # speculative decoding (ISSUE 9): one ROUND per boundary —
        # k draft proposals, ONE blockwise target verify over k+1
        # positions, oracle-parity acceptance. The draft's KV rides
        # the same page tables (kv.draft_cache); its prompt prefill
        # reuses the width-bucketed join menu against the draft model.
        self.spec_k = int(spec_k)
        self.draft_params = draft_params
        if self.spec_k:
            self._spec_draft = spec_draft_fn(
                draft_model, kv.spec, self.slots, self.length,
                self.n_row_pages, self.spec_k, float(temperature),
                top_k, top_p)
            self._spec_verify = spec_verify_fn(
                model, kv.spec, self.slots, self.length,
                self.n_row_pages, self.spec_k, float(temperature),
                top_k, top_p, eos_id)
            self._join_draft = {
                wd: paged_join_fn(draft_model, kv.spec, self.slots,
                                  self.length, self.n_row_pages, wd)
                for wd in menu
            }
        self.spec_on = np.ones((self.slots,), bool)
        self.last_spec_stats = (0, 0)  # (drafted, accepted) last round
        self.out = jnp.zeros((self.slots, self.length), jnp.int32)
        self.page_table = np.zeros((self.slots, self.n_row_pages),
                                   np.int32)  # 0 = the write sink
        self.pos = np.zeros((self.slots,), np.int32)
        self.kv_limit = np.zeros((self.slots,), np.int32)
        self.last_tok = np.zeros((self.slots,), np.int32)
        self.stream_ids = np.zeros((self.slots,), np.int32)
        self.done = np.ones((self.slots,), bool)
        self.occupants: List[Optional[Request]] = [None] * self.slots
        self.plans: List[Optional[Any]] = [None] * self.slots
        # chunked prefill (ISSUE 13): rows admitted but still mid-
        # prefill — they hold a slot, plan and page table like any
        # occupant, but ride the segment fn's ``done`` mask (no decode,
        # KV writes to the sink) until advance_prefill() finishes their
        # prompt in budget-bounded chunks interleaved with segments
        self.prefilling = np.zeros((self.slots,), bool)
        self.prefill_next = np.zeros((self.slots,), np.int32)
        self._prefill_full: List[Optional[np.ndarray]] = [None] * self.slots
        self._prefill_cursor = 0  # round-robin over mid-prefill slots
        self.segments_run = 0
        self.last_join_width = 0  # observability: the window bench bills
        self._warmed = False

    # ---- capacity queries (SlotPool-compatible surface) -------------
    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.occupants) if r is None]

    def has_live(self) -> bool:
        return any(r is not None for r in self.occupants)

    def live_count(self) -> int:
        return sum(r is not None for r in self.occupants)

    def decode_live(self) -> bool:
        """Any occupant actually DECODING — admitted and past its
        chunked prefill. The scheduler runs a segment only when this
        is true: a pool whose every occupant is still mid-prefill
        makes progress through :meth:`advance_prefill`, not segments."""
        return any(r is not None and not self.prefilling[i]
                   for i, r in enumerate(self.occupants))

    def can_admit(self, max_new_tokens: int) -> bool:
        """Budget sanity only — PAGE availability is the scheduler's
        question to :meth:`PagedKV.plan` (which may say no even when a
        slot is free: that request then stays queued)."""
        return max_new_tokens <= self.max_new_cap

    def can_step(self) -> bool:
        return True  # per-row positions: no shared horizon to exhaust

    def reset(self) -> None:
        """No-op: the paged pool has no shared horizon to rewind."""

    # ---- device transitions -----------------------------------------
    def join(self, admits: List[Tuple[int, Request, Any]]) -> None:
        """Admit ``(slot, request, plan)`` triples (plans from
        :meth:`PagedKV.plan`): execute COW forks, write each row's
        uncached suffix + prefill it through the page table, publish
        completed prompt pages into the prefix tree.

        A request carrying already-generated tokens (mid-decode page
        eviction, ISSUE 11) joins with its EFFECTIVE prompt
        (prompt + generated) and its REMAINING budget — positions,
        sampling keys and the kv limit land exactly where the
        uninterrupted run's would, so the retry is token-identical."""
        import jax.numpy as jnp

        if not admits:
            return
        kv = self.kv
        widths = np.zeros((self.slots,), np.int32)
        starts = np.zeros((self.slots,), np.int32)
        fulls = {}
        need_w = 1
        for slot, req, plan in admits:
            if self.occupants[slot] is not None:
                raise RuntimeError(f"slot {slot} is occupied")
            full = req.effective_prompt()
            fulls[slot] = full
            p = int(full.size)
            budget = req.remaining_new()
            if not 1 <= p <= self.bucket:
                raise ValueError(
                    f"prompt length {p} outside (0, bucket={self.bucket}]"
                )
            if budget > self.max_new_cap or budget < 1:
                raise RuntimeError(
                    f"request {req.id} budget {budget} outside the "
                    f"pool's (0, max_new_cap={self.max_new_cap}]"
                )
            kv.execute_forks(plan)
            row = self.page_table[slot]
            row[:] = 0
            row[: len(plan.table)] = plan.table
            starts[slot] = plan.start
            widths[slot] = plan.width
            need_w = max(need_w, plan.width)
            self.pos[slot] = p - 1
            self.kv_limit[slot] = p + budget - 1
            self.last_tok[slot] = p + budget - 1
            self.stream_ids[slot] = req.stream_id
            self.spec_on[slot] = bool(getattr(req, "speculate", True))
            self.done[slot] = False
            self.occupants[slot] = req
            self.plans[slot] = plan
            req.slot = slot
        w = next(wd for wd in self._widths if wd >= need_w)
        self.last_join_width = w
        tokens = np.zeros((self.slots, w), np.int32)
        for slot, req, plan in admits:
            tokens[slot, : plan.width] = fulls[slot][plan.start:]
        with trace.span("serve.prefill_join", phase="prefill",
                        bucket=self.bucket, n=len(admits), width=w,
                        hits=sum(pl.hit for _, _, pl in admits),
                        requests=",".join(r.id for _, r, _ in admits)):
            self.kv.cache, self.out = self._join[w](
                self.params, self.kv.cache, self.out,
                jnp.asarray(tokens), jnp.asarray(starts),
                jnp.asarray(widths), jnp.asarray(self.page_table),
            )
            if self.spec_k:
                # draft prefill through the SAME page table/suffix
                # window: shared-prefix pages then carry BOTH models'
                # KV, so a prefix-cache hit skips both prefills. The
                # out it returns is content-identical (same token
                # writes) — keep the target join's.
                self.kv.draft_cache, _ = self._join_draft[w](
                    self.draft_params, self.kv.draft_cache, self.out,
                    jnp.asarray(tokens), jnp.asarray(starts),
                    jnp.asarray(widths), jnp.asarray(self.page_table),
                )
        _mem.tag("kv_pages", (self.kv.cache, self.out))
        if self.spec_k:
            _mem.tag("kv_draft", self.kv.draft_cache)
        for slot, req, plan in admits:
            # publish the EFFECTIVE prompt (a resumed request's
            # includes its generated tokens — the plan's n_full was
            # computed against exactly this sequence)
            kv.insert_prompt(fulls[slot], plan)

    def segment_advance(self) -> int:
        """KV positions one boundary can write per row: a speculative
        round's verify covers ``spec_k + 1`` positions (for EVERY live
        row — opt-out rows' windows are rewritten too), a plain
        segment ``seg``."""
        return (self.spec_k + 1) if self.spec_k else self.seg

    def segment_width(self) -> Optional[int]:
        """Narrowest compiled table width covering every live row's
        pages THIS segment (reads span ``[0, pos)``, writes reach
        ``min(pos + advance, kv_limit)``) — the hoisted segment's
        dense window is ``width × page_size`` positions long, so young
        rows attend over short windows. None on the per-step path."""
        if not self._hoist:
            return None
        ps = self.kv.spec.page_size
        adv = self.segment_advance()
        need = 1
        for slot, req in enumerate(self.occupants):
            if req is None or self.done[slot]:
                continue
            cover = min(int(self.pos[slot]) + adv,
                        int(self.kv_limit[slot]))
            need = max(need, -(-cover // ps))
        return next(w for w in self._seg_widths if w >= need)

    # ---- chunked prefill (ISSUE 13) ---------------------------------
    def begin_chunked(self, slot: int, req: Request, plan: Any) -> None:
        """Admit one request as a CHUNKED-prefill occupant: all of
        :meth:`join`'s bookkeeping (plan, page table, positions, COW
        forks) but NO device dispatch — the prompt's uncached suffix is
        prefilled by successive :meth:`advance_prefill` chunks, each a
        bounded suffix-join through the existing width menu, so decode
        segments for the other rows interleave between chunks instead
        of waiting out one full-width join. The row rides the segment
        fn's ``done`` mask until its prefill completes (KV writes to
        the sink, emitted fill tokens discarded by the harvest)."""
        if self.occupants[slot] is not None:
            raise RuntimeError(f"slot {slot} is occupied")
        full = req.effective_prompt()
        p = int(full.size)
        budget = req.remaining_new()
        if not 1 <= p <= self.bucket:
            raise ValueError(
                f"prompt length {p} outside (0, bucket={self.bucket}]")
        if plan.width < 2:
            raise RuntimeError(
                "chunked admission needs an uncached suffix (width >= "
                "2); a full-prefix hit is already a width-1 join")
        self.kv.execute_forks(plan)
        row = self.page_table[slot]
        row[:] = 0
        row[: len(plan.table)] = plan.table
        self.pos[slot] = p - 1
        self.kv_limit[slot] = p + budget - 1
        self.last_tok[slot] = p + budget - 1
        self.stream_ids[slot] = req.stream_id
        self.spec_on[slot] = bool(getattr(req, "speculate", True))
        self.done[slot] = True  # not decoding yet
        self.prefilling[slot] = True
        self.prefill_next[slot] = int(plan.start)
        self._prefill_full[slot] = full
        self.occupants[slot] = req
        self.plans[slot] = plan
        req.slot = slot

    def advance_prefill(self, budget: int) -> Optional[Tuple[int, int, bool]]:
        """Run ONE budget-bounded prefill chunk for the next mid-
        prefill slot (round-robin): a suffix-join dispatch covering at
        most ``budget`` KV positions through the narrowest compiled
        width that fits — the same executable (and the same KV values,
        position by position) an atomic join would have used, so
        chunked outputs are token-identical to unchunked ones.

        Completed FULL pages publish into the prefix tree at every
        chunk boundary, so an evicted or duplicate request hits the
        partial prefix mid-flight. Returns ``(slot, positions_written,
        completed)`` or None when no row is mid-prefill. On the final
        chunk (frontier reaches p-1) the row flips live: the next
        decode segment appends the last prompt token's KV and samples
        its first token, exactly like an atomic join's row."""
        import jax.numpy as jnp

        pf = [i for i in range(self.slots) if self.prefilling[i]]
        if not pf:
            return None
        slot = pf[self._prefill_cursor % len(pf)]
        self._prefill_cursor += 1
        req = self.occupants[slot]
        plan = self.plans[slot]
        full = self._prefill_full[slot]
        p = int(full.size)
        f = int(self.prefill_next[slot])
        c = min(max(1, int(budget)), p - 1 - f)
        w = next(wd for wd in self._widths if wd >= c + 1)
        self.last_join_width = w
        tokens = np.zeros((self.slots, w), np.int32)
        tokens[slot, : c + 1] = full[f: f + c + 1]
        starts = np.zeros((self.slots,), np.int32)
        starts[slot] = f
        widths = np.zeros((self.slots,), np.int32)
        widths[slot] = c + 1
        with trace.span("serve.prefill_chunk", phase="prefill",
                        bucket=self.bucket, slot=slot, start=f,
                        tokens=c, width=w, requests=req.id):
            self.kv.cache, self.out = self._join[w](
                self.params, self.kv.cache, self.out,
                jnp.asarray(tokens), jnp.asarray(starts),
                jnp.asarray(widths), jnp.asarray(self.page_table),
            )
            if self.spec_k:
                # the draft prefills the same window (shared pages
                # carry both models' KV — the publish contract)
                self.kv.draft_cache, _ = self._join_draft[w](
                    self.draft_params, self.kv.draft_cache, self.out,
                    jnp.asarray(tokens), jnp.asarray(starts),
                    jnp.asarray(widths), jnp.asarray(self.page_table),
                )
        _mem.tag("kv_pages", (self.kv.cache, self.out))
        if self.spec_k:
            _mem.tag("kv_draft", self.kv.draft_cache)
        f2 = f + c
        self.prefill_next[slot] = f2
        # chunk-boundary publish: every page fully covered by the
        # written frontier joins the tree NOW (insert is idempotent
        # for chunks already present), bounded by the prompt's own
        # n_full — a duplicate prompt queued behind this one hits the
        # partial chain even if this row is later evicted
        if self.kv.prefix is not None:
            ps = self.kv.spec.page_size
            n = min(f2 // ps, plan.n_full)
            if n > 0:
                self.kv.prefix.insert(full[: n * ps], plan.table[:n])
        completed = f2 >= p - 1
        if completed:
            self.prefilling[slot] = False
            self._prefill_full[slot] = None
            self.done[slot] = False  # decodes from the next segment
        return slot, c, completed

    def join_ring(self, slot: int, req: Request, plan: Any,
                  n_shards: int) -> None:
        """Ring-attention prefill offload (ISSUE 13): prefill the
        prompt SEQUENCE-PARALLEL over ``n_shards`` devices (causal
        ring attention under shard_map — the training path's long-
        context machinery, striped layout for ring balance), scatter
        the harvested per-layer K/V into this plan's pages, and finish
        admission with a width-1 join (token write only — exactly the
        full-prefix-hit fast path). Per-device residency during
        prefill is O(p / n_shards): prompts beyond one device's
        prefill budget become servable, and paged decode afterwards is
        plain single-device decode."""
        from tpuflow.infer.generate import ring_prefill_kv

        full = req.effective_prompt()
        p = int(full.size)
        if not 1 <= p <= self.bucket:
            raise ValueError(
                f"prompt length {p} outside (0, bucket={self.bucket}]")
        padded = np.zeros((self.bucket,), np.int32)
        padded[:p] = full
        with trace.span("serve.ring_prefill", phase="prefill",
                        bucket=self.bucket, n_shards=n_shards,
                        tokens=p, requests=req.id):
            harvest = ring_prefill_kv(self.kv.model, self.params,
                                      padded[None, :], n_shards=n_shards)
            # the landing wholesale-rewrites the plan's private pages
            # from the matched frontier on — a partially-matched tail
            # page's COW copy would only be clobbered, so drop the
            # fork instead of executing it
            plan.forks = []
            self.kv.land_ring(plan, harvest, self.n_row_pages, p)
        # the harvest covered [0, p-1); admission completes as a
        # width-1 join (writes the final prompt token, whose KV the
        # first decode step appends) — plan start/width say so
        plan.start = p - 1
        plan.width = 1
        self.join([(slot, req, plan)])

    def extend_for_segment(self) -> Tuple[List[Tuple[int, Request]], int]:
        """Incremental page allocation (ISSUE 11): before a segment
        runs, grow every live row's plan to cover the positions this
        boundary will write (``pos .. min(pos+advance, kv_limit)-1``)
        — a position whose table slot still points at the sink would
        silently scatter its KV there and corrupt the row's reads.

        Returns ``(starved, extend_events)``: rows the allocator could
        not cover even after LRU pressure on the prefix tree. The
        SCHEDULER owns what happens to them (publish prefix → evict ONE
        → re-sweep: a single eviction's freed pages usually rescue the
        rest of the batch, so the pool can never deadlock against
        itself). Idempotent for covered rows — safe to re-run after an
        eviction."""
        ps = self.kv.spec.page_size
        adv = self.segment_advance()
        starved: List[Tuple[int, Request]] = []
        events = 0
        for slot, req in enumerate(self.occupants):
            if req is None or self.done[slot]:
                continue
            plan = self.plans[slot]
            if plan is None:  # pragma: no cover - defensive
                continue
            cover = min(int(self.pos[slot]) + adv,
                        int(self.kv_limit[slot]))
            need = max(1, -(-cover // ps))  # ceil
            if need > len(plan.table):
                have = len(plan.table)
                got = self.kv.extend(plan, need - have)
                if got is None:
                    starved.append((slot, req))
                    continue
                self.page_table[slot, have:have + len(got)] = got
                events += 1
        return starved, events

    def publish_generated(self, slot: int) -> int:
        """At request FINISH (ISSUE 8 satellite — the PR 6 known-limit
        follow-on): publish the prompt+completion page chain into the
        prefix tree, so a multi-turn follow-up whose prompt extends
        this request's transcript hits the cache past the original
        prompt. Must run BEFORE :meth:`evict` (the tree retains its
        own references; evict only drops this request's).

        Only pages whose every KV position is KNOWN-written are
        publishable: the final harvested token's KV may never have
        been written (a budget-ended row's last token is produced but
        not consumed), so the chain covers the first
        ``len(prompt+tokens) - 1`` positions — conservative by at most
        one token. Returns the number of new tree nodes.

        With ``spec_k`` the bar covers the DRAFT store too (shared
        page ids — a published chain a later hit trusts must carry
        BOTH models' KV, or the draft attends to garbage and
        acceptance silently collapses): opt-out rows
        (``speculate=False``) never draft-write their generated
        positions, so they publish nothing beyond the join-time prompt
        pages; speculative rows trim ONE extra position — the draft's
        written frontier ends at the last round's ``pos0 + k - 1``,
        which a fully-accepted final round leaves one position behind
        the target's."""
        req = self.occupants[slot]
        plan = self.plans[slot]
        if (req is None or plan is None or self.kv.prefix is None
                or not req.tokens):
            return 0
        if self.spec_k and not self.spec_on[slot]:
            return 0  # no draft KV exists for the generated positions
        full = np.concatenate(
            [req.prompt_ids, np.asarray(req.tokens, np.int32)])
        ps = self.kv.spec.page_size
        covered = int(full.size) - 1 - (1 if self.spec_k else 0)
        n_full = max(0, covered) // ps
        if n_full <= plan.n_full:
            return 0  # nothing beyond the join-time prompt publish
        return self.kv.prefix.insert(full[: n_full * ps],
                                     plan.table[:n_full])

    def evict(self, slot: int) -> Optional[Request]:
        """Free a slot AND its pages immediately (cancellation /
        deadline expiry / harvest): shared pages just drop this
        request's reference; exclusive ones return to the free list
        the same instant — the next queued request can take them at
        this very boundary."""
        req = self.occupants[slot]
        self.occupants[slot] = None
        plan = self.plans[slot]
        self.plans[slot] = None
        if plan is not None:
            self.kv.release(plan)
        self.page_table[slot, :] = 0  # every write now hits the sink
        self.done[slot] = True
        self.pos[slot] = 0
        self.kv_limit[slot] = 0
        self.last_tok[slot] = 0
        self.spec_on[slot] = True
        self.prefilling[slot] = False
        self.prefill_next[slot] = 0
        self._prefill_full[slot] = None
        return req

    def warm(self) -> None:
        """Pre-compile join (narrow + full width), segment, and the
        COW copy executable with a throwaway request."""
        from tpuflow.infer.generate import paged_copy

        # own flag, not segments_run: warm rewinds segments_run so the
        # bench/metrics never count warm-up segments, and must still
        # no-op on a second prepare() like SlotPool.warm() does
        if self._warmed or self.segments_run or self.has_live():
            return
        self._warmed = True
        plan = self.kv.plan(np.ones(1, np.int32), 1)
        if plan is None:  # pragma: no cover - tiny pool misconfig
            return
        plan.n_full = 0  # NEVER publish the dummy warm-up prompt into
        # the prefix tree — tree-retained garbage pages would inflate
        # kv_pages_in_use until pressure evicts them
        plan.budget_pages = 0  # …and keep warm-up rows out of the
        # held-vs-budget accounting (they would read as ratio 1.0)
        self.join([(0, Request(prompt_ids=np.ones(1, np.int32),
                               max_new_tokens=1), plan)])
        self.run_segment()
        self.evict(0)
        full = self.kv.plan(np.ones(self.bucket, np.int32), 1)
        if full is not None:
            full.n_full = 0
            full.budget_pages = 0
            self.join([(0, Request(
                prompt_ids=np.ones(self.bucket, np.int32),
                max_new_tokens=1), full)])
            self.run_segment()
            self.evict(0)
        if self._hoist and len(self._seg_widths) > 1:
            # warm EVERY hoisted width class: the dummies above only
            # reach width 1..2, and the first production segment
            # landing on a cold class would otherwise pay its XLA
            # compile at a live decode boundary — the exact stall
            # warm() exists to prevent. Positions pinned per class
            # like the bench's cost-table ops; writes past the dummy
            # plan's pages hit the sink (garbage nobody reads).
            dummy = np.ones(self.bucket, np.int32)
            plan3 = self.kv.plan(dummy, self.max_new_cap)
            if plan3 is not None:
                plan3.n_full = 0
                plan3.budget_pages = 0
                self.join([(0, Request(
                    prompt_ids=dummy,
                    max_new_tokens=self.max_new_cap), plan3)])
                ps = self.kv.spec.page_size
                for w in self._seg_widths:
                    self.pos[0] = max(0, min(
                        w * ps - self.seg, int(self.kv_limit[0]) - 1))
                    self.done[0] = False
                    self.run_segment()
                self.evict(0)
        self.kv.cache = paged_copy(self.kv.cache, [0], [0])  # sink no-op
        _mem.tag("kv_pages", self.kv.cache)
        self.segments_run = 0

    def run_segment(self):
        """Advance every occupied row. Same event contract as
        :class:`SlotPool.run_segment`. With ``spec_k`` set, one call
        is one SPECULATIVE ROUND (1..k+1 tokens per live row — draft
        propose, blockwise verify, oracle-parity accept) instead of
        ``seg`` plain steps."""
        import jax.numpy as jnp

        self._record_held()
        if self.spec_k:
            return self._run_spec_round()
        pos0 = self.pos.copy()
        live_before = self.live_count()
        w = self.segment_width()
        seg_fn = self._segment[w]
        table = self.page_table if w is None else self.page_table[:, :w]
        with trace.span("serve.decode_segment", phase="decode",
                        bucket=self.bucket, seg=self.seg,
                        live=live_before, paged=1, width=w or 0):
            res = seg_fn(
                self.params, self.kv.cache, self.out,
                jnp.asarray(self.done), jnp.asarray(pos0),
                jnp.asarray(self.kv_limit), jnp.asarray(self.last_tok),
                jnp.asarray(self.stream_ids), self._rng,
                jnp.asarray(table),
            )
            if self.n_experts:
                (self.kv.cache, self.out, done_dev, toks,
                 load_dev) = res
                self.last_expert_load = np.asarray(load_dev)
            else:
                self.kv.cache, self.out, done_dev, toks = res
            self.segments_run += 1
            was_done = self.done
            self.done = np.array(done_dev)
            toks = np.asarray(toks)
        _mem.tag("kv_pages", (self.kv.cache, self.out))
        self.pos = pos0 + self.seg
        if self.prefilling.any():
            # mid-prefill rows rode the segment as done rows (masked
            # writes, discarded samples): their position is the
            # prefill machinery's, not the segment's to advance
            self.pos[self.prefilling] = pos0[self.prefilling]
        events = []
        for slot, req in enumerate(self.occupants):
            if req is None or was_done[slot]:
                continue
            budget = int(self.last_tok[slot]) - int(pos0[slot])
            new: List[int] = []
            finished = bool(self.done[slot])
            for tok in toks[slot][: max(0, min(self.seg, budget))]:
                if self.eos_id is not None and int(tok) == self.eos_id:
                    break
                new.append(int(tok))
            events.append((slot, req, new, finished))
        return events, live_before

    def _record_held(self) -> None:
        """One held-pages sample per live plan per boundary — the
        held-vs-budget accounting (:meth:`PagedKV.held_vs_budget_mean`
        folds these at release). Warm-up plans opt out by zeroing
        ``budget_pages``."""
        for slot, req in enumerate(self.occupants):
            if req is None or self.done[slot]:
                continue
            plan = self.plans[slot]
            if plan is not None and plan.budget_pages:
                plan.held_sum += len(plan.table)
                plan.held_n += 1

    def _run_spec_round(self):
        """One speculative round: k draft steps (one dispatch), one
        blockwise verify+accept (one dispatch). Rejected positions
        need NO cleanup — each row's write position simply advances by
        its emitted count, and the next round's verify rewrites
        whatever the rejection left above it (per-row write_pos
        rewind; the pages were the row's own all along)."""
        import jax.numpy as jnp

        pos0 = self.pos.copy()
        live_before = self.live_count()
        done0 = jnp.asarray(self.done)
        jpos0 = jnp.asarray(pos0)
        jlim = jnp.asarray(self.kv_limit)
        jstreams = jnp.asarray(self.stream_ids)
        jspec = jnp.asarray(self.spec_on)
        jtable = jnp.asarray(self.page_table)
        with trace.span("serve.spec_round", phase="decode",
                        bucket=self.bucket, k=self.spec_k,
                        live=live_before):
            with trace.span("serve.spec_draft", phase="decode",
                            bucket=self.bucket, k=self.spec_k):
                self.kv.draft_cache, drafts = self._spec_draft(
                    self.draft_params, self.kv.draft_cache, self.out,
                    done0, jpos0, jlim, jspec, jstreams, self._rng,
                    jtable,
                )
            with trace.span("serve.spec_verify", phase="decode",
                            bucket=self.bucket, k=self.spec_k):
                (self.kv.cache, self.out, done_dev, xs, n_emit,
                 n_acc) = self._spec_verify(
                    self.params, self.kv.cache, self.out, drafts,
                    done0, jpos0, jlim, jnp.asarray(self.last_tok),
                    jspec, jstreams, self._rng, jtable,
                )
            self.segments_run += 1
            was_done = self.done
            self.done = np.array(done_dev)
            xs = np.asarray(xs)
            n_emit = np.asarray(n_emit, np.int32)
            n_acc = np.asarray(n_acc, np.int32)
        _mem.tag("kv_pages", (self.kv.cache, self.out))
        _mem.tag("kv_draft", self.kv.draft_cache)
        self.pos = pos0 + n_emit
        drafted = accepted = 0
        events = []
        for slot, req in enumerate(self.occupants):
            if req is None or was_done[slot]:
                continue
            if self.spec_on[slot]:
                drafted += self.spec_k
                accepted += int(n_acc[slot])
            new: List[int] = []
            finished = bool(self.done[slot])
            for tok in xs[slot][: int(n_emit[slot])]:
                if self.eos_id is not None and int(tok) == self.eos_id:
                    break
                new.append(int(tok))
            events.append((slot, req, new, finished))
        self.last_spec_stats = (drafted, accepted)
        return events, live_before
