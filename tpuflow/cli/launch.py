"""SPMD launcher (N2) — the HorovodRunner/mpirun equivalent.

The reference's launch cascade — pickle the train fn, Spark barrier job,
BarrierTaskContext IP gather, ``mpirun`` one python per worker
(P1/03_model_training_distributed.py:256-263) — collapses on TPU to
"run the SAME program once per host with a coordinator address"
(SPMD). This CLI covers the three topologies:

1. real pod: run on each host with --process-id/--num-processes (or let
   TPU metadata fill them in), one command per host;
2. local fake cluster: ``--local N`` forks N CPU processes on this
   machine with a shared coordinator — the multi-process test rig the
   reference lacks (SURVEY.md §4);
3. ``--np -1``: driver-local single process, the reference's smoke mode
   (P1/03:385-397).

Gang semantics (≙ Spark barrier mode, P1/03:256): with --local, if any
process exits non-zero the launcher terminates the rest and exits
non-zero — all-or-nothing, no half-alive training jobs. ``--restarts N``
completes the failure story (SURVEY.md §5.3): after a gang failure the
whole gang is relaunched (fresh coordinator port) up to N times; paired
with ``Trainer.maybe_resume`` the job continues from its last
checkpoint — the restart half the reference's barrier mode leaves to
the operator. On real pods the same contract holds per host: have the
cluster manager re-run the identical command line.

Usage:
  python -m tpuflow.cli.launch --local 4 -- python train_script.py
  python -m tpuflow.cli.launch --np -1 -- python train_script.py
  python -m tpuflow.cli.launch --coordinator host0:8476 \
      --num-processes 4 --process-id $HOST_ID -- python train_script.py
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List


def _parse(argv: List[str]) -> tuple:
    p = argparse.ArgumentParser(prog="tpuflow.cli.launch", description=__doc__)
    p.add_argument("--local", type=int, default=0,
                   help="fork N local CPU processes (fake cluster)")
    p.add_argument("--np", type=int, default=None,
                   help="-1 = single local process (smoke mode)")
    p.add_argument("--coordinator", type=str, default=None,
                   help="host:port of process 0 (multi-host)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--port", type=int, default=8476)
    p.add_argument("--restarts", type=int, default=0,
                   help="relaunch the gang up to N times after a failure "
                        "(checkpoint resume continues the run)")
    p.add_argument("--faults", type=str, default=None, metavar="SPEC",
                   help="arm the fault-injection harness in every "
                        "launched process (sets TPUFLOW_FAULTS; "
                        "spec: 'point=kind[@step][xTIMES];...', e.g. "
                        "'train.step=kill@7' — see tpuflow.testing."
                        "faults). Chaos-test a gang: paired with "
                        "--restarts and checkpoint resume the job "
                        "must survive the injected failure")
    p.add_argument("--compile-cache", type=str, default=None,
                   metavar="DIR",
                   help="persistent XLA compilation cache dir for every "
                        "launched process (JAX_COMPILATION_CACHE_DIR "
                        "with the cache-everything thresholds) — "
                        "relaunches and multi-host gangs deserialize "
                        "executables instead of recompiling; same knob "
                        "as TrainConfig.compilation_cache_dir")
    if "--" not in argv:
        p.error("command required after --")
    split = argv.index("--")
    args = p.parse_args(argv[:split])
    cmd = argv[split + 1 :]
    if not cmd:
        p.error("empty command after --")
    return args, cmd


def _run_local_cluster(n: int, port: int, cmd: List[str]) -> int:
    """Fork n processes with coordinator env; gang-fail together."""
    procs: List[subprocess.Popen] = []
    base = dict(os.environ)
    # hermetic CPU: each process sees n fake devices? No — one CPU device
    # per process; the mesh spans processes (true multi-process SPMD).
    base.pop("PALLAS_AXON_POOL_IPS", None)
    base["PYTHONPATH"] = ":".join(
        p for p in base.get("PYTHONPATH", "").split(":") if p and "axon" not in p
    )
    base["JAX_PLATFORMS"] = base.get("TPUFLOW_LOCAL_PLATFORM", "cpu")
    # each process gets its natural device count: strip any inherited
    # virtual-device forcing (e.g. from a test harness)
    if "XLA_FLAGS" in base:
        base["XLA_FLAGS"] = " ".join(
            f
            for f in base["XLA_FLAGS"].split()
            if "xla_force_host_platform_device_count" not in f
        )
    for i in range(n):
        env = dict(base)
        env["TPUFLOW_COORDINATOR"] = f"localhost:{port}"
        env["TPUFLOW_NUM_PROCESSES"] = str(n)
        env["TPUFLOW_PROCESS_ID"] = str(i)
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    interrupted = None
    try:
        remaining = set(range(n))
        while remaining:
            for i in list(remaining):
                code = procs[i].poll()
                if code is not None:
                    remaining.discard(i)
                    if code != 0:
                        rc = code
                        raise RuntimeError(f"process {i} exited {code}")
            time.sleep(0.2)
    except (RuntimeError, KeyboardInterrupt) as e:
        if isinstance(e, KeyboardInterrupt):
            interrupted = e
        rc = rc or 1
        for pr in procs:
            if pr.poll() is None:
                pr.send_signal(signal.SIGTERM)
        for pr in procs:
            try:
                pr.wait(timeout=5)
            except subprocess.TimeoutExpired:
                # a worker wedged in its SIGTERM handler — including a
                # preempt-enabled worker whose flag-only handler left
                # it blocked inside a collective with a dead peer —
                # must not hang the launcher (or orphan peers):
                # escalate after a SHORT grace (clean exits are fast;
                # wedged ones need SIGKILL anyway); and a
                # worker that survives even SIGKILL (D-state I/O) must
                # not abort the reap loop for its peers
                pr.kill()
                try:
                    pr.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    print(
                        f"tpuflow.launch: pid {pr.pid} unkillable "
                        "(uninterruptible state); abandoning",
                        file=sys.stderr,
                        flush=True,
                    )
    if interrupted is not None:
        # a deliberate Ctrl-C must not look like a gang failure (the
        # --restarts loop would relaunch the job the user just killed)
        raise interrupted
    return rc


def main(argv: List[str] | None = None) -> int:
    args, cmd = _parse(argv if argv is not None else sys.argv[1:])
    if args.compile_cache:
        # set on OUR env so every launch path below inherits it (the
        # local gang copies os.environ; jax reads these at import)
        os.makedirs(args.compile_cache, exist_ok=True)
        os.environ["JAX_COMPILATION_CACHE_DIR"] = args.compile_cache
        os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
        os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "-1"
    if args.faults:
        # arm the fault-injection harness (ISSUE 10) in every launched
        # process — tpuflow.testing.faults parses TPUFLOW_FAULTS at
        # import, so the trainer under test needs no code change
        os.environ["TPUFLOW_FAULTS"] = args.faults
    if args.local and args.local > 0:
        rc = 0
        for attempt in range(max(0, args.restarts) + 1):
            if attempt == 1 and args.faults:
                # sabotage arms the FIRST launch only: a step-gated
                # kill would otherwise fire again on every resumed
                # relaunch (resume replays the fault's step) and the
                # chaos drive could never demonstrate survival.
                # Deterministic every-launch faults are still one
                # `export TPUFLOW_FAULTS=...` away.
                os.environ.pop("TPUFLOW_FAULTS", None)
            # fresh port per attempt: the previous coordinator socket can
            # linger in TIME_WAIT and refuse the bind
            rc = _run_local_cluster(args.local, args.port + attempt, cmd)
            if rc == 0:
                return 0
            if attempt < args.restarts:
                print(
                    f"tpuflow.launch: gang failed (rc={rc}); relaunching "
                    f"(attempt {attempt + 2}/{args.restarts + 1})",
                    file=sys.stderr,
                    flush=True,
                )
                time.sleep(1.0)
        return rc
    if args.restarts:
        print(
            "tpuflow.launch: --restarts only drives the --local gang; on "
            "real pods have the cluster manager re-run this command "
            "(resume picks up the checkpoints)",
            file=sys.stderr,
            flush=True,
        )
    env = dict(os.environ)
    if args.np == -1 or (
        args.coordinator is None and not args.local
    ):
        # driver-local smoke mode: no distributed init (≙ np=-1)
        env.pop("TPUFLOW_COORDINATOR", None)
        env["TPUFLOW_NUM_PROCESSES"] = "1"
        env["TPUFLOW_PROCESS_ID"] = "0"
        return subprocess.call(cmd, env=env)
    env["TPUFLOW_COORDINATOR"] = args.coordinator
    if args.num_processes is not None:
        env["TPUFLOW_NUM_PROCESSES"] = str(args.num_processes)
    if args.process_id is not None:
        env["TPUFLOW_PROCESS_ID"] = str(args.process_id)
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    sys.exit(main())
