"""``python -m tpuflow.cli.serve`` — alias of ``python -m tpuflow.serve``
(the serving CLI lives with the runtime; this keeps the cli/ namespace
complete: launch, runs, serve)."""

from tpuflow.serve.__main__ import main

if __name__ == "__main__":
    import sys

    sys.exit(main())
