"""Run browser CLI — the headless equivalent of the MLflow UI.

The reference inspects experiments through the Databricks MLflow UI
(runs table, per-run params/metrics — used throughout P2/01-P2/03);
tpuflow's tracking store is a directory tree, and this CLI is the
operator surface over it:

  python -m tpuflow.cli.runs list   [--store DIR] [--experiment E]
  python -m tpuflow.cli.runs show   RUN_ID [--store DIR]
  python -m tpuflow.cli.runs best   --metric val_accuracy [--mode max]
  python -m tpuflow.cli.runs models [--store DIR]

`best` mirrors the search_runs(metric-ordered) selection the notebooks
do programmatically (P2/01:257-261, P2/02:390-399).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from tpuflow.track import TrackingStore


def _fmt_table(rows: List[dict], cols: List[str]) -> str:
    if not rows:
        return "(no runs)"
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols
    }
    line = "  ".join(c.ljust(widths[c]) for c in cols)
    out = [line, "  ".join("-" * widths[c] for c in cols)]
    for r in rows:
        out.append("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))
    return "\n".join(out)


def _metric_cols(rows: List[dict], limit: int = 4) -> List[str]:
    seen: List[str] = []
    for r in rows:
        for k in r:
            if k.startswith("metrics.") and k not in seen:
                seen.append(k)
    return seen[:limit]


def cmd_list(store: TrackingStore, experiment: Optional[str]) -> int:
    rows = store.search_runs(experiment=experiment)
    cols = ["run_id", "run_name", "status"] + _metric_cols(rows)
    print(_fmt_table(rows, cols))
    return 0


def cmd_show(store: TrackingStore, run_id: str) -> int:
    run = store.get_run(run_id)
    print(json.dumps(
        {
            "meta": run.meta(),
            "params": run.params(),
            "metrics": run.metrics(),
        },
        indent=2,
        default=str,
    ))
    return 0


def cmd_best(
    store: TrackingStore, metric: str, mode: str, experiment: Optional[str]
) -> int:
    order = f"metrics.{metric} {'DESC' if mode == 'max' else 'ASC'}"
    rows = store.search_runs(order_by=order, experiment=experiment)
    rows = [r for r in rows if f"metrics.{metric}" in r]
    if not rows:
        print(f"no runs with metric {metric!r}", file=sys.stderr)
        return 1
    best = rows[0]
    print(json.dumps(best, indent=2, default=str))
    return 0


def cmd_models(store: TrackingStore) -> int:
    import os

    from tpuflow.track.registry import ModelRegistry

    if not os.path.isdir(os.path.join(store.root, "registry")):
        # browsing must not create the registry tree (ModelRegistry's
        # constructor mkdirs it)
        print("(no models)")
        return 0
    reg = ModelRegistry(store)
    rows = []
    for name in reg.list_models():
        for v in reg.versions(name):
            rows.append({
                "model": name,
                "version": v.get("version"),
                "stage": v.get("stage"),
                "source": v.get("source_uri"),
            })
    print(_fmt_table(rows, ["model", "version", "stage", "source"]))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tpuflow.cli.runs", description=__doc__)
    p.add_argument("--store", default=None,
                   help="tracking store root (default: the store's default)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pl = sub.add_parser("list");     pl.add_argument("--experiment")
    ps = sub.add_parser("show");     ps.add_argument("run_id")
    pb = sub.add_parser("best")
    pb.add_argument("--metric", required=True)
    pb.add_argument("--mode", choices=["max", "min"], default="max")
    pb.add_argument("--experiment")
    sub.add_parser("models")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])

    import os

    root = args.store if args.store else TrackingStore.default_root()
    if not os.path.isdir(os.path.join(root, "runs")):
        # a browser must not mkdir a store that isn't there — that would
        # mask a wrong --store/cwd as "(no runs)"
        print(f"no tracking store at {root!r} (pass --store)", file=sys.stderr)
        return 1
    store = TrackingStore(root)
    try:
        if args.cmd == "list":
            return cmd_list(store, args.experiment)
        if args.cmd == "show":
            return cmd_show(store, args.run_id)
        if args.cmd == "best":
            return cmd_best(store, args.metric, args.mode, args.experiment)
        return cmd_models(store)
    except (KeyError, FileNotFoundError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) closed early — normal for a
        # browser CLI; suppress the traceback os-level too
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
