"""``python -m tpuflow.cli.obs`` — inspect host-span traces (ISSUE 4).

The read side of the observability plane: both subcommands consume a
Chrome trace-event JSON — the file
:func:`tpuflow.obs.trace.export_chrome_trace` writes, or a
``jax.profiler`` capture directory (``*.trace.json.gz`` is found and
parsed through the same loader, :mod:`tpuflow.obs.report`)::

  python -m tpuflow.cli.obs trace  <file-or-dir> [--top N]
      top host spans by total time (name / total / mean / count)

  python -m tpuflow.cli.obs report <file-or-dir> [--prefix train.]
      step-time breakdown: host-dispatch vs device vs data-wait (and
      compile/checkpoint/eval, or queue/prefill/decode for a serving
      capture) as fractions of the capture window

  python -m tpuflow.cli.obs postmortem <bundle-or-root> [--spans N]
      pretty-print a flight-record bundle (tpuflow.obs.flight): trip
      reason, watchdog history, heartbeat ages, the last spans before
      the dump, gauge snapshot, in-flight serve requests. Given a dump
      ROOT directory, the newest bundle inside is shown.

  python -m tpuflow.cli.obs trace-report <bundle|file|url>
      per-phase text timeline of a MERGED tier trace (ISSUE 19): one
      row per span across router + replicas in offset-corrected start
      order with parent nesting and a phase-attribution footer. Takes
      a router ``/v1/trace/<id>`` URL, a saved copy of that JSON, or a
      flight-record bundle (renders the ``tier_traces`` the router
      bundled).

  python -m tpuflow.cli.obs slo-report <bundle|file|url>
      objective-by-objective SLO verdicts (ISSUE 20): latency
      percentiles vs thresholds and multiwindow error-budget burn
      rates with margins. Takes a frontend ``/v1/slo`` URL, a saved
      copy of that JSON, or a flight-record bundle (renders its
      ``slo.json`` section).

  python -m tpuflow.cli.obs memreport <bundle-or-root>
      the memory-and-compile plane of a bundle (ISSUE 7): the
      device-buffer ledger (per-component bytes + peaks + untagged
      residual + HBM headroom), the executable registry (per-site
      compiles / cost + roofline / memory analysis / compile-cache
      stats), and the paged-KV sub-view (absorbing
      ``tools/kv_memory_report.py`` — see MIGRATION.md).

For XLA *device-op* attribution of a jax.profiler capture, use
``python tools/trace_top_ops.py <dir>`` — same loader, op-level table.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _load_tier_traces(path: str) -> List[dict]:
    """Resolve a ``trace-report`` operand into tier-trace dicts: a
    router ``/v1/trace/<id>`` URL, a saved copy of that JSON, or a
    flight-record bundle whose router provider bundled recent
    ``tier_traces``."""
    import json

    if path.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(path, timeout=10) as r:
            return [json.load(r)]
    import os

    if os.path.isdir(path):
        from tpuflow.obs.flight import load

        out = []
        for name, sec in sorted(load(path).items()):
            if not isinstance(sec, dict):
                continue
            tt = (sec.get("trace") or {}).get("tier_traces") \
                if isinstance(sec.get("trace"), dict) else None
            for rid, spans in sorted((tt or {}).items()):
                out.append({"id": rid, "spans": spans,
                            "clock_offset_s": sec["trace"].get(
                                "clock_offset_s")})
        return out
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "spans" in obj:
        return [obj]
    tt = obj.get("tier_traces") if isinstance(obj, dict) else None
    return [{"id": rid, "spans": spans}
            for rid, spans in sorted((tt or {}).items())]


def _load_slo_report(path: str) -> Optional[dict]:
    """Resolve an ``slo-report`` operand into a report dict: a
    frontend ``/v1/slo`` URL, a saved copy of that JSON, or a
    flight-record bundle carrying the ``slo`` provider section."""
    import json

    if path.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(path, timeout=10) as r:
            return json.load(r)
    import os

    if os.path.isdir(path):
        from tpuflow.obs.flight import load

        sec = load(path).get("slo")
        return sec if isinstance(sec, dict) else None
    with open(path) as f:
        obj = json.load(f)
    return obj if isinstance(obj, dict) else None


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tpuflow.cli.obs",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pt = sub.add_parser("trace", help="top host spans by total time")
    pt.add_argument("path", help="chrome-trace JSON file or capture dir")
    pt.add_argument("--top", type=int, default=15)
    pr = sub.add_parser("report", help="step-time breakdown by phase")
    pr.add_argument("path", help="chrome-trace JSON file or capture dir")
    pr.add_argument("--prefix", default=None,
                    help="restrict to span names under this prefix "
                         "(e.g. 'train.' or 'serve.')")
    pp = sub.add_parser("postmortem",
                        help="pretty-print a flight-record bundle")
    pp.add_argument("path", help="bundle directory (or the dump root — "
                                 "newest bundle wins)")
    pp.add_argument("--spans", type=int, default=12,
                    help="how many of the last spans to show")
    pc = sub.add_parser("trace-report",
                        help="per-phase text timeline of a merged "
                             "tier trace")
    pc.add_argument("path", help="router /v1/trace/<id> URL, a saved "
                                 "tier-trace JSON, or a flight bundle")
    pm = sub.add_parser("memreport",
                        help="memory-and-compile report of a bundle "
                             "(ledger + executables + KV sub-view)")
    pm.add_argument("path", help="bundle directory (or the dump root — "
                                 "newest bundle wins)")
    ps = sub.add_parser("slo-report",
                        help="objective-by-objective SLO verdicts "
                             "(latency + burn-rate margins)")
    ps.add_argument("path", help="frontend /v1/slo URL, a saved SLO "
                                 "report JSON, or a flight bundle")
    args = p.parse_args(argv)

    if args.cmd == "slo-report":
        from tpuflow.obs.slo import format_slo_report

        try:
            report = _load_slo_report(args.path)
        except (OSError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return 1
        if not report or "objectives" not in report:
            print(f"no SLO report under {args.path}", file=sys.stderr)
            return 1
        print(format_slo_report(report))
        return 0

    if args.cmd == "trace-report":
        from tpuflow.obs.report import tier_timeline

        try:
            traces = _load_tier_traces(args.path)
        except (OSError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return 1
        if not traces:
            print(f"no tier traces under {args.path}", file=sys.stderr)
            return 1
        print("\n\n".join(tier_timeline(t) for t in traces))
        return 0

    if args.cmd == "postmortem":
        from tpuflow.obs.flight import format_postmortem, load

        try:
            bundle = load(args.path)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(format_postmortem(bundle, top_spans=args.spans))
        return 0

    if args.cmd == "memreport":
        from tpuflow.obs.flight import load
        from tpuflow.obs.memory import format_memreport

        try:
            bundle = load(args.path)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(format_memreport(bundle))
        return 0

    from tpuflow.obs.report import (
        format_report,
        load_trace_events,
        spans_from_events,
        step_breakdown,
        top_spans,
    )

    events = load_trace_events(args.path)
    spans = spans_from_events(events)
    if not spans:
        print(f"no spans found under {args.path}", file=sys.stderr)
        return 1

    if args.cmd == "trace":
        rows = top_spans(spans, top=args.top)
        width = max(len(r["name"]) for r in rows)
        print(f"{'span':<{width}}  {'total_ms':>10}  {'mean_ms':>9} "
              f"{'count':>6}")
        for r in rows:
            print(f"{r['name']:<{width}}  {r['total_ms']:>10.3f}  "
                  f"{r['mean_ms']:>9.3f} {r['count']:>6}")
        return 0

    print(format_report(step_breakdown(spans, prefix=args.prefix)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
