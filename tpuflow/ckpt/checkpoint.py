"""Checkpoint / resume (C17, SURVEY.md §5.4).

The reference writes rank-0-only per-epoch weight checkpoints
(``ModelCheckpoint(save_weights_only=True)`` to
``{checkpoint_dir}/checkpoint-{epoch}.ckpt``,
P2/02_hyperopt_distributed_model.py:65-67,206-211) but never restores.
This module keeps the layout semantics and ADDS real resume: full
TrainState (params + BN stats + optimizer state + step) serialization,
atomic writes, latest-checkpoint discovery, and restore-into-state.

Serialization is flax msgpack (dependency-light, host-RAM friendly at
this model scale). Only the primary process WRITES files, but saving
cross-process-sharded (ZeRO/FSDP) state is a COLLECTIVE — every
process must call save_checkpoint so the assembling allgathers match
(see save_checkpoint's contract). Restored state is placed back under
the template's shardings on load — replicated state everywhere, the
consistency story BroadcastGlobalVariablesCallback documents
(P1/03:305-308), and partitioned state sliced per process.
"""

from __future__ import annotations

import contextlib
import os
import re
import struct
import tempfile
import time
import zlib
from typing import Any, List, Optional

import jax
from flax import serialization

_PAT = re.compile(r"checkpoint-(\d+)\.ckpt$")


class CorruptCheckpointError(ValueError):
    """A checkpoint file failed its integrity check (CRC/length footer
    mismatch, truncated payload, unreadable shard). Discovery
    (:func:`latest_resume_point` / :func:`latest_checkpoint`) catches
    this and falls back to the previous valid checkpoint instead of
    dying in ``msgpack_restore`` — restore of an EXPLICIT path
    surfaces it."""


# ---- integrity footer (ISSUE 10 satellite) ---------------------------
#
# Every payload written by _atomic_save carries a fixed 20-byte
# trailer: 8-byte magic + CRC32 + payload length. Readers strip and
# verify it; files WITHOUT the magic are legacy pre-footer checkpoints
# and still load unverified (MIGRATION.md r11). The footer turns a
# torn/bit-flipped file into a detected CorruptCheckpointError at
# DISCOVERY time rather than a msgpack exception mid-restore.

_FOOTER_MAGIC = b"TPFWCRC1"
_FOOTER_LEN = len(_FOOTER_MAGIC) + 12  # + u32 crc + u64 payload len


def _with_footer(data: bytes) -> bytes:
    return data + _FOOTER_MAGIC + struct.pack(
        "<IQ", zlib.crc32(data) & 0xFFFFFFFF, len(data)
    )


def _strip_footer(data: bytes, path: str = "<bytes>") -> bytes:
    """Verified payload of ``data``; legacy (no magic) passes through
    unchecked, a PRESENT footer that fails CRC/length raises."""
    if len(data) < _FOOTER_LEN or data[-_FOOTER_LEN:-12] != _FOOTER_MAGIC:
        return data  # legacy single-file format keeps restoring
    crc, n = struct.unpack("<IQ", data[-12:])
    payload = data[:-_FOOTER_LEN]
    if len(payload) != n:
        raise CorruptCheckpointError(
            f"{path}: truncated checkpoint (footer says {n} payload "
            f"bytes, file holds {len(payload)})"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptCheckpointError(
            f"{path}: checkpoint CRC mismatch (corrupt payload)"
        )
    return payload


def read_verified(path: str) -> bytes:
    """Read ``path`` and verify/strip its integrity footer (legacy
    files come back as-is). Raises :class:`CorruptCheckpointError` on
    mismatch — shared by the single-file and sharded readers."""
    with open(path, "rb") as f:
        return _strip_footer(f.read(), path)


def verify_checkpoint(path: str) -> bool:
    """True iff ``path`` loads: footer files verify by CRC (cheap);
    legacy footer-less files pay one msgpack parse (the only way to
    detect their truncation)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    try:
        payload = _strip_footer(data, path)
    except CorruptCheckpointError:
        return False
    if len(data) >= _FOOTER_LEN and data[-_FOOTER_LEN:-12] == _FOOTER_MAGIC:
        return True  # CRC already proved the payload
    try:
        serialization.msgpack_restore(payload)
    except Exception:
        return False
    return True


def checkpoint_number(path: str) -> int:
    """The N of a ``checkpoint-{N}.ckpt`` path (the reference's layout,
    P2/02:206-211) — the one parser for the filename format."""
    m = _PAT.search(path)
    if m is None:
        raise ValueError(f"not a checkpoint path: {path!r}")
    return int(m.group(1))


def _is_key(x: Any) -> bool:
    from tpuflow.parallel.mesh import is_typed_prng_key

    return is_typed_prng_key(x)


def _unkey(tree: Any) -> Any:
    """Typed PRNG keys → raw uint32 (msgpack-serializable)."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree
    )


def _rekey(template: Any, restored: Any) -> Any:
    """Re-wrap raw key data where the template holds typed keys."""
    return jax.tree.map(
        lambda t, r: jax.random.wrap_key_data(r) if _is_key(t) and not _is_key(r) else r,
        template,
        restored,
    )


def _path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(checkpoint_dir, f"checkpoint-{step}.ckpt")


def _host_fetch(tree: Any) -> Any:
    """Fetch a (possibly cross-process-sharded) device tree to host.

    Replicated or single-process leaves come back via plain device_get.
    PARTITIONED leaves on a non-addressable mesh (ZeRO/FSDP optimizer
    state) are assembled with a process allgather so every process
    holds the full global array — the checkpoint file is always the
    complete state regardless of how training sharded it.
    """

    def one(x):
        if _needs_allgather(x):
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x, tiled=True)
        return x

    return jax.device_get(jax.tree.map(one, tree))


def _needs_allgather(x: Any) -> bool:
    """Leaf is partitioned over devices this process cannot address —
    fetching it to host requires a process allgather."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.sharding.is_fully_replicated
    )


def is_cross_process_sharded(tree: Any) -> bool:
    """True if any leaf is partitioned over devices this process cannot
    address — i.e. saving it is a collective (see save_checkpoint)."""
    return any(_needs_allgather(x) for x in jax.tree.leaves(tree))


def save_checkpoint(
    checkpoint_dir: str,
    state: Any,
    step: int,
    weights_only: bool = False,
) -> str:
    """Write checkpoint atomically. ``weights_only`` mirrors the
    reference's save_weights_only=True (params+batch_stats only).

    COLLECTIVE when ``state`` holds cross-process-sharded leaves
    (ZeRO/FSDP): assembling them is an allgather, so EVERY process must
    call this with the same state; only the primary touches the
    filesystem (rank-0 discipline, P2/02:206-211). With fully
    replicated/addressable state (the Trainer default) non-primary
    processes may skip the call entirely — there is no collective.
    """
    payload = _build_payload(state, weights_only)
    return _atomic_save(checkpoint_dir, _path(checkpoint_dir, step), payload)


def _build_payload(state: Any, weights_only: bool):
    """THE checkpoint payload (shared by the sync and async writers so
    their file contents can never diverge): full host-fetched state
    dict, or the reference's weights-only (params+batch_stats) form."""
    if weights_only:
        return {
            "params": _host_fetch(state.params),
            "batch_stats": _host_fetch(state.batch_stats),
        }
    return _host_fetch(serialization.to_state_dict(_unkey(state)))


@contextlib.contextmanager
def join_async_writes(get_checkpointers):
    """finally-join background checkpoint writes: stacked into the
    trainers' fit ``with`` blocks so an EXCEPTIONAL exit still makes
    the in-flight write durable (and surfaces its failure) instead of
    abandoning a daemon thread mid-write — the sync path would have
    completed that checkpoint before the exception propagated.
    ``get_checkpointers`` is a callable (the checkpointer may be
    created lazily inside the loop)."""
    try:
        yield
    finally:
        for c in get_checkpointers():
            if c is not None:
                c.wait()


class AsyncCheckpointer:
    """Overlap checkpoint WRITES with training (r05).

    The device→host fetch — and, for cross-process-sharded ZeRO/FSDP
    state, the assembling allgather — must stay synchronous (it is a
    collective and it snapshots the state before the next step mutates
    it), but the serialize + atomic write is pure host work:
    :meth:`save` runs it on a background thread and returns once the
    PAYLOAD is captured, so the train loop overlaps the disk write
    with the next epoch. One write in flight at a time: ``save`` joins
    the previous write first (ordering + error propagation), and
    :meth:`wait` joins the last one — call it at train end (the
    trainers do) or before reading the files back.
    """

    def __init__(self):
        import threading

        self._threading = threading
        self._thread = None
        self._err: "BaseException | None" = None

    def save(self, checkpoint_dir: str, state: Any, step: int,
             weights_only: bool = False) -> str:
        self.wait()
        payload = _build_payload(state, weights_only)
        path = _path(checkpoint_dir, step)

        def run():
            try:
                _atomic_save(checkpoint_dir, path, payload)
            except BaseException as e:  # surfaced by the next wait()
                self._err = e

        self._thread = self._threading.Thread(
            target=run, name=f"ckpt-write-{step}", daemon=True
        )
        self._thread.start()
        return path

    def wait(self) -> None:
        """Join the in-flight write; re-raise its failure here (in the
        caller's thread) if it had one."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("async checkpoint write failed") from err


_STEP_PAT = re.compile(r"checkpoint-step-(\d+)\.ckpt$")


def _atomic_save(checkpoint_dir: str, path: str, payload: Any) -> str:
    """Rank-0 atomic write shared by both checkpoint namespaces:
    serialize → tempfile in the target dir → os.replace; the tempfile
    is unlinked on any failure so aborted writes never litter the
    checkpoint dir."""
    from tpuflow.core.dist import is_primary
    from tpuflow.testing import faults

    if not is_primary():
        return path
    faults.fire("ckpt.write")  # raise/delay/kill injection point
    os.makedirs(checkpoint_dir, exist_ok=True)
    data = _with_footer(serialization.msgpack_serialize(payload))
    fd, tmp = tempfile.mkstemp(dir=checkpoint_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    faults.file_hook("ckpt.file", path)  # corrupt/truncate injection
    return path


def save_step_checkpoint(checkpoint_dir: str, state: Any,
                         global_step: int) -> str:
    """Mid-epoch (preemption) checkpoint: ``checkpoint-step-{N}.ckpt``
    where N is the GLOBAL step count — disjoint from the epoch-boundary
    ``checkpoint-{epoch}.ckpt`` namespace (the reference's layout,
    P2/02:206-211), so epoch-granular consumers never misread one.
    Same atomic write + rank-0 discipline as :func:`save_checkpoint`;
    always the full TrainState (exact resume is the whole point of a
    preemption save)."""
    payload = _host_fetch(serialization.to_state_dict(_unkey(state)))
    return _atomic_save(
        checkpoint_dir,
        os.path.join(checkpoint_dir, f"checkpoint-step-{global_step}.ckpt"),
        payload,
    )


def _resume_candidates(checkpoint_dir: str, steps_per_epoch: int
                       ) -> List[tuple]:
    """Every restorable checkpoint in ``checkpoint_dir`` as
    ``(effective_step, prefer_rank, path)``, BEST FIRST: higher global
    step wins; at equal step an epoch file beats a step file beats a
    sharded manifest (clean boundary, then the cheaper reader)."""
    out = []
    if not os.path.isdir(checkpoint_dir):
        return out
    for fn in os.listdir(checkpoint_dir):
        p = os.path.join(checkpoint_dir, fn)
        ms = _STEP_PAT.search(fn)
        m = _PAT.search(fn)
        if ms:
            out.append((int(ms.group(1)), 1, p))
        elif m:
            out.append((int(m.group(1)) * steps_per_epoch, 0, p))
        else:
            from tpuflow.ckpt.sharded import manifest_step

            step = manifest_step(fn)
            if step is not None:
                out.append((step, 2, p))
    out.sort(key=lambda c: (c[0], -c[1]), reverse=True)
    return out


def _candidate_valid(path: str) -> bool:
    """Integrity gate shared by discovery: single files verify via
    footer/parse, sharded manifests verify manifest + every shard."""
    if path.endswith(".manifest.json"):
        from tpuflow.ckpt.sharded import verify_sharded

        return verify_sharded(path)
    return verify_checkpoint(path)


def latest_resume_point(checkpoint_dir: str, steps_per_epoch: int
                        ) -> Optional[tuple]:
    """Newest VALID checkpoint across all three namespaces (epoch
    files, step files, sharded manifests), compared in global-step
    units (epoch ckpt N ≙ step N·steps_per_epoch; ties prefer the
    epoch file). Corrupt or truncated candidates — a torn write, a
    bit-flip, a missing shard — are SKIPPED, falling back to the
    previous valid one (ISSUE 10 satellite: a bad newest checkpoint
    must cost one checkpoint interval, not the run). Returns ``(path,
    epoch, skip_steps)`` or None when nothing valid exists."""
    for step, _rank, path in _resume_candidates(
            checkpoint_dir, steps_per_epoch):
        if _candidate_valid(path):
            return path, step // steps_per_epoch, step % steps_per_epoch
    return None


def list_checkpoints(checkpoint_dir: str) -> List[str]:
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for fn in os.listdir(checkpoint_dir):
        if _PAT.search(fn):
            out.append(os.path.join(checkpoint_dir, fn))
    return sorted(out, key=lambda p: int(_PAT.search(p).group(1)))


def latest_checkpoint(checkpoint_dir: str) -> Optional[str]:
    """Newest VALID epoch checkpoint (corrupt files skipped — same
    fallback contract as :func:`latest_resume_point`)."""
    for p in reversed(list_checkpoints(checkpoint_dir)):
        if verify_checkpoint(p):
            return p
    return None


def restore_checkpoint(path: str) -> dict:
    """Raw payload (dict of numpy arrays); integrity-verified when the
    file carries the CRC footer (raises CorruptCheckpointError on
    mismatch). Legacy footer-less files load as before — and a
    TRUNCATED footer file looks footer-less (the trailer was cut off),
    so an unparseable payload is also surfaced as
    :class:`CorruptCheckpointError`, not a raw msgpack exception."""
    try:
        return serialization.msgpack_restore(read_verified(path))
    except CorruptCheckpointError:
        raise
    except Exception as e:
        raise CorruptCheckpointError(
            f"{path}: unreadable checkpoint payload ({e})"
        ) from e


def restore_into_state(path: str, state: Any) -> Any:
    """Restore a FULL checkpoint into a template TrainState (resume).

    The template supplies structure (built by Trainer.init_state); the
    payload supplies values, including optimizer state and step, so
    training continues exactly where it stopped — the capability the
    reference gestures at but never implements (SURVEY.md §5.4).

    A ``*.manifest.json`` path routes to the SHARDED restore
    (tpuflow.ckpt.sharded), which re-slices the saved shards under the
    template's own mesh/sharding — a different process count or mesh
    shape than the saver's is fine.
    """
    if path.endswith(".manifest.json"):
        from tpuflow.ckpt.sharded import restore_sharded_into_state

        return restore_sharded_into_state(path, state)
    payload = restore_checkpoint(path)
    if set(payload.keys()) == {"params", "batch_stats"}:
        restored = state.replace(
            params=serialization.from_state_dict(state.params, payload["params"]),
            batch_stats=serialization.from_state_dict(
                state.batch_stats, payload["batch_stats"]
            ),
        )
    else:
        restored = serialization.from_state_dict(_unkey(state), payload)
        restored = _rekey(state, restored)
    # keep the template's sharding (replicated across the mesh);
    # put_replicated handles non-addressable (multi-process) meshes —
    # every process restores the same file, so values are host-identical
    from tpuflow.parallel.mesh import put_replicated

    return jax.tree.map(
        lambda v, t: put_replicated(v, t.sharding)
        if hasattr(t, "sharding")
        else v,
        restored,
        state,
    )


# ---- deploy pins (ISSUE 15 satellite) --------------------------------
#
# The gc-vs-watcher race: a serving-side ModelWatcher that has SEEN a
# manifest but not finished restoring it must be able to hold retention
# off that set — otherwise a trainer's gc_checkpoints(keep_last=N) can
# delete shard files out from under a half-read restore. Two layers:
#
# - an in-memory refcount (nested pin/unpin balance) guards the
#   in-process shape (watcher and gc in one process);
# - a PIN SIDECAR file (`<manifest>.pin-<pid>` holding pid + host)
#   makes the pin visible to a gc running in ANOTHER process on the
#   shared checkpoint filesystem (the trainer-publishes /
#   server-watches shape). gc skips sets with a LIVE sidecar: same
#   host + pid alive, or (other host / unreadable) younger than
#   _PIN_STALE_S — and deletes stale ones, so a crashed reader never
#   blocks retention forever. The sidecar name matches no discovery
#   pattern, so resume scans and set listings never see it.

import json as _json
import threading as _threading

_PIN_LOCK = _threading.Lock()
_PINNED: dict = {}
#: a foreign-host pin sidecar older than this is presumed crashed
_PIN_STALE_S = 3600.0


def _pin_sidecar(path: str) -> str:
    return f"{path}.pin-{os.getpid()}"


def pin_checkpoint(path: str) -> None:
    """Hold retention off this checkpoint (a manifest path pins its
    WHOLE shard set) until the matching :func:`unpin_checkpoint` —
    including retention run by OTHER processes on the shared
    checkpoint dir (best-effort sidecar; see module comment)."""
    import socket

    p = os.path.abspath(path)
    with _PIN_LOCK:
        n = _PINNED.get(p, 0) + 1
        _PINNED[p] = n
    if n == 1:
        try:
            with open(_pin_sidecar(p), "w") as f:
                _json.dump({"pid": os.getpid(),
                            "host": socket.gethostname(),
                            "ts": time.time()}, f)
        except OSError:
            pass  # read-only namespace: in-memory pin still holds


def unpin_checkpoint(path: str) -> None:
    """Release one pin (no-op if not pinned — unpin must be safe on
    every error path)."""
    p = os.path.abspath(path)
    with _PIN_LOCK:
        n = _PINNED.get(p, 0) - 1
        if n <= 0:
            _PINNED.pop(p, None)
        else:
            _PINNED[p] = n
    if n <= 0:
        try:
            os.unlink(_pin_sidecar(p))
        except OSError:
            pass


def pinned_checkpoints() -> List[str]:
    with _PIN_LOCK:
        return sorted(_PINNED)


def _pin_sidecars_of(path: str) -> List[str]:
    d, base = os.path.split(os.path.abspath(path))
    prefix = base + ".pin-"
    try:
        return [os.path.join(d, fn) for fn in os.listdir(d)
                if fn.startswith(prefix)]
    except OSError:
        return []


def _externally_pinned(path: str) -> bool:
    """Whether ANY process holds a live pin sidecar on ``path`` —
    stale sidecars (dead pid on this host; old mtime elsewhere) are
    collected here so a crashed reader cannot block retention."""
    import socket

    host = socket.gethostname()
    live = False
    for sc in _pin_sidecars_of(path):
        stale = False
        try:
            with open(sc) as f:
                rec = _json.load(f)
            if rec.get("host") == host:
                try:
                    os.kill(int(rec["pid"]), 0)
                except PermissionError:
                    pass  # ALIVE, just unsignalable (other user)
                except (OSError, ValueError, TypeError):
                    stale = True  # holder died on this host
            elif time.time() - os.path.getmtime(sc) > _PIN_STALE_S:
                stale = True  # foreign/ancient: presume crashed
        except (OSError, ValueError):
            try:
                stale = (time.time() - os.path.getmtime(sc)
                         > _PIN_STALE_S)
            except OSError:
                continue  # vanished: its holder just unpinned
        if stale:
            try:
                os.unlink(sc)
            except OSError:
                pass
        else:
            live = True
    return live


# ---- retention (ISSUE 10 satellite) ----------------------------------


def gc_checkpoints(checkpoint_dir: str, keep_last: int,
                   just_wrote: Optional[str] = None) -> List[str]:
    """Delete all but the newest ``keep_last`` checkpoints PER
    NAMESPACE (epoch files; step files + sharded sets — a manifest and
    its shard files count as ONE checkpoint) and return the removed
    paths. Both file kinds accumulate unboundedly otherwise.

    Safety rails: the newest VALID checkpoint of each namespace is
    never deleted even when retention would name it (if the newest N
    are all corrupt, the newest valid survivor is the only thing a
    restart can restore); rank-0 discipline (non-primary is a no-op,
    matching who wrote the files); PINNED checkpoints
    (:func:`pin_checkpoint` — the serving ModelWatcher's mid-restore
    guard, ISSUE 15) are skipped however retention ranks them. ``just_wrote`` names a checkpoint
    the caller finished writing moments ago — trusted valid without
    re-reading it, so the per-save rail scan costs nothing instead of
    a full CRC pass over the newest checkpoint.

    Shard sets whose manifest never published (a killed save) are
    invisible to discovery but must not leak past retention: they join
    the step namespace as unrestorable candidates and age out like any
    other checkpoint. A save IN PROGRESS is always the newest step
    (global step is monotonic), so it sits inside the retention window
    and is never collected mid-write."""
    from tpuflow.core.dist import is_primary

    if not is_primary() or keep_last < 1:
        return []
    if not os.path.isdir(checkpoint_dir):
        return []
    from tpuflow.ckpt.sharded import (
        _SHARD_PAT,
        manifest_step,
        meta_path,
        sharded_set_files,
    )

    # candidates: (step_key, path, kind, orphan_files)
    epoch_ns: List[tuple] = []
    step_ns: List[tuple] = []
    shard_files: dict = {}
    manifest_steps = set()
    for fn in os.listdir(checkpoint_dir):
        p = os.path.join(checkpoint_dir, fn)
        sm = _SHARD_PAT.search(fn)
        if sm:
            shard_files.setdefault(int(sm.group(1)), []).append(p)
            continue
        if _STEP_PAT.search(fn):
            step_ns.append(
                (int(_STEP_PAT.search(fn).group(1)), p, "file", ()))
        elif _PAT.search(fn):
            epoch_ns.append(
                (int(_PAT.search(fn).group(1)), p, "file", ()))
        else:
            s = manifest_step(fn)
            if s is not None:
                manifest_steps.add(s)
                step_ns.append((s, p, "manifest", ()))
    for s, fl in shard_files.items():
        if s not in manifest_steps:  # orphaned set: killed mid-save
            step_ns.append((s, "", "orphan", tuple(sorted(fl))))
    removed: List[str] = []
    # deploy pins (ISSUE 15): a manifest the serving-side ModelWatcher
    # is mid-restore on is untouchable, wherever retention would rank
    # it — the watcher pins before verify and unpins after the swap
    pinned = {os.path.abspath(p) for p in pinned_checkpoints()}
    for ns in (epoch_ns, step_ns):
        ns.sort(reverse=True)  # newest first
        if not ns[keep_last:]:
            continue  # nothing to delete: don't pay the validity scan
        newest_valid = next(
            (c for c in ns if c[2] != "orphan"
             and (c[1] == just_wrote or _candidate_valid(c[1]))),
            None,
        )
        for cand in ns[keep_last:]:
            if cand is newest_valid:
                continue
            if cand[1] and (os.path.abspath(cand[1]) in pinned
                            or _externally_pinned(cand[1])):
                continue
            _step, path, kind, orphans = cand
            if kind == "manifest":
                # any sidecar still present is stale (a live one made
                # us skip above): collect it with its set
                doomed = sharded_set_files(path) + _pin_sidecars_of(path)
            elif kind == "orphan":
                doomed = list(orphans) + [
                    meta_path(f) for f in orphans
                    if os.path.exists(meta_path(f))
                ]
            else:
                doomed = [path]
            for f in doomed:
                try:
                    os.unlink(f)
                    removed.append(f)
                except OSError:
                    pass
    return removed
