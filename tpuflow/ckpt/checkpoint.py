"""Checkpoint / resume (C17, SURVEY.md §5.4).

The reference writes rank-0-only per-epoch weight checkpoints
(``ModelCheckpoint(save_weights_only=True)`` to
``{checkpoint_dir}/checkpoint-{epoch}.ckpt``,
P2/02_hyperopt_distributed_model.py:65-67,206-211) but never restores.
This module keeps the layout semantics and ADDS real resume: full
TrainState (params + BN stats + optimizer state + step) serialization,
atomic writes, latest-checkpoint discovery, and restore-into-state.

Serialization is flax msgpack (dependency-light, host-RAM friendly at
this model scale). Only the primary process WRITES files, but saving
cross-process-sharded (ZeRO/FSDP) state is a COLLECTIVE — every
process must call save_checkpoint so the assembling allgathers match
(see save_checkpoint's contract). Restored state is placed back under
the template's shardings on load — replicated state everywhere, the
consistency story BroadcastGlobalVariablesCallback documents
(P1/03:305-308), and partitioned state sliced per process.
"""

from __future__ import annotations

import contextlib
import os
import re
import tempfile
from typing import Any, List, Optional

import jax
from flax import serialization

_PAT = re.compile(r"checkpoint-(\d+)\.ckpt$")


def checkpoint_number(path: str) -> int:
    """The N of a ``checkpoint-{N}.ckpt`` path (the reference's layout,
    P2/02:206-211) — the one parser for the filename format."""
    m = _PAT.search(path)
    if m is None:
        raise ValueError(f"not a checkpoint path: {path!r}")
    return int(m.group(1))


def _is_key(x: Any) -> bool:
    from tpuflow.parallel.mesh import is_typed_prng_key

    return is_typed_prng_key(x)


def _unkey(tree: Any) -> Any:
    """Typed PRNG keys → raw uint32 (msgpack-serializable)."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree
    )


def _rekey(template: Any, restored: Any) -> Any:
    """Re-wrap raw key data where the template holds typed keys."""
    return jax.tree.map(
        lambda t, r: jax.random.wrap_key_data(r) if _is_key(t) and not _is_key(r) else r,
        template,
        restored,
    )


def _path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(checkpoint_dir, f"checkpoint-{step}.ckpt")


def _host_fetch(tree: Any) -> Any:
    """Fetch a (possibly cross-process-sharded) device tree to host.

    Replicated or single-process leaves come back via plain device_get.
    PARTITIONED leaves on a non-addressable mesh (ZeRO/FSDP optimizer
    state) are assembled with a process allgather so every process
    holds the full global array — the checkpoint file is always the
    complete state regardless of how training sharded it.
    """

    def one(x):
        if _needs_allgather(x):
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x, tiled=True)
        return x

    return jax.device_get(jax.tree.map(one, tree))


def _needs_allgather(x: Any) -> bool:
    """Leaf is partitioned over devices this process cannot address —
    fetching it to host requires a process allgather."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.sharding.is_fully_replicated
    )


def is_cross_process_sharded(tree: Any) -> bool:
    """True if any leaf is partitioned over devices this process cannot
    address — i.e. saving it is a collective (see save_checkpoint)."""
    return any(_needs_allgather(x) for x in jax.tree.leaves(tree))


def save_checkpoint(
    checkpoint_dir: str,
    state: Any,
    step: int,
    weights_only: bool = False,
) -> str:
    """Write checkpoint atomically. ``weights_only`` mirrors the
    reference's save_weights_only=True (params+batch_stats only).

    COLLECTIVE when ``state`` holds cross-process-sharded leaves
    (ZeRO/FSDP): assembling them is an allgather, so EVERY process must
    call this with the same state; only the primary touches the
    filesystem (rank-0 discipline, P2/02:206-211). With fully
    replicated/addressable state (the Trainer default) non-primary
    processes may skip the call entirely — there is no collective.
    """
    payload = _build_payload(state, weights_only)
    return _atomic_save(checkpoint_dir, _path(checkpoint_dir, step), payload)


def _build_payload(state: Any, weights_only: bool):
    """THE checkpoint payload (shared by the sync and async writers so
    their file contents can never diverge): full host-fetched state
    dict, or the reference's weights-only (params+batch_stats) form."""
    if weights_only:
        return {
            "params": _host_fetch(state.params),
            "batch_stats": _host_fetch(state.batch_stats),
        }
    return _host_fetch(serialization.to_state_dict(_unkey(state)))


@contextlib.contextmanager
def join_async_writes(get_checkpointers):
    """finally-join background checkpoint writes: stacked into the
    trainers' fit ``with`` blocks so an EXCEPTIONAL exit still makes
    the in-flight write durable (and surfaces its failure) instead of
    abandoning a daemon thread mid-write — the sync path would have
    completed that checkpoint before the exception propagated.
    ``get_checkpointers`` is a callable (the checkpointer may be
    created lazily inside the loop)."""
    try:
        yield
    finally:
        for c in get_checkpointers():
            if c is not None:
                c.wait()


class AsyncCheckpointer:
    """Overlap checkpoint WRITES with training (r05).

    The device→host fetch — and, for cross-process-sharded ZeRO/FSDP
    state, the assembling allgather — must stay synchronous (it is a
    collective and it snapshots the state before the next step mutates
    it), but the serialize + atomic write is pure host work:
    :meth:`save` runs it on a background thread and returns once the
    PAYLOAD is captured, so the train loop overlaps the disk write
    with the next epoch. One write in flight at a time: ``save`` joins
    the previous write first (ordering + error propagation), and
    :meth:`wait` joins the last one — call it at train end (the
    trainers do) or before reading the files back.
    """

    def __init__(self):
        import threading

        self._threading = threading
        self._thread = None
        self._err: "BaseException | None" = None

    def save(self, checkpoint_dir: str, state: Any, step: int,
             weights_only: bool = False) -> str:
        self.wait()
        payload = _build_payload(state, weights_only)
        path = _path(checkpoint_dir, step)

        def run():
            try:
                _atomic_save(checkpoint_dir, path, payload)
            except BaseException as e:  # surfaced by the next wait()
                self._err = e

        self._thread = self._threading.Thread(
            target=run, name=f"ckpt-write-{step}", daemon=True
        )
        self._thread.start()
        return path

    def wait(self) -> None:
        """Join the in-flight write; re-raise its failure here (in the
        caller's thread) if it had one."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("async checkpoint write failed") from err


_STEP_PAT = re.compile(r"checkpoint-step-(\d+)\.ckpt$")


def _atomic_save(checkpoint_dir: str, path: str, payload: Any) -> str:
    """Rank-0 atomic write shared by both checkpoint namespaces:
    serialize → tempfile in the target dir → os.replace; the tempfile
    is unlinked on any failure so aborted writes never litter the
    checkpoint dir."""
    from tpuflow.core.dist import is_primary

    if not is_primary():
        return path
    os.makedirs(checkpoint_dir, exist_ok=True)
    data = serialization.msgpack_serialize(payload)
    fd, tmp = tempfile.mkstemp(dir=checkpoint_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def save_step_checkpoint(checkpoint_dir: str, state: Any,
                         global_step: int) -> str:
    """Mid-epoch (preemption) checkpoint: ``checkpoint-step-{N}.ckpt``
    where N is the GLOBAL step count — disjoint from the epoch-boundary
    ``checkpoint-{epoch}.ckpt`` namespace (the reference's layout,
    P2/02:206-211), so epoch-granular consumers never misread one.
    Same atomic write + rank-0 discipline as :func:`save_checkpoint`;
    always the full TrainState (exact resume is the whole point of a
    preemption save)."""
    payload = _host_fetch(serialization.to_state_dict(_unkey(state)))
    return _atomic_save(
        checkpoint_dir,
        os.path.join(checkpoint_dir, f"checkpoint-step-{global_step}.ckpt"),
        payload,
    )


def latest_resume_point(checkpoint_dir: str, steps_per_epoch: int
                        ) -> Optional[tuple]:
    """Newest checkpoint across BOTH namespaces, compared in global-
    step units (epoch ckpt N ≙ step N·steps_per_epoch; ties prefer the
    epoch file — a clean boundary). Returns ``(path, epoch,
    skip_steps)`` where ``skip_steps`` is the position within epoch
    ``epoch`` the stream must fast-forward to, or None when the
    directory holds nothing."""
    best = None  # (effective_step, is_step_ckpt, path)
    if not os.path.isdir(checkpoint_dir):
        return None
    for fn in os.listdir(checkpoint_dir):
        m = _PAT.search(fn)
        ms = _STEP_PAT.search(fn)
        if ms:
            cand = (int(ms.group(1)), 1, os.path.join(checkpoint_dir, fn))
        elif m:
            cand = (int(m.group(1)) * steps_per_epoch, 0,
                    os.path.join(checkpoint_dir, fn))
        else:
            continue
        # prefer higher step; at equal step prefer the epoch file
        if best is None or (cand[0], -cand[1]) > (best[0], -best[1]):
            best = cand
    if best is None:
        return None
    step, _is_step, path = best
    return path, step // steps_per_epoch, step % steps_per_epoch


def list_checkpoints(checkpoint_dir: str) -> List[str]:
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for fn in os.listdir(checkpoint_dir):
        if _PAT.search(fn):
            out.append(os.path.join(checkpoint_dir, fn))
    return sorted(out, key=lambda p: int(_PAT.search(p).group(1)))


def latest_checkpoint(checkpoint_dir: str) -> Optional[str]:
    cks = list_checkpoints(checkpoint_dir)
    return cks[-1] if cks else None


def restore_checkpoint(path: str) -> dict:
    """Raw payload (dict of numpy arrays)."""
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def restore_into_state(path: str, state: Any) -> Any:
    """Restore a FULL checkpoint into a template TrainState (resume).

    The template supplies structure (built by Trainer.init_state); the
    payload supplies values, including optimizer state and step, so
    training continues exactly where it stopped — the capability the
    reference gestures at but never implements (SURVEY.md §5.4).
    """
    payload = restore_checkpoint(path)
    if set(payload.keys()) == {"params", "batch_stats"}:
        restored = state.replace(
            params=serialization.from_state_dict(state.params, payload["params"]),
            batch_stats=serialization.from_state_dict(
                state.batch_stats, payload["batch_stats"]
            ),
        )
    else:
        restored = serialization.from_state_dict(_unkey(state), payload)
        restored = _rekey(state, restored)
    # keep the template's sharding (replicated across the mesh);
    # put_replicated handles non-addressable (multi-process) meshes —
    # every process restores the same file, so values are host-identical
    from tpuflow.parallel.mesh import put_replicated

    return jax.tree.map(
        lambda v, t: put_replicated(v, t.sharding)
        if hasattr(t, "sharding")
        else v,
        restored,
        state,
    )
