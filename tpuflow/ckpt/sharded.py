"""Sharded checkpoints (ISSUE 10 tentpole piece 1).

The single-file writer (tpuflow.ckpt.checkpoint) assembles
cross-process-sharded ZeRO/FSDP state with a process allgather before
rank 0 serializes the FULL state — at multi-slice scale that allgather
is exactly the traffic ZeRO sharded the optimizer state to avoid
(Rajbhandari et al., PAPERS.md), and the write wall-clock scales with
total state, not with per-process state. This module writes what each
process already holds:

- every process serializes ONLY its addressable, replica-0 shards into
  ``checkpoint-step-{N}.shard-{P}-of-{W}.ckpt`` (P = process index,
  W = process count) — chunk keys carry the leaf path and the global
  index of the slice, so the file set is self-describing and NO
  assembling collective runs on save (pinned by test);
- the primary then publishes ``checkpoint-step-{N}.manifest.json``
  atomically (tempfile + ``os.replace``) once every shard file exists
  — the manifest names each leaf's global shape/dtype and which file
  holds which slice, plus a CRC per shard file. A checkpoint EXISTS
  iff its manifest does; readers never see a torn set.

Restore is layout-free: :func:`restore_sharded_into_state` assembles
each template leaf from whatever chunks the manifest names and places
it under the TEMPLATE's own sharding — a different process count, mesh
shape, or ZeRO mode than the saver's re-slices transparently (the
elastic-resize path rides exactly this property, and reuses the
chunk/assembly helpers in-memory via :func:`host_state_dict`).

The legacy single-file format stays fully supported beside this one
(``latest_resume_point`` compares both in global-step units).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from tpuflow.ckpt.checkpoint import (
    CorruptCheckpointError,
    _unkey,
    _rekey,
    _with_footer,
    read_verified,
)

_MANIFEST_PAT = re.compile(r"checkpoint-step-(\d+)\.manifest\.json$")
_SHARD_PAT = re.compile(
    r"checkpoint-step-(\d+)\.shard-(\d+)-of-(\d+)\.ckpt$"
)
FORMAT = "tpuflow-sharded-ckpt-v1"


def manifest_path(checkpoint_dir: str, global_step: int) -> str:
    return os.path.join(
        checkpoint_dir, f"checkpoint-step-{global_step}.manifest.json"
    )


def shard_path(checkpoint_dir: str, global_step: int, p: int,
               w: int) -> str:
    return os.path.join(
        checkpoint_dir,
        f"checkpoint-step-{global_step}.shard-{p}-of-{w}.ckpt",
    )


def manifest_step(filename: str) -> Optional[int]:
    """The N of a ``checkpoint-step-{N}.manifest.json`` name (None for
    anything else) — the discovery hook checkpoint.py's resume scan
    uses."""
    m = _MANIFEST_PAT.search(filename)
    return int(m.group(1)) if m else None


# ---- flat state-dict plumbing ---------------------------------------


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    """Nested state dict → flat ``{'a/b/c': leaf}``. ``/`` is safe as a
    separator: flax collection/param names never contain it."""
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
        return out
    out[prefix[:-1] if prefix else ""] = tree
    return out


def _apply_flat(template_sd: Any, flat: Dict[str, Any],
                prefix: str = "") -> Any:
    """Rebuild the TEMPLATE's nested state-dict structure with leaves
    substituted from ``flat`` — structure-preserving where a plain
    unflatten would drop empty collections (``batch_stats={}``)."""
    if isinstance(template_sd, dict):
        return {
            k: _apply_flat(v, flat, f"{prefix}{k}/")
            for k, v in template_sd.items()
        }
    return flat[prefix[:-1] if prefix else ""]


def _norm_index(index: Tuple, shape: Tuple[int, ...]) -> Tuple[Tuple[int, int], ...]:
    """Shard index (tuple of slices) → ((start, stop), ...) with Nones
    resolved against the global shape — the canonical chunk id."""
    out = []
    for sl, dim in zip(index, shape):
        out.append((sl.start or 0, dim if sl.stop is None else sl.stop))
    return tuple(out)


def _index_str(norm: Tuple[Tuple[int, int], ...]) -> str:
    if not norm:
        return "scalar"
    return ",".join(f"{a}:{b}" for a, b in norm)


def _parse_index(s: str) -> Tuple[Tuple[int, int], ...]:
    if s == "scalar":
        return ()
    return tuple(
        (int(a), int(b))
        for a, b in (part.split(":") for part in s.split(","))
    )


def _owned_chunks(leaf: Any) -> List[Tuple[Tuple[Tuple[int, int], ...], np.ndarray]]:
    """The (index, data) chunks THIS process must write for ``leaf``:
    replica-0 addressable shards of a jax.Array (each global slice is
    written exactly once across the gang), or the whole value when the
    leaf is plain host data (only the primary calls us with those).
    Never triggers a cross-process fetch — ``shard.data`` is local by
    definition."""
    if isinstance(leaf, jax.Array):
        shape = tuple(leaf.shape)
        out = []
        seen = set()
        for sh in leaf.addressable_shards:
            if sh.replica_id != 0:
                continue
            norm = _norm_index(tuple(sh.index), shape)
            if norm in seen:  # paranoia: one write per global slice
                continue
            seen.add(norm)
            out.append((norm, np.asarray(sh.data)))
        return out
    arr = np.asarray(leaf)
    return [(tuple((0, d) for d in arr.shape), arr)]


# ---- save ------------------------------------------------------------


def meta_path(shard: str) -> str:
    """The tiny publish sidecar beside a shard file (chunk keys + CRC
    the WRITER computed): ``<shard>.meta.json``. Deleted by the primary
    after the manifest publishes; never matches the shard/step/manifest
    name patterns, so discovery and retention ignore live ones."""
    return shard + ".meta.json"


def _atomic_write(checkpoint_dir: str, final: str, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=checkpoint_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, final)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_sharded_checkpoint(
    checkpoint_dir: str,
    state: Any,
    global_step: int,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    publish_timeout_s: float = 120.0,
) -> str:
    """Write this process's shard file and (on the primary) publish the
    manifest; returns the manifest path.

    NO assembling allgather runs here — each process serializes only
    shard data it already holds, and the publish is O(manifest), not
    O(state): each writer drops a tiny ``.meta.json`` sidecar (its
    chunk keys + the CRC32/length of the bytes it just wrote), and the
    primary publishes the manifest from the W sidecars without ever
    reading a shard payload (polling up to ``publish_timeout_s`` — a
    shared checkpoint dir is already the operating assumption of the
    single-file format). Every process must call this with the same
    state/step, like ``save_checkpoint``.

    A RE-save at the same step (a post-rollback replay re-reaching an
    epoch boundary) must not let the primary record a STALE peer file:
    every process unlinks its own previous shard/sidecar first, and a
    gang barrier orders all unlinks before any write — any sidecar the
    publish poll sees is from THIS save.

    Fully-replicated leaves have exactly one replica-0 shard across
    the gang, so they are written once, by whichever process holds it;
    plain host leaves (non-jax) are written by the primary.
    """
    from tpuflow.core.dist import barrier, is_primary
    from tpuflow.testing import faults

    p = jax.process_index() if process_index is None else process_index
    w = jax.process_count() if process_count is None else process_count
    flat = _flatten(serialization.to_state_dict(_unkey(state)))
    payload: Dict[str, np.ndarray] = {}
    for key, leaf in sorted(flat.items()):
        if not isinstance(leaf, jax.Array) and not is_primary():
            continue  # host leaves are primary's to write
        for norm, data in _owned_chunks(leaf):
            payload[f"{key}|{_index_str(norm)}"] = data
    faults.fire("ckpt.write")
    os.makedirs(checkpoint_dir, exist_ok=True)
    final = shard_path(checkpoint_dir, global_step, p, w)
    for stale in (final, meta_path(final)):
        try:
            os.unlink(stale)
        except OSError:
            pass
    barrier(f"tpuflow_sharded_save_{global_step}")
    data = _with_footer(serialization.msgpack_serialize(payload))
    _atomic_write(checkpoint_dir, final, data)
    faults.file_hook("ckpt.shard", final)
    # the sidecar's CRC is of the bytes the writer MEANT to write — a
    # corrupt/truncated landing (injected or real) therefore fails
    # verify_sharded instead of being notarized into the manifest
    _atomic_write(
        checkpoint_dir, meta_path(final),
        json.dumps({
            "crc32": zlib.crc32(data) & 0xFFFFFFFF,
            "bytes": len(data),
            "chunks": sorted(payload.keys()),
        }).encode(),
    )
    mpath = manifest_path(checkpoint_dir, global_step)
    if not is_primary():
        return mpath
    # primary: wait for the full sidecar set (sidecar lands after its
    # shard, so sidecar existence == shard complete), then publish
    # atomically. leaf metadata (global shape/dtype) comes from the
    # primary's own state view — identical everywhere by contract.
    leaf_meta = {
        key: {
            "shape": list(np.shape(leaf)),
            "dtype": _leaf_dtype(leaf),
            "chunks": [],
        }
        for key, leaf in flat.items()
    }
    deadline = time.monotonic() + publish_timeout_s
    files: Dict[str, Dict[str, Any]] = {}
    metas: List[str] = []
    for q in range(w):
        fpath = shard_path(checkpoint_dir, global_step, q, w)
        mp = meta_path(fpath)
        while not os.path.exists(mp):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"shard {q}/{w} of step {global_step} did not land "
                    f"within {publish_timeout_s:g}s — cannot publish "
                    "manifest"
                )
            time.sleep(0.05)
        with open(mp) as f:
            meta = json.load(f)
        metas.append(mp)
        qname = os.path.basename(fpath)
        files[qname] = {
            "crc32": int(meta["crc32"]),
            "bytes": int(meta["bytes"]),
        }
        for chunk_key in meta["chunks"]:
            key, _, idx = chunk_key.rpartition("|")
            if key not in leaf_meta:  # saver had a leaf we don't know
                continue
            leaf_meta[key]["chunks"].append(
                {"index": [list(ab) for ab in _parse_index(idx)],
                 "file": qname}
            )
    manifest = {
        "format": FORMAT,
        "global_step": int(global_step),
        "shards": w,
        "files": files,
        "leaves": leaf_meta,
    }
    _atomic_write(checkpoint_dir, mpath,
                  json.dumps(manifest, indent=1).encode())
    for mp in metas:  # sidecars served their purpose
        try:
            os.unlink(mp)
        except OSError:
            pass
    faults.file_hook("ckpt.file", mpath)
    return mpath


def _leaf_dtype(leaf: Any) -> str:
    if isinstance(leaf, jax.Array):
        return str(leaf.dtype)
    return str(np.asarray(leaf).dtype)


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def _load_shard(path: str) -> Dict[str, np.ndarray]:
    """Verified chunk dict of one shard file (CRC footer checked)."""
    return serialization.msgpack_restore(read_verified(path))


# ---- read side -------------------------------------------------------


def load_manifest(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CorruptCheckpointError(f"{path}: unreadable manifest "
                                     f"({e})") from e
    if man.get("format") != FORMAT:
        raise CorruptCheckpointError(
            f"{path}: unknown sharded-checkpoint format "
            f"{man.get('format')!r}"
        )
    return man


def sharded_set_files(mpath: str) -> List[str]:
    """The manifest plus every shard file it references (retention GC
    deletes a set as one unit). Unreadable manifest → the manifest and
    any shard files matching its step by NAME (a half-written set must
    still be collectable)."""
    d = os.path.dirname(mpath)
    try:
        man = load_manifest(mpath)
        out = [mpath] + [os.path.join(d, fn) for fn in man["files"]]
    except (CorruptCheckpointError, KeyError, TypeError):
        step = manifest_step(os.path.basename(mpath))
        out = [mpath]
        if step is not None and os.path.isdir(d):
            for fn in os.listdir(d):
                m = _SHARD_PAT.search(fn)
                if m and int(m.group(1)) == step:
                    out.append(os.path.join(d, fn))
    # a publish that crashed mid-way can leave .meta.json sidecars
    return out + [meta_path(f) for f in out[1:]
                  if os.path.exists(meta_path(f))]


def verify_sharded(mpath: str) -> bool:
    """Integrity gate for discovery: manifest parses AND every shard
    file exists with the recorded byte count + CRC32. A missing or
    bit-flipped shard invalidates the whole set — resume falls back to
    the previous valid checkpoint."""
    try:
        man = load_manifest(mpath)
    except CorruptCheckpointError:
        return False
    d = os.path.dirname(mpath)
    for fn, rec in man.get("files", {}).items():
        p = os.path.join(d, fn)
        try:
            if os.path.getsize(p) != int(rec["bytes"]):
                return False
            if _crc32_file(p) != int(rec["crc32"]):
                return False
        except (OSError, KeyError, TypeError, ValueError):
            return False
    return True


def latest_manifest(checkpoint_dir: str, *, min_step: int = -1,
                    verify: bool = True) -> Optional[str]:
    """The newest published manifest above ``min_step`` (optionally
    only a :func:`verify_sharded`-clean one) — the one-call answer to
    "what would a deployment pick up next?" for scripts and operator
    tooling (ISSUE 15): publish is atomic and manifest-last, so a
    manifest that verifies IS a promoted checkpoint. The serving
    ``ModelWatcher`` runs its own sweep instead of this helper — it
    must PIN each candidate before verifying (the gc race) and track
    per-step failure state; semantic parity between the two is pinned
    by tests/test_serve_deploy.py."""
    for mp in reversed(list_sharded_checkpoints(checkpoint_dir)):
        step = manifest_step(os.path.basename(mp))
        if step is None or step <= min_step:
            continue
        if not verify or verify_sharded(mp):
            return mp
    return None


def list_sharded_checkpoints(checkpoint_dir: str) -> List[str]:
    """Manifest paths under ``checkpoint_dir``, oldest step first."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for fn in os.listdir(checkpoint_dir):
        if manifest_step(fn) is not None:
            out.append(os.path.join(checkpoint_dir, fn))
    return sorted(
        out, key=lambda p: manifest_step(os.path.basename(p))
    )


def assemble_leaves(mpath: str,
                    want: Optional[List[str]] = None
                    ) -> Dict[str, np.ndarray]:
    """Full host arrays for manifest leaves (all, or just ``want``):
    allocate the global shape, fill every chunk from its shard file.
    This is the re-slice pivot — the caller places the result under
    ANY target sharding, independent of the saver's layout."""
    man = load_manifest(mpath)
    d = os.path.dirname(mpath)
    shard_cache: Dict[str, Dict[str, np.ndarray]] = {}
    out: Dict[str, np.ndarray] = {}
    for key, meta in man["leaves"].items():
        if want is not None and key not in want:
            continue
        shape = tuple(meta["shape"])
        full = np.empty(shape, np.dtype(meta["dtype"]))
        covered = 0
        for chunk in meta["chunks"]:
            fn = chunk["file"]
            if fn not in shard_cache:
                try:
                    shard_cache[fn] = _load_shard(os.path.join(d, fn))
                except (OSError, CorruptCheckpointError) as e:
                    raise CorruptCheckpointError(
                        f"{mpath}: shard {fn} unreadable ({e})"
                    ) from e
            norm = tuple(tuple(ab) for ab in chunk["index"])
            data = shard_cache[fn].get(f"{key}|{_index_str(norm)}")
            if data is None:
                raise CorruptCheckpointError(
                    f"{mpath}: chunk {key}|{_index_str(norm)} missing "
                    f"from {fn}"
                )
            sl = tuple(slice(a, b) for a, b in norm)
            full[sl] = np.asarray(data).reshape(
                tuple(b - a for a, b in norm)
            )
            covered += int(np.prod([b - a for a, b in norm],
                                   dtype=np.int64)) if norm else 1
        if covered < int(np.prod(shape, dtype=np.int64) if shape else 1):
            raise CorruptCheckpointError(
                f"{mpath}: leaf {key} chunks cover {covered} of "
                f"{int(np.prod(shape)) if shape else 1} elements"
            )
        out[key] = full
    return out


def restore_sharded_into_state(mpath: str, state: Any) -> Any:
    """Restore a sharded checkpoint into a template TrainState,
    RE-SLICING under the template's own mesh/shardings — the saver's
    process count and mesh shape are irrelevant (the manifest speaks
    global indices). Parity with single-file restore is pinned by
    test."""
    from tpuflow.parallel.mesh import put_replicated

    template_sd = serialization.to_state_dict(_unkey(state))
    template_flat = _flatten(template_sd)
    host = assemble_leaves(mpath, want=list(template_flat.keys()))
    missing = [k for k in template_flat if k not in host]
    if missing:
        raise CorruptCheckpointError(
            f"{mpath}: template leaves missing from manifest: "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    restored = serialization.from_state_dict(
        _unkey(state), _apply_flat(template_sd, host)
    )
    restored = _rekey(state, restored)
    return jax.tree.map(
        lambda v, t: put_replicated(v, t.sharding)
        if hasattr(t, "sharding") else v,
        restored,
        state,
    )


# ---- in-memory twin (elastic resize) ---------------------------------


def host_state_dict(state: Any) -> Dict[str, np.ndarray]:
    """Flat ``{key: full host array}`` of a (possibly sharded) state,
    assembled from ADDRESSABLE shards only — the in-memory twin of
    save-then-assemble that elastic resize uses at a block boundary
    (no files, and in the single-controller case no collective).
    Raises if this process cannot see every element (a true
    multi-process resize goes through the on-disk shard set
    instead)."""
    flat = _flatten(serialization.to_state_dict(_unkey(state)))
    out: Dict[str, np.ndarray] = {}
    for key, leaf in flat.items():
        if not isinstance(leaf, jax.Array):
            out[key] = np.asarray(leaf)
            continue
        shape = tuple(leaf.shape)
        if leaf.is_fully_addressable:
            out[key] = np.asarray(jax.device_get(leaf))
            continue
        full = np.empty(shape, leaf.dtype)
        covered = 0
        for norm, data in _owned_chunks(leaf):
            sl = tuple(slice(a, b) for a, b in norm)
            full[sl] = data.reshape(tuple(b - a for a, b in norm))
            covered += int(np.prod([b - a for a, b in norm],
                                   dtype=np.int64)) if norm else 1
        total = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if covered < total:
            raise ValueError(
                f"host_state_dict: leaf {key} is only {covered}/{total} "
                "addressable from this process — use the on-disk "
                "sharded checkpoint for multi-process re-sharding"
            )
        out[key] = full
    return out


def place_state_dict(host: Dict[str, np.ndarray], template: Any) -> Any:
    """Flat host arrays → a state shaped and SHARDED like ``template``
    (the restore half of :func:`host_state_dict`; elastic resize calls
    this with the NEW mesh's template)."""
    from tpuflow.parallel.mesh import put_replicated

    template_sd = serialization.to_state_dict(_unkey(template))
    restored = serialization.from_state_dict(
        _unkey(template), _apply_flat(template_sd, dict(host))
    )
    restored = _rekey(template, restored)
    return jax.tree.map(
        lambda v, t: put_replicated(v, t.sharding)
        if hasattr(t, "sharding") else v,
        restored,
        template,
    )
