from tpuflow.ckpt.checkpoint import (  # noqa: F401
    AsyncCheckpointer,
    CorruptCheckpointError,
    gc_checkpoints,
    latest_checkpoint,
    latest_resume_point,
    list_checkpoints,
    pin_checkpoint,
    pinned_checkpoints,
    restore_checkpoint,
    restore_into_state,
    save_checkpoint,
    save_step_checkpoint,
    unpin_checkpoint,
    verify_checkpoint,
)
from tpuflow.ckpt.sharded import (  # noqa: F401
    latest_manifest,
    list_sharded_checkpoints,
    restore_sharded_into_state,
    save_sharded_checkpoint,
    verify_sharded,
)
