from tpuflow.ckpt.checkpoint import (  # noqa: F401
    AsyncCheckpointer,
    latest_checkpoint,
    latest_resume_point,
    list_checkpoints,
    restore_checkpoint,
    restore_into_state,
    save_checkpoint,
    save_step_checkpoint,
)
