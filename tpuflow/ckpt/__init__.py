from tpuflow.ckpt.checkpoint import (  # noqa: F401
    latest_checkpoint,
    list_checkpoints,
    restore_checkpoint,
    restore_into_state,
    save_checkpoint,
)
