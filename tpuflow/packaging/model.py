"""Packaged inference model (C13) — the mlflow.pyfunc equivalent.

≙ ``FlowerPyFunc(mlflow.pyfunc.PythonModel)``
(P2/03_pyfunc_distributed_inference.py:157-234): a self-contained
directory bundling weights + image params + class names + pre/post
processing, loadable by URI, taking raw JPEG bytes in and returning
class-name strings out (argmax over logits, P2/03:206-212).

Behavior notes vs the reference:
- The reference's pyfunc preprocess diverges from its training
  preprocess (PIL resize WITHOUT preprocess_input scaling,
  P2/03:214-234 — flagged in SURVEY.md §7). Here the packaged model
  applies the SAME pipeline as training (native decode → bilinear
  resize → [-1,1] scale): unified on purpose; the divergence was a bug
  in the reference, not a behavior to keep.
- The bytes-as-str transport quirk is preserved: inputs that arrive as
  ``str(b'...')`` reprs are repaired via ast.literal_eval
  (≙ P2/03:226-229).

Directory layout:
  MODEL.json        format metadata, classes, img params, model config
  weights.msgpack   params + batch_stats
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from tpuflow.track.store import _atomic_json

_FORMAT_VERSION = 1

# model_type -> builder(model_config) -> flax module. Extensible so other
# model families can package themselves.
_MODEL_BUILDERS: Dict[str, Any] = {}


def register_model_builder(model_type: str, builder) -> None:
    _MODEL_BUILDERS[model_type] = builder


def _default_builders():
    if "transfer_classifier" not in _MODEL_BUILDERS:
        from tpuflow.models import TransferClassifier

        register_model_builder(
            "transfer_classifier",
            lambda cfg: TransferClassifier(
                num_classes=cfg["num_classes"],
                dropout=cfg.get("dropout", 0.0),
                width_mult=cfg.get("width_mult", 1.0),
                freeze_backbone=cfg.get("freeze_backbone", True),
                backbone=cfg.get("backbone", "mobilenet_v2"),
                fold_bn=cfg.get("fold_bn", False),
            ),
        )


def save_packaged_model(
    out_dir: str,
    params: Any,
    batch_stats: Any,
    classes: Sequence[str],
    img_height: int = 224,
    img_width: int = 224,
    img_channels: int = 3,
    model_type: str = "transfer_classifier",
    model_config: Optional[Dict[str, Any]] = None,
) -> str:
    """≙ mlflow.pyfunc.log_model(python_model=FlowerPyFunc(), artifacts=...)
    (P2/03:354-363) — but as a plain directory format."""
    import jax
    from flax import serialization

    os.makedirs(out_dir, exist_ok=True)
    model_config = dict(model_config or {})
    model_config.setdefault("num_classes", len(classes))
    meta = {
        "format_version": _FORMAT_VERSION,
        "model_type": model_type,
        "classes": list(classes),
        "img_params": {
            "img_height": img_height,
            "img_width": img_width,
            "img_channels": img_channels,
        },
        "model_config": model_config,
    }
    _atomic_json(os.path.join(out_dir, "MODEL.json"), meta)
    payload = {
        "params": jax.device_get(params),
        "batch_stats": jax.device_get(batch_stats),
    }
    with open(os.path.join(out_dir, "weights.msgpack"), "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
    return out_dir


class PackagedModel:
    """Loaded packaged model: JPEG bytes → class-name strings.

    ``fold_bn=True`` (serving-time BN folding, r05): the backbone's
    BatchNorms fold into their convs AT LOAD — packaged weights stay
    in the canonical unfolded format on disk, every BN layer leaves
    the serving graph (tpuflow.models.classifier.fold_backbone_
    variables; inference is exactly where folding is always valid).
    transfer_classifier only."""

    def __init__(self, path: str, fold_bn: bool = False):
        # ≙ FlowerPyFunc.load_context (P2/03:161-184)
        from flax import serialization

        with open(os.path.join(path, "MODEL.json")) as f:
            self.meta = json.load(f)
        if self.meta.get("format_version", 0) > _FORMAT_VERSION:
            raise ValueError("packaged model from a newer format version")
        _default_builders()
        cfg = self.meta["model_config"]
        if fold_bn:
            if self.meta["model_type"] != "transfer_classifier":
                raise ValueError(
                    "fold_bn serving is only defined for the "
                    "transfer_classifier family (the CNN backbones)"
                )
            # the folded module: BN gone; freeze flag irrelevant at
            # inference (train=False) but the module guard requires it
            cfg = dict(cfg, fold_bn=True, freeze_backbone=True)
        builder = _MODEL_BUILDERS[self.meta["model_type"]]
        self.model = builder(cfg)
        with open(os.path.join(path, "weights.msgpack"), "rb") as f:
            payload = serialization.msgpack_restore(f.read())
        self.variables = {
            "params": payload["params"],
            "batch_stats": payload.get("batch_stats", {}),
        }
        if fold_bn:
            from tpuflow.models.classifier import fold_backbone_variables

            self.variables = fold_backbone_variables(
                self.variables,
                backbone=self.meta["model_config"].get(
                    "backbone", "mobilenet_v2"
                ),
            )
        self.classes: List[str] = self.meta["classes"]
        ip = self.meta["img_params"]
        self.img_height, self.img_width = ip["img_height"], ip["img_width"]
        self._jit_forward = None

    # -- preprocessing ----------------------------------------------------

    @staticmethod
    def _coerce_bytes(x: Any) -> bytes:
        """Repair bytes that crossed a serialization boundary as their
        str repr (≙ ast.literal_eval fix, P2/03:226-229)."""
        if isinstance(x, (bytes, bytearray)):
            return bytes(x)
        if isinstance(x, str):
            return ast.literal_eval(x)
        raise TypeError(f"expected JPEG bytes, got {type(x)}")

    def preprocess(self, contents: Iterable[Any]) -> np.ndarray:
        """JPEG bytes → uint8 [N,H,W,3] via the native decode plane
        (replaces the reference's per-row PIL loop, P2/03:204 — the
        documented throughput cliff)."""
        from tpuflow.native import decode_resize_batch

        blobs = [self._coerce_bytes(c) for c in contents]
        images, _ok = decode_resize_batch(
            blobs, self.img_height, self.img_width
        )
        return images

    # -- prediction -------------------------------------------------------

    def predict_logits(self, contents: Sequence[Any], batch_size: int = 64) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        from tpuflow.models.preprocess import preprocess_input

        if self._jit_forward is None:
            model = self.model
            from tpuflow.obs.executables import registered_jit

            @registered_jit(key="packaging.predict_logits")
            def fwd(variables, x):
                return model.apply(variables, preprocess_input(x), train=False)

            self._jit_forward = fwd
        out = []
        n = len(contents)
        for s in range(0, n, batch_size):
            chunk = list(contents[s : s + batch_size])
            images = self.preprocess(chunk)
            # pad to full batch so XLA compiles ONE static shape
            pad = batch_size - len(chunk)
            if pad:
                images = np.concatenate(
                    [images, np.zeros((pad, *images.shape[1:]), np.uint8)]
                )
            logits = self._jit_forward(self.variables, jnp.asarray(images))
            out.append(np.asarray(logits[: len(chunk)], np.float32))
        return np.concatenate(out) if out else np.zeros((0, len(self.classes)), np.float32)

    def predict(self, contents: Sequence[Any], batch_size: int = 64) -> List[str]:
        """≙ FlowerPyFunc.predict: argmax → class-name strings
        (P2/03:186-212)."""
        logits = self.predict_logits(contents, batch_size)
        idx = logits.argmax(axis=-1)
        return [self.classes[i] for i in idx]


def load_packaged_model(
    uri_or_path: str, store=None, registry=None, fold_bn: bool = False
) -> PackagedModel:
    """Load by path, ``runs:/...`` or ``models:/...`` URI
    (≙ mlflow.pyfunc.load_model, P2/03:446). ``fold_bn=True`` folds
    the backbone's BatchNorms into the convs at load (serving-time
    folding — see PackagedModel)."""
    path = uri_or_path
    if uri_or_path.startswith("models:/"):
        if registry is None:
            raise ValueError("models:/ uri needs a registry")
        path = registry.resolve_uri(uri_or_path)
    elif uri_or_path.startswith("runs:/"):
        if store is None:
            raise ValueError("runs:/ uri needs a tracking store")
        path = store.resolve_uri(uri_or_path)
    return PackagedModel(path, fold_bn=fold_bn)
