from tpuflow.packaging.model import (  # noqa: F401
    PackagedModel,
    load_packaged_model,
    save_packaged_model,
)
