from tpuflow.packaging.model import (  # noqa: F401
    PackagedModel,
    load_packaged_model,
    save_packaged_model,
)
from tpuflow.packaging.lm import (  # noqa: F401
    PackagedLM,
    load_packaged_lm,
    save_packaged_lm,
)
