"""Packaged LM — the pyfunc-style artifact for the transformer family.

The reference's packaged-model concept (C13: weights + config + pre/post
processing in one loadable directory, P2/03:157-234) applied to the
model family the reference doesn't have: a causal LM whose "predict" is
autoregressive generation (tpuflow.infer.generate) and whose eval is
next-token loss / perplexity. Same directory format family as
tpuflow.packaging.model (MODEL.json + weights.msgpack), same registry
story (register the directory, stage it, load by URI).

The TEXT surface (``generate_text``) is the bucketed serving frontend
of the blockwise engine: prompts are grouped into POWER-OF-TWO token-
length buckets, each row LEFT-padded to its bucket with the pad slots
masked out of attention (``pad_lens`` — tpuflow.infer.generate), so a
table-scale run compiles once per (length bucket, batch bucket)
instead of once per distinct prompt length. Buckets drain in
``serve_slots``-sized waves refilled from the pending queue, the
batch-granularity form of continuous batching (finished waves free
their slots for queued prompts immediately; in-scan slot swapping is
the engine-level next step).

Directory layout:
  MODEL.json        format metadata, model_config, generate_defaults
  weights.msgpack   params
  tokenizer.json    (optional) bundled ByteBPE — enables the TEXT
                    surface: generate_text / score_text take raw
                    strings, the symmetry of the image packaged model's
                    bytes-in contract (P2/03:186-212)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from tpuflow.track.store import _atomic_json

_FORMAT_VERSION = 1
_MODEL_TYPE = "transformer_lm"

# smallest prompt-length bucket: prompts shorter than this pad up to it
# (one compile covers every prompt of 1..8 tokens; the pad slots are
# attention-masked, so outputs are unchanged)
_MIN_LEN_BUCKET = 8


def _bucket_len(plen: int) -> int:
    """Next power of two >= plen, floored at _MIN_LEN_BUCKET — the
    prompt-CAPACITY bucket shared by every prompt that pads to it."""
    return max(_MIN_LEN_BUCKET, 1 << (max(1, plen) - 1).bit_length())


def save_packaged_lm(
    out_dir: str,
    params: Any,
    model_config: Dict[str, Any],
    generate_defaults: Optional[Dict[str, Any]] = None,
    tokenizer=None,
) -> str:
    """Bundle LM params + build config (+ default sampling knobs) into a
    loadable directory (≙ mlflow.pyfunc.log_model, P2/03:354-363).

    ``model_config`` is the kwargs of
    :func:`tpuflow.models.build_transformer_lm` that rebuild this
    architecture (vocab_size, dim, depth, heads, ...).
    """
    import jax
    from flax import serialization

    os.makedirs(out_dir, exist_ok=True)
    model_config = dict(model_config)
    if "dtype" in model_config and not isinstance(model_config["dtype"], str):
        # normalize real dtypes to their name so the JSON round trip is
        # loadable (getattr(jnp, name) on load)
        model_config["dtype"] = np.dtype(model_config["dtype"]).name
    meta = {
        "format_version": _FORMAT_VERSION,
        "model_type": _MODEL_TYPE,
        "model_config": model_config,
        "generate_defaults": dict(generate_defaults or {}),
    }
    _atomic_json(os.path.join(out_dir, "MODEL.json"), meta)
    with open(os.path.join(out_dir, "weights.msgpack"), "wb") as f:
        f.write(
            serialization.msgpack_serialize({"params": jax.device_get(params)})
        )
    if tokenizer is not None:
        from tpuflow.data.text import ByteBPE

        if not isinstance(tokenizer, ByteBPE):
            # a HuggingFace tokenizer's .save() would silently write its
            # own format here and make the artifact unloadable later
            raise ValueError(
                "save_packaged_lm bundles tpuflow ByteBPE tokenizers "
                f"only (got {type(tokenizer).__name__}); convert or "
                "ship the external tokenizer alongside the artifact"
            )
        tokenizer.save(os.path.join(out_dir, "tokenizer.json"))
    return out_dir


class PackagedLM:
    """Loaded packaged LM: token prompts in → continuations out."""

    def __init__(self, path: str):
        from flax import serialization

        from tpuflow.models import build_transformer_lm

        with open(os.path.join(path, "MODEL.json")) as f:
            self.meta = json.load(f)
        if self.meta.get("format_version", 0) > _FORMAT_VERSION:
            raise ValueError("packaged LM from a newer format version")
        if self.meta.get("model_type") != _MODEL_TYPE:
            raise ValueError(
                f"not a packaged LM: model_type={self.meta.get('model_type')!r}"
                " (image classifiers load via tpuflow.packaging.PackagedModel)"
            )
        cfg = dict(self.meta["model_config"])
        # dtype arrives as a string after the JSON round trip
        if isinstance(cfg.get("dtype"), str):
            import jax.numpy as jnp

            cfg["dtype"] = getattr(jnp, cfg["dtype"])
        # a packaged model serves OUTSIDE shard_map: strip the training
        # topology axes (an LM trained with ring-attention SP or expert
        # sharding has identical params; the named axes matter only at
        # sharded apply time — same twin trick as LMTrainer.init_state)
        cfg.pop("seq_axis", None)
        cfg.pop("ep_axis", None)
        self.model = build_transformer_lm(**cfg)
        self._jit_loss = None
        self._jit_text_loss = None
        with open(os.path.join(path, "weights.msgpack"), "rb") as f:
            payload = serialization.msgpack_restore(f.read())
        self.params = payload["params"]
        self.generate_defaults: Dict[str, Any] = self.meta.get(
            "generate_defaults", {}
        )
        self.tokenizer = None
        tok_path = os.path.join(path, "tokenizer.json")
        if os.path.exists(tok_path):
            from tpuflow.data.text import ByteBPE

            try:
                self.tokenizer = ByteBPE.load(tok_path)
            except ValueError:
                # foreign/corrupt tokenizer file: the id-based surface
                # must keep working; only the text surface is lost
                self.tokenizer = None

    def generate(
        self,
        prompts: np.ndarray,
        max_new_tokens: Optional[int] = None,
        **kwargs,
    ) -> np.ndarray:
        """(B, P) int32 prompts → (B, P + max_new_tokens) int32.
        Keyword args (temperature, top_k, top_p, seed, eos_id) default
        to the packaged ``generate_defaults``."""
        from tpuflow.infer.generate import generate

        opts = dict(self.generate_defaults)
        opts.update(kwargs)
        if max_new_tokens is None:
            max_new_tokens = int(opts.pop("max_new_tokens", 32))
        else:
            opts.pop("max_new_tokens", None)
        out = generate(
            self.model,
            self.params,
            np.asarray(prompts, np.int32),
            max_new_tokens=int(max_new_tokens),
            **opts,
        )
        return np.asarray(out)

    def _require_tokenizer(self):
        if self.tokenizer is None:
            raise ValueError(
                "this packaged LM has no bundled tokenizer; package with "
                "save_packaged_lm(..., tokenizer=ByteBPE(...)) to use "
                "the text surface, or call generate()/score() on ids"
            )
        return self.tokenizer

    def generate_text(
        self,
        prompts: "Sequence[str]",
        max_new_tokens: Optional[int] = None,
        serve_slots: Optional[int] = None,
        scheduler: str = "slot",
        **kwargs,
    ) -> "list[str]":
        """Raw strings in -> continued strings out (prompt INCLUDED,
        like generate()) — the text symmetry of the image packaged
        model's bytes-in contract.

        Prompts are encoded with the bundled tokenizer and grouped into
        POWER-OF-TWO token-length buckets: each row is LEFT-padded to
        its bucket length and the engine masks the pad slots out of
        attention (``pad_lens`` — tpuflow.infer.generate), so one
        compile covers EVERY prompt length that shares a bucket instead
        of one compile per distinct length. Each bucket drains in
        ``serve_slots``-sized waves refilled from the bucket's pending
        queue (continuous batching at wave granularity: a finished wave
        frees all its slots for queued prompts at once; ``None`` serves
        each bucket in a single wave). Wave batches are padded up to
        the next power of two (pad rows repeat row 0 and are
        discarded), so a table-scale run compiles once per (length
        bucket, batch bucket) — without this, generate_table's chunking
        makes group sizes vary per chunk and recompiles repeatedly
        (ADVICE r03). Output order matches input order.

        Sampling (temperature > 0) draws per-ROW keys folded by
        (logical step, row index) (infer/generate._sample), so a row's
        RNG stream is independent of the pad rows appended after it AND
        of how much left-padding its bucket added (logit-level numerics
        can still vary with batch shape on some backends) — but a
        prompt's ROW INDEX within its wave depends on which other
        prompts share the bucket, so sampled outputs can differ from a
        one-at-a-time loop (greedy output is identical either way).

        ``scheduler`` selects the ``serve_slots`` engine: ``'slot'``
        (default) routes through the slot-level continuous-batching
        scheduler (tpuflow.serve — finished rows free their slot at
        decode-SEGMENT boundaries and queued prompts prefill into them
        mid-flight), ``'wave'`` keeps the original wave-drain loop
        here. The two are token-identical under pinned seeds (each
        request's RNG stream is keyed by its admission index, not its
        physical slot; tests/test_serve.py pins the parity), so 'wave'
        doubles as the slot scheduler's oracle."""
        tok = self._require_tokenizer()
        if scheduler not in ("slot", "wave"):
            raise ValueError(
                f"scheduler must be 'slot' or 'wave', got {scheduler!r}"
            )
        if serve_slots is not None and serve_slots < 1:
            raise ValueError(f"serve_slots must be >= 1, got {serve_slots}")
        if serve_slots is not None and scheduler == "slot":
            from tpuflow.serve.scheduler import serve_texts

            opts = dict(self.generate_defaults)
            opts.update(kwargs)
            if max_new_tokens is None:
                max_new_tokens = int(opts.pop("max_new_tokens", 32))
            else:
                opts.pop("max_new_tokens", None)
            known = {"temperature", "top_k", "top_p", "seed", "eos_id"}
            # only EXPLICIT kwargs can reject the call: a package whose
            # generate_defaults carry engine-tuning keys (engine,
            # prefill_chunk, ... — valid for generate()/the wave path)
            # must keep serving; those defaults simply don't apply to
            # the slot engine
            extra = set(kwargs) - known
            if extra:
                raise ValueError(
                    f"scheduler='slot' takes sampling kwargs "
                    f"{sorted(known)} only (got {sorted(extra)}); "
                    "engine-tuning kwargs need scheduler='wave'"
                )
            return serve_texts(
                self, list(prompts), int(max_new_tokens), int(serve_slots),
                temperature=float(opts.get("temperature", 0.0)),
                top_k=opts.get("top_k"), top_p=opts.get("top_p"),
                eos_id=opts.get("eos_id"), seed=int(opts.get("seed", 0)),
            )
        eos = kwargs.get("eos_id", self.generate_defaults.get("eos_id"))
        encoded = [np.asarray(tok.encode(p), np.int32) for p in prompts]
        by_bucket: "dict[int, list[int]]" = {}
        for i, ids in enumerate(encoded):
            by_bucket.setdefault(_bucket_len(len(ids)), []).append(i)
        out: "list[Optional[str]]" = [None] * len(prompts)
        wave = serve_slots or max(1, len(prompts))
        for blen, queue in by_bucket.items():
            while queue:
                idxs, queue = queue[:wave], queue[wave:]
                batch = np.zeros((len(idxs), blen), np.int32)
                pads = np.empty((len(idxs),), np.int32)
                for row, i in enumerate(idxs):
                    ids = encoded[i]
                    pads[row] = blen - len(ids)
                    batch[row, pads[row]:] = ids
                # next pow2 >= B, capped at the CALLER's total prompt
                # count: generate_table sizes its chunks to the device-
                # memory budget, and padding past it could OOM
                bucket = min(1 << (len(idxs) - 1).bit_length(),
                             len(prompts))
                if bucket > len(idxs):
                    batch = np.concatenate(
                        [batch, np.tile(batch[:1], (bucket - len(idxs), 1))]
                    )
                    pads = np.concatenate(
                        [pads, np.tile(pads[:1], bucket - len(idxs))]
                    )
                fulls = self.generate(batch, max_new_tokens=max_new_tokens,
                                      pad_lens=pads, **kwargs)
                for row, i in enumerate(idxs):
                    # strip the row's left pads: logical prompt + gen
                    full = fulls[row][int(pads[row]):]
                    plen = len(encoded[i])
                    if eos is not None:
                        # after a row emits eos the remaining fixed-
                        # length positions repeat it — truncate before
                        # decoding
                        cont = full[plen:]
                        hits = np.nonzero(cont == int(eos))[0]
                        if len(hits):
                            full = full[: plen + int(hits[0])]
                    out[i] = tok.decode(full).decode("utf-8", "replace")
        return out

    def score_text(self, texts: "Sequence[str]") -> Dict[str, float]:
        """Mean next-token loss + perplexity over raw strings: encodes
        with the bundled tokenizer, right-pads to the longest row, and
        masks the padded targets (token_loss's ignore_index) so ragged
        documents score exactly."""
        import jax
        import jax.numpy as jnp

        from tpuflow.models.transformer import perplexity, token_loss

        tok = self._require_tokenizer()
        rows = [tok.encode(t) for t in texts]
        short = [i for i, r in enumerate(rows) if len(r) < 2]
        if not rows or short:
            raise ValueError(
                "score_text needs at least 2 tokens per text; texts at "
                f"indices {short or '[]'} are too short"
            )
        width = max(len(r) for r in rows)
        ids = np.zeros((len(rows), width), np.int32)
        tgt = np.full((len(rows), width), -1, np.int32)
        for i, r in enumerate(rows):
            ids[i, : len(r)] = r
            tgt[i, : len(r)] = r
        if self._jit_text_loss is None:
            # one jitted closure; jax re-specializes per padded width
            from tpuflow.obs.executables import registered_jit

            self._jit_text_loss = registered_jit(
                key="packaging.score_text",
            )(lambda params, ids, tgt: token_loss(
                    self.model.apply({"params": params}, ids)[:, :-1],
                    tgt[:, 1:], ignore_index=-1,
                )
            )
        loss = float(self._jit_text_loss(
            self.params, jnp.asarray(ids), jnp.asarray(tgt)
        ))
        return {"loss": loss, "ppl": perplexity(loss)}

    def score(self, tokens: np.ndarray) -> Dict[str, float]:
        """Mean next-token loss + perplexity of (B, S) int32 rows —
        the LM analogue of the classifier's evaluate metrics."""
        import jax
        import jax.numpy as jnp

        from tpuflow.models.transformer import next_token_loss, perplexity

        if self._jit_loss is None:
            # built once — score() in an eval loop must not retrace
            from tpuflow.obs.executables import registered_jit

            self._jit_loss = registered_jit(
                key="packaging.score",
            )(lambda params, toks: next_token_loss(
                self.model.apply({"params": params}, toks), toks
            ))
        loss = float(
            self._jit_loss(self.params, jnp.asarray(tokens, jnp.int32))
        )
        return {"loss": loss, "ppl": perplexity(loss)}


def load_packaged_lm(
    uri_or_path: str, store=None, registry=None
) -> PackagedLM:
    """Load by path, ``runs:/...`` or ``models:/...`` URI
    (≙ mlflow.pyfunc.load_model, P2/03:446, for the LM format)."""
    path = uri_or_path
    if uri_or_path.startswith("models:/"):
        if registry is None:
            raise ValueError("models:/ uri needs a registry")
        path = registry.resolve_uri(uri_or_path)
    elif uri_or_path.startswith("runs:/"):
        if store is None:
            raise ValueError("runs:/ uri needs a tracking store")
        path = store.resolve_uri(uri_or_path)
    return PackagedLM(path)
