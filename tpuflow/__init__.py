"""tpuflow — a TPU-native distributed deep-learning framework.

Re-implements, TPU-first, the full capability surface of the reference
workshop (smellslikeml/distributed-deep-learning-workshop): columnar image
table store, sharded streaming input pipeline with a native C++ decode
plane, Flax transfer-learning models, data-parallel training over a
``jax.sharding.Mesh`` with XLA collectives (replacing Horovod/NCCL),
experiment tracking + model registry (replacing MLflow), TPE
hyperparameter search (replacing Hyperopt), packaged inference models and
distributed batch inference (replacing the pyfunc/Spark-UDF path).

Layer map (see SURVEY.md §1 for the reference's equivalent):

  cli/        multi-host SPMD launcher (≙ HorovodRunner/mpirun)
  parallel/   mesh + collectives (≙ Horovod C++ core over NCCL/MPI)
  data/       table store + streaming loader (≙ Delta Lake + Petastorm)
  native/     C++ JPEG decode/resize data plane (≙ tf.data C++ kernels)
  models/     Flax models + preprocess (≙ Keras/MobileNetV2)
  ops/        Pallas/XLA custom ops
  train/      Trainer, schedules, callbacks (≙ Keras fit + hvd callbacks)
  ckpt/       checkpoint/resume (≙ ModelCheckpoint)
  track/      run tracking + model registry (≙ MLflow)
  packaging/  packaged inference model format (≙ mlflow.pyfunc)
  tune/       TPE search + trial executors (≙ Hyperopt)
  infer/      distributed batch inference (≙ spark_udf)
  obs/        profiling, MFU, device metrics (≙ Ganglia/Horovod timeline)
"""

__version__ = "0.1.0"
