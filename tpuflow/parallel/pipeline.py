"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

Absent from the reference (SURVEY.md §2c — its only training
parallelism is Horovod data parallelism); first-class here because the
TPU build targets model scales where one chip cannot hold the stack.

TPU-idiomatic SPMD formulation (no per-stage programs, no host
scheduler): every device runs the SAME traced computation inside
``shard_map`` over a ``pipe`` mesh axis —

- layer parameters are STACKED with a leading stage dimension and
  sharded over the axis, so each device holds its own stage's weights;
- a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks runs the
  classic GPipe fill/steady/drain schedule: stage 0 ingests one
  microbatch per tick, every stage applies its layer, and activations
  hop to the next stage via ``lax.ppermute`` (one neighbor ICI
  transfer per tick — XLA overlaps it with the next tick's compute);
- backward falls out of autodiff: differentiating the scan replays the
  schedule in reverse (ppermute's transpose is the reverse ppermute),
  which IS GPipe's accumulate-over-microbatches backward.

The bubble fraction is (n_stages-1)/(n_micro+n_stages-1); pick
``n_micro >= 4 * n_stages`` to amortize it.

Stage functions must be shape-uniform (same activation shape in and
out) — the standard homogeneous-blocks restriction of SPMD pipelining;
put the embed/head in the first/last stage fns if they differ.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpuflow.core.compat import axis_size as _axis_size
from tpuflow.core.compat import typeof as _typeof
from tpuflow.parallel.collectives import pvary as _pvary

PIPE_AXIS = "pipe"


def stack_stage_params(stage_params: Sequence[Any]):
    """Stack per-stage parameter pytrees along a new leading axis.

    The result is what you shard over the pipe axis:
    ``in_specs=P('pipe')`` gives each device a (1, ...) slice; pipeline()
    strips that leading axis before calling the stage fn.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
) -> Callable[[Any, Any], Any]:
    """Build the per-device pipelined apply, for use inside shard_map.

    ``stage_fn(stage_params, x_micro) -> y_micro`` is one stage's
    computation (shape-preserving). Returns ``run(stacked_params, x)``
    where, per device, ``stacked_params`` is this stage's (1, ...) slice
    and ``x`` is the full ``(n_micro, micro_batch, ...)`` input
    (replicated; only stage 0 reads it). The returned buffer holds the
    pipeline outputs on the LAST stage (zeros elsewhere) — use
    ``from_last_stage`` to replicate them, or reduce on-stage (e.g. a
    loss) and ``from_last_stage`` the scalar.
    """

    def run(stacked_params, x):
        params = jax.tree.map(lambda a: a[0], stacked_params)
        idx = lax.axis_index(axis_name)
        n = _axis_size(axis_name)
        n_micro = x.shape[0]
        if n_micro != n_microbatches:
            raise ValueError(
                f"input has {n_micro} microbatches, pipeline built for "
                f"{n_microbatches}"
            )
        ticks = n_micro + n - 1
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(carry, t):
            state, outbuf = carry
            # stage 0 ingests microbatch t (clipped garbage during drain
            # ticks — those outputs never reach a valid output slot)
            inp = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
            )
            cur = jnp.where(idx == 0, inp, state)
            y = stage_fn(params, cur)
            # the microbatch fed at tick p arrives at the last stage at
            # tick p + n - 1 ⇒ this tick's last-stage output is slot t-(n-1)
            pos = t - (n - 1)
            written = lax.dynamic_update_index_in_dim(
                outbuf, y, jnp.clip(pos, 0, n_micro - 1), axis=0
            )
            outbuf = jnp.where((pos >= 0) & (idx == n - 1), written, outbuf)
            state = lax.ppermute(y, axis_name, fwd_perm)
            return (state, outbuf), None

        # the carry must vary over the pipe axis AND any axes the input
        # already varies over (e.g. 'data' under DP x PP row sharding)
        axes = tuple(
            getattr(_typeof(x), "vma", frozenset()) | {axis_name}
        )
        state0 = _pvary(jnp.zeros(x.shape[1:], x.dtype), axes)
        out0 = _pvary(jnp.zeros_like(x), axes)
        (_, outbuf), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
        return outbuf

    return run


def pipeline_1f1b(
    first_fn: Callable[[Any, Any], Any],
    stage_fn: Callable[[Any, Any], Any],
    last_fn: Callable[[Any, Any, Any], Any],
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
) -> Callable[[Any, Any, Any, Any, Any], Any]:
    """1F1B (one-forward-one-backward) pipeline schedule, manual VJP.

    :func:`pipeline` (GPipe) differentiates the forward scan, so
    autodiff keeps ALL ``n_micro`` stage inputs alive until the drain
    finishes — activation memory O(n_micro). 1F1B starts microbatch
    ``m``'s backward the moment the last stage has its loss, draining
    residuals as it goes: at most ``2·n_stages-1`` stage inputs are
    resident per device (a circular buffer here), the schedule of
    production pipeline trainers (PipeDream-flush / Megatron's
    non-interleaved 1F1B). Same math as GPipe — gradients accumulate
    over all microbatches before the (outside) optimizer step — only
    the op ORDER and residual lifetime differ.

    Per tick every device runs ONE forward op (microbatch ``t - s``)
    and ONE backward op (microbatch ``t - (2S-2-s)``), with activations
    hopping forward and gradients hopping backward via ``ppermute``;
    the backward recomputes its stage forward from the saved INPUT
    (per-stage rematerialization, as GPipe-with-remat would).

    ``first_fn(first_params, data) -> x`` — the (cheap, recomputed per
    tick) input embedding; running it INSIDE stage 0 lets its parameter
    gradient accumulate in place, so nothing O(n_micro) is ever
    carried. ``stage_fn(stage_params, x) -> y`` — shape-preserving
    block stack. ``last_fn(last_params, y, targets) -> scalar`` — one
    microbatch's MEAN loss (final norm + LM head + loss live here:
    1F1B needs the loss per-microbatch at the last stage to seed each
    backward).

    Returns ``run(stacked_params, first_params, last_params,
    data_micro, tgt_micro) -> (loss_mean, stage_grads, first_grads,
    last_grads)`` for use INSIDE ``shard_map`` over ``axis_name``:
    in_specs ``(P(axis), P(), P(), P(), P())``, out_specs ``(P(),
    P(axis), P(), P())`` — ``stage_grads`` carries a leading length-1
    stage axis matching the stacked layout, the rest are replicated.
    """

    def run(stacked_params, first_params, last_params, data_micro,
            tgt_micro):
        # every value in the schedule varies over the pipe axis AND any
        # axes the microbatch data already varies over (e.g. 'data'
        # under DP x PP row sharding)
        axes = tuple(
            getattr(_typeof(data_micro), "vma", frozenset())
            | {axis_name}
        )
        # stage params too: they are pipe-sharded but replicated over
        # any data axis, and their VJP must stay per-device math (the
        # caller mean-reduces the returned grads across replicas)
        params = jax.tree.map(
            lambda a: _pvary(a[0], axes), stacked_params
        )
        # tag the replicated first/head params as varying up front: the
        # VJPs inside the per-stage conds must be pure per-device math
        # (a VJP w.r.t. an UNVARYING operand would make the type system
        # insert a psum over the axis — a collective inside a
        # conditionally-executed branch)
        first_params = jax.tree.map(
            lambda p: _pvary(p, axes), first_params
        )
        last_params = jax.tree.map(
            lambda p: _pvary(p, axes), last_params
        )
        idx = lax.axis_index(axis_name)
        n = _axis_size(axis_name)
        m_total = data_micro.shape[0]
        if m_total != n_microbatches:
            raise ValueError(
                f"input has {m_total} microbatches, pipeline built for "
                f"{n_microbatches}"
            )
        w = 2 * n  # circular residual slots (in-flight ≤ 2n-1)
        ticks = m_total + 2 * n - 2
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]
        bwd_perm = [(i, (i - 1) % n) for i in range(n)]
        inv_m = 1.0 / m_total

        def _zeros_varying(tree):
            return jax.tree.map(
                lambda p: _pvary(jnp.zeros_like(p), axes), tree
            )

        def _data_at(buf, i):
            return lax.dynamic_index_in_dim(
                buf, jnp.clip(i, 0, m_total - 1), 0, keepdims=False
            )

        # activation shape/dtype via an eval_shape probe (no FLOPs)
        x_probe = jax.eval_shape(
            lambda fp, d: first_fn(fp, d), first_params, data_micro[0]
        )
        x_shape, x_dtype = x_probe.shape, x_probe.dtype

        def tick(carry, t):
            fwd_in, bwd_in, resid, gacc, facc, lacc, loss_acc = carry
            f = t - idx  # this stage's forward microbatch
            b = t - (2 * n - 2 - idx)  # this stage's backward microbatch
            valid_f = (f >= 0) & (f < m_total)
            valid_b = (b >= 0) & (b < m_total)
            slot_f = lax.rem(jnp.clip(f, 0, m_total - 1), w)
            slot_b = lax.rem(jnp.clip(b, 0, m_total - 1), w)

            # ---- one forward op (stage 0 embeds its microbatch; the
            # embed is cheap enough to recompute rather than carry)
            x_in = jnp.where(
                idx == 0,
                first_fn(first_params, _data_at(data_micro, f)),
                fwd_in,
            )
            y = stage_fn(params, x_in)
            # save the stage INPUT (backward recomputes from it); only
            # while valid — a drain-tick write could clobber a residual
            # whose backward has not run yet
            resid = jnp.where(
                valid_f,
                lax.dynamic_update_index_in_dim(resid, x_in, slot_f, 0),
                resid,
            )

            # ---- last stage: this tick's fwd micro IS its bwd micro
            # (f == b there) — loss + seed gradient via the head's VJP
            def head(args):
                y_, tgt_ = args
                lv, vjp = jax.vjp(
                    lambda lp, yy: last_fn(lp, yy, tgt_), last_params, y_
                )
                # seed must carry the loss's varying-manual-axes type
                dlp, dy_ = vjp(
                    _pvary(jnp.asarray(inv_m, jnp.float32), axes)
                )
                return lv, dlp, dy_

            def no_head(args):
                return (
                    _pvary(jnp.zeros((), jnp.float32), axes),
                    _zeros_varying(last_params),
                    _pvary(jnp.zeros(x_shape, x_dtype), axes),
                )

            is_last = idx == n - 1
            lv, dlp, dy = lax.cond(
                is_last & valid_b, head, no_head,
                (y, _data_at(tgt_micro, b)),
            )
            loss_acc = loss_acc + lv
            lacc = jax.tree.map(jnp.add, lacc, dlp)

            # ---- one backward op (remat from the saved input)
            g_in = jnp.where(is_last, dy, bwd_in)
            x_saved = lax.dynamic_index_in_dim(resid, slot_b, 0,
                                               keepdims=False)

            def do_bwd(args):
                xs, gi = args
                _, vjp = jax.vjp(stage_fn, params, xs)
                return vjp(gi)

            def no_bwd(args):
                return (
                    _zeros_varying(params),
                    _pvary(jnp.zeros(x_shape, x_dtype), axes),
                )

            dp, dx = lax.cond(valid_b, do_bwd, no_bwd, (x_saved, g_in))
            gacc = jax.tree.map(jnp.add, gacc, dp)

            # ---- stage 0: dx is the embedding-output gradient for
            # micro b — fold it into the first_fn parameter grads NOW
            # (an embed-param-sized accumulator, not an O(n_micro)
            # activation buffer)
            def do_first(args):
                d_b, dxv = args
                _, vjp = jax.vjp(
                    lambda fp: first_fn(fp, d_b), first_params
                )
                (dfp,) = vjp(dxv)
                return dfp

            def no_first(args):
                return _zeros_varying(first_params)

            dfp = lax.cond(
                valid_b & (idx == 0), do_first, no_first,
                (_data_at(data_micro, b), dx),
            )
            facc = jax.tree.map(jnp.add, facc, dfp)

            fwd_next = lax.ppermute(y, axis_name, fwd_perm)
            bwd_next = lax.ppermute(dx, axis_name, bwd_perm)
            return (
                fwd_next, bwd_next, resid, gacc, facc, lacc, loss_acc
            ), None

        zeros_x = _pvary(jnp.zeros(x_shape, x_dtype), axes)
        carry0 = (
            zeros_x,
            zeros_x,
            _pvary(jnp.zeros((w, *x_shape), x_dtype), axes),
            _zeros_varying(params),
            _zeros_varying(first_params),
            _zeros_varying(last_params),
            _pvary(jnp.zeros((), jnp.float32), axes),
        )
        (_, _, _, gacc, facc, lacc, loss_acc), _ = lax.scan(
            tick, carry0, jnp.arange(ticks)
        )
        # loss/lacc live on the last stage, facc on stage 0; zeros
        # elsewhere, so a plain psum replicates them
        loss_mean = lax.psum(loss_acc, axis_name) * inv_m
        first_grads = jax.tree.map(
            lambda g: lax.psum(g, axis_name), facc
        )
        last_grads = jax.tree.map(
            lambda g: lax.psum(g, axis_name), lacc
        )
        stage_grads = jax.tree.map(lambda g: g[None], gacc)
        return loss_mean, stage_grads, first_grads, last_grads

    return run


# ---- shared helpers for the interleaved schedules (train + fwd-only) ----

def _micro_at(buf, i, m_total):
    """Microbatch ``i`` of a ``(n_micro, ...)`` buffer (index clipped —
    invalid slots read garbage that is never consumed)."""
    return lax.dynamic_index_in_dim(
        buf, jnp.clip(i, 0, m_total - 1), 0, keepdims=False
    )


def _buf_read(buf, c, w, x_shape):
    """Read activation ``(chunk c, buffer slot w)`` of a
    ``(v, n_buf, *x_shape)`` buffer."""
    return lax.dynamic_slice(
        buf, (c, w) + (0,) * len(x_shape), (1, 1) + x_shape
    ).reshape(x_shape)


def _buf_write_if(buf, val, c, w, valid, x_shape):
    """Write ``val`` at ``(c, w)`` when ``valid`` — read-select-write
    keeps the conditional O(activation), not O(buffer): a jnp.where
    over the whole buffer would copy it every slot."""
    cur = _buf_read(buf, c, w, x_shape)
    return lax.dynamic_update_slice(
        buf,
        jnp.where(valid, val, cur).reshape((1, 1) + x_shape),
        (c, w) + (0,) * len(x_shape),
    )


def _sched_tables(sched, keys):
    """Schedule tables as replicated device constants (each device
    gathers its own column with axis_index)."""
    return {k: jnp.asarray(getattr(sched, k)) for k in keys}


def pipeline_interleaved(
    first_fn: Callable[[Any, Any], Any],
    stage_fn: Callable[[Any, Any], Any],
    last_fn: Callable[[Any, Any, Any], Any],
    sched,
    axis_name: str = PIPE_AXIS,
) -> Callable[[Any, Any, Any, Any, Any], Any]:
    """Interleaved (virtual-stage) 1F1B schedule, manual VJP.

    Each device holds ``v = sched.n_chunks`` NON-contiguous model
    chunks (round-robin: device ``d`` owns stages ``d, d+n, ...``), and
    each schedule slot runs ONE op — a chunk forward or a chunk
    backward — per the precomputed tables of
    :class:`tpuflow.parallel.interleave.InterleavedSchedule`. The flush
    bubble is ``~2*(n-1)`` chunk-ops instead of the non-interleaved
    ``~2*(n-1)`` FULL-stage ops: v× less idle time, traded for ~v× the
    resident activations (``sched.n_buf`` per chunk) and one
    activation + one gradient ``ppermute`` per chunk-op instead of per
    stage-op.

    ``first_fn``/``stage_fn``/``last_fn`` contract matches
    :func:`pipeline_1f1b` (embed recomputed at stage 0, loss head
    inside the last chunk's backward, per-stage rematerialization from
    the saved chunk INPUT). Returns ``run(stacked_params, first_params,
    last_params, data_micro, tgt_micro) -> (loss_mean, stage_grads,
    first_grads, last_grads)`` for use inside ``shard_map``: in_specs
    ``(P(axis), P(), P(), P(), P())``, out_specs ``(P(), P(axis), P(),
    P())``. Per-device ``stacked_params`` leaves carry a leading
    ``(v, ...)`` chunk axis — globally ``(n*v, ...)`` in DEVICE-MAJOR
    order (device d's chunks at rows ``[d*v, (d+1)*v)``), i.e. global
    row ``d*v + c`` holds model stage ``c*n + d``.
    """
    n = sched.n_devices
    v = sched.n_chunks
    m_total = sched.n_micro
    n_buf = sched.n_buf
    inv_m = 1.0 / m_total
    tb = _sched_tables(sched, (
        "op_valid", "op_kind", "op_chunk", "op_micro", "op_buf",
        "arecv_valid", "arecv_chunk", "arecv_buf",
        "grecv_valid", "grecv_chunk", "grecv_buf",
    ))

    def run(stacked_params, first_params, last_params, data_micro,
            tgt_micro):
        if data_micro.shape[0] != m_total:
            raise ValueError(
                f"input has {data_micro.shape[0]} microbatches, schedule "
                f"built for {m_total}"
            )
        axes = tuple(
            getattr(_typeof(data_micro), "vma", frozenset())
            | {axis_name}
        )
        params = jax.tree.map(lambda a: _pvary(a, axes), stacked_params)
        first_params = jax.tree.map(lambda p: _pvary(p, axes), first_params)
        last_params = jax.tree.map(lambda p: _pvary(p, axes), last_params)
        idx = lax.axis_index(axis_name)
        if _axis_size(axis_name) != n:
            raise ValueError(
                f"axis {axis_name!r} has size {_axis_size(axis_name)}, "
                f"schedule built for {n}"
            )
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]
        bwd_perm = [(i, (i - 1) % n) for i in range(n)]

        def _zeros_varying(tree):
            return jax.tree.map(
                lambda p: _pvary(jnp.zeros_like(p), axes), tree
            )

        def _data_at(buf, i):
            return _micro_at(buf, i, m_total)

        def _chunk_at(tree, c):
            return jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                tree,
            )

        x_probe = jax.eval_shape(
            lambda fp, d: first_fn(fp, d), first_params, data_micro[0]
        )
        x_shape, x_dtype = x_probe.shape, x_probe.dtype

        def _read(buf, c, w):
            return _buf_read(buf, c, w, x_shape)

        def _write_if(buf, val, c, w, valid):
            return _buf_write_if(buf, val, c, w, valid, x_shape)

        def slot(carry, t):
            fwd_msg, bwd_msg, xbuf, gbuf, gacc, facc, lacc, loss_acc = carry
            cell = {k: tb[k][t, idx] for k in tb}
            # ---- route last slot's ring arrivals into the buffers
            xbuf = _write_if(
                xbuf, fwd_msg, cell["arecv_chunk"], cell["arecv_buf"],
                cell["arecv_valid"],
            )
            gbuf = _write_if(
                gbuf, bwd_msg, cell["grecv_chunk"], cell["grecv_buf"],
                cell["grecv_valid"],
            )

            c, w = cell["op_chunk"], cell["op_buf"]
            micro, valid = cell["op_micro"], cell["op_valid"]
            params_c = _chunk_at(params, c)
            is_s0 = (idx == 0) & (c == 0)
            is_last = (idx == n - 1) & (c == v - 1)

            def fwd_branch(carry_in):
                xbuf, gbuf, gacc, facc, lacc, loss_acc = carry_in
                x_arr = _read(xbuf, c, w)
                x_emb = first_fn(first_params, _data_at(data_micro, micro))
                x_in = jnp.where(is_s0, x_emb, x_arr)
                # persist stage 0's input for its backward recompute
                # (other stages' inputs were persisted on arrival)
                xbuf = _write_if(xbuf, x_in, c, w, valid & is_s0)
                y = stage_fn(params_c, x_in)
                zero_dx = _pvary(jnp.zeros(x_shape, x_dtype), axes)
                return (xbuf, gbuf, gacc, facc, lacc, loss_acc, y, zero_dx)

            def bwd_branch(carry_in):
                xbuf, gbuf, gacc, facc, lacc, loss_acc = carry_in
                x_saved = _read(xbuf, c, w)
                gi = _read(gbuf, c, w)

                def with_head(args):
                    xs, _ = args
                    lv, vjp = jax.vjp(
                        lambda lp, pc, xx: last_fn(
                            lp, stage_fn(pc, xx), _data_at(tgt_micro, micro)
                        ),
                        last_params, params_c, xs,
                    )
                    dlp, dpc, dx = vjp(
                        _pvary(jnp.asarray(inv_m, jnp.float32), axes)
                    )
                    return lv, dlp, dpc, dx

                def without_head(args):
                    xs, gi_ = args
                    _, vjp = jax.vjp(stage_fn, params_c, xs)
                    dpc, dx = vjp(gi_)
                    return (
                        _pvary(jnp.zeros((), jnp.float32), axes),
                        _zeros_varying(last_params),
                        dpc, dx,
                    )

                # no invalid-op guard here: the builder emits every
                # bubble slot as kind F (asserted in its _verify), so
                # the backward branch only ever runs a REAL op
                lv, dlp, dpc, dx = lax.cond(
                    is_last, with_head, without_head, (x_saved, gi)
                )
                loss_acc = loss_acc + lv
                lacc = jax.tree.map(jnp.add, lacc, dlp)
                # accumulate this chunk's grads in place
                gacc = jax.tree.map(
                    lambda acc, g: lax.dynamic_update_index_in_dim(
                        acc,
                        lax.dynamic_index_in_dim(
                            acc, c, 0, keepdims=False) + g,
                        c, 0,
                    ),
                    gacc, dpc,
                )

                # stage 0: fold dx into the embed grads NOW (an
                # embed-sized accumulator, nothing O(n_micro) carried)
                def do_first(args):
                    d_b, dxv = args
                    _, vjp = jax.vjp(
                        lambda fp: first_fn(fp, d_b), first_params
                    )
                    (dfp,) = vjp(dxv)
                    return dfp

                def no_first(args):
                    return _zeros_varying(first_params)

                dfp = lax.cond(
                    is_s0, do_first, no_first,
                    (_data_at(data_micro, micro), dx),
                )
                facc = jax.tree.map(jnp.add, facc, dfp)
                zero_y = _pvary(jnp.zeros(x_shape, x_dtype), axes)
                return (xbuf, gbuf, gacc, facc, lacc, loss_acc, zero_y, dx)

            carry_in = (xbuf, gbuf, gacc, facc, lacc, loss_acc)
            (xbuf, gbuf, gacc, facc, lacc, loss_acc, y_out,
             dx_out) = lax.cond(
                cell["op_kind"] == 0, fwd_branch, bwd_branch, carry_in
            )
            fwd_msg = lax.ppermute(y_out, axis_name, fwd_perm)
            bwd_msg = lax.ppermute(dx_out, axis_name, bwd_perm)
            return (
                fwd_msg, bwd_msg, xbuf, gbuf, gacc, facc, lacc, loss_acc
            ), None

        zeros_x = _pvary(jnp.zeros(x_shape, x_dtype), axes)
        carry0 = (
            zeros_x,
            zeros_x,
            _pvary(jnp.zeros((v, n_buf, *x_shape), x_dtype), axes),
            _pvary(jnp.zeros((v, n_buf, *x_shape), x_dtype), axes),
            _zeros_varying(params),
            _zeros_varying(first_params),
            _zeros_varying(last_params),
            _pvary(jnp.zeros((), jnp.float32), axes),
        )
        (_, _, _, _, gacc, facc, lacc, loss_acc), _ = lax.scan(
            slot, carry0, jnp.arange(sched.n_ticks)
        )
        loss_mean = lax.psum(loss_acc, axis_name) * inv_m
        first_grads = jax.tree.map(lambda g: lax.psum(g, axis_name), facc)
        last_grads = jax.tree.map(lambda g: lax.psum(g, axis_name), lacc)
        return loss_mean, gacc, first_grads, last_grads

    return run


def pipeline_interleaved_fwd(
    first_fn: Callable[[Any, Any], Any],
    stage_fn: Callable[[Any, Any], Any],
    sched,
    axis_name: str = PIPE_AXIS,
) -> Callable[[Any, Any, Any], Any]:
    """Forward-only interleaved pipeline (for eval/inference through the
    interleaved DEVICE-MAJOR parameter layout, which the contiguous
    GPipe :func:`pipeline` cannot consume). Uses the same slot tables
    with every backward op a no-op slot; the last chunk's outputs are
    collected per microbatch and replicated via :func:`from_last_stage`
    by the caller. Returns ``run(stacked_params, first_params,
    data_micro) -> (n_micro, ...)`` last-stage outputs (zeros off the
    last device).
    """
    n, v, m_total, n_buf = (
        sched.n_devices, sched.n_chunks, sched.n_micro, sched.n_buf
    )
    tb = _sched_tables(sched, (
        "op_valid", "op_kind", "op_chunk", "op_micro", "op_buf",
        "arecv_valid", "arecv_chunk", "arecv_buf",
    ))

    def run(stacked_params, first_params, data_micro):
        axes = tuple(
            getattr(_typeof(data_micro), "vma", frozenset())
            | {axis_name}
        )
        params = jax.tree.map(lambda a: _pvary(a, axes), stacked_params)
        first_params = jax.tree.map(lambda p: _pvary(p, axes), first_params)
        idx = lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]

        x_probe = jax.eval_shape(
            lambda fp, d: first_fn(fp, d), first_params, data_micro[0]
        )
        x_shape, x_dtype = x_probe.shape, x_probe.dtype

        def slot(carry, t):
            fwd_msg, xbuf, outbuf = carry
            cell = {k: tb[k][t, idx] for k in tb}
            xbuf = _buf_write_if(
                xbuf, fwd_msg, cell["arecv_chunk"], cell["arecv_buf"],
                cell["arecv_valid"], x_shape,
            )
            c, w, micro = cell["op_chunk"], cell["op_buf"], cell["op_micro"]
            do_f = cell["op_valid"] & (cell["op_kind"] == 0)
            is_s0 = (idx == 0) & (c == 0)
            is_last = (idx == n - 1) & (c == v - 1)
            x_in = jnp.where(
                is_s0,
                first_fn(first_params, _micro_at(data_micro, micro,
                                                 m_total)),
                _buf_read(xbuf, c, w, x_shape),
            )
            params_c = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                params,
            )
            y = stage_fn(params_c, x_in)
            # collect last-chunk outputs per microbatch
            pos = jnp.clip(micro, 0, m_total - 1)
            cur_out = lax.dynamic_index_in_dim(outbuf, pos, 0,
                                               keepdims=False)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf,
                jnp.where(do_f & is_last, y, cur_out),
                pos, 0,
            )
            fwd_msg = lax.ppermute(y, axis_name, fwd_perm)
            return (fwd_msg, xbuf, outbuf), None

        zeros_x = _pvary(jnp.zeros(x_shape, x_dtype), axes)
        carry0 = (
            zeros_x,
            _pvary(jnp.zeros((v, n_buf, *x_shape), x_dtype), axes),
            _pvary(jnp.zeros((m_total, *x_shape), x_dtype), axes),
        )
        (_, _, outbuf), _ = lax.scan(
            slot, carry0, jnp.arange(sched.n_ticks)
        )
        return outbuf

    return run


def from_last_stage(x, axis_name: str = PIPE_AXIS):
    """Replicate a value held by the last pipeline stage to all stages
    (psum of a one-hot mask — a single small collective)."""
    idx = lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    return lax.psum(jnp.where(idx == n - 1, x, jnp.zeros_like(x)), axis_name)


def split_microbatches(batch, n_microbatches: int):
    """(B, ...) → (n_micro, B // n_micro, ...). B must divide evenly —
    the identical-step-count discipline of the sharded loader
    (reference P1/03:197-200) extends to microbatches."""
    b = batch.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by {n_microbatches} microbatches"
        )
    return batch.reshape(n_microbatches, b // n_microbatches, *batch.shape[1:])
