"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

Absent from the reference (SURVEY.md §2c — its only training
parallelism is Horovod data parallelism); first-class here because the
TPU build targets model scales where one chip cannot hold the stack.

TPU-idiomatic SPMD formulation (no per-stage programs, no host
scheduler): every device runs the SAME traced computation inside
``shard_map`` over a ``pipe`` mesh axis —

- layer parameters are STACKED with a leading stage dimension and
  sharded over the axis, so each device holds its own stage's weights;
- a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks runs the
  classic GPipe fill/steady/drain schedule: stage 0 ingests one
  microbatch per tick, every stage applies its layer, and activations
  hop to the next stage via ``lax.ppermute`` (one neighbor ICI
  transfer per tick — XLA overlaps it with the next tick's compute);
- backward falls out of autodiff: differentiating the scan replays the
  schedule in reverse (ppermute's transpose is the reverse ppermute),
  which IS GPipe's accumulate-over-microbatches backward.

The bubble fraction is (n_stages-1)/(n_micro+n_stages-1); pick
``n_micro >= 4 * n_stages`` to amortize it.

Stage functions must be shape-uniform (same activation shape in and
out) — the standard homogeneous-blocks restriction of SPMD pipelining;
put the embed/head in the first/last stage fns if they differ.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpuflow.parallel.collectives import pvary as _pvary

PIPE_AXIS = "pipe"


def stack_stage_params(stage_params: Sequence[Any]):
    """Stack per-stage parameter pytrees along a new leading axis.

    The result is what you shard over the pipe axis:
    ``in_specs=P('pipe')`` gives each device a (1, ...) slice; pipeline()
    strips that leading axis before calling the stage fn.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
) -> Callable[[Any, Any], Any]:
    """Build the per-device pipelined apply, for use inside shard_map.

    ``stage_fn(stage_params, x_micro) -> y_micro`` is one stage's
    computation (shape-preserving). Returns ``run(stacked_params, x)``
    where, per device, ``stacked_params`` is this stage's (1, ...) slice
    and ``x`` is the full ``(n_micro, micro_batch, ...)`` input
    (replicated; only stage 0 reads it). The returned buffer holds the
    pipeline outputs on the LAST stage (zeros elsewhere) — use
    ``from_last_stage`` to replicate them, or reduce on-stage (e.g. a
    loss) and ``from_last_stage`` the scalar.
    """

    def run(stacked_params, x):
        params = jax.tree.map(lambda a: a[0], stacked_params)
        idx = lax.axis_index(axis_name)
        n = lax.axis_size(axis_name)
        n_micro = x.shape[0]
        if n_micro != n_microbatches:
            raise ValueError(
                f"input has {n_micro} microbatches, pipeline built for "
                f"{n_microbatches}"
            )
        ticks = n_micro + n - 1
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(carry, t):
            state, outbuf = carry
            # stage 0 ingests microbatch t (clipped garbage during drain
            # ticks — those outputs never reach a valid output slot)
            inp = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
            )
            cur = jnp.where(idx == 0, inp, state)
            y = stage_fn(params, cur)
            # the microbatch fed at tick p arrives at the last stage at
            # tick p + n - 1 ⇒ this tick's last-stage output is slot t-(n-1)
            pos = t - (n - 1)
            written = lax.dynamic_update_index_in_dim(
                outbuf, y, jnp.clip(pos, 0, n_micro - 1), axis=0
            )
            outbuf = jnp.where((pos >= 0) & (idx == n - 1), written, outbuf)
            state = lax.ppermute(y, axis_name, fwd_perm)
            return (state, outbuf), None

        state0 = _pvary(jnp.zeros(x.shape[1:], x.dtype), axis_name)
        out0 = _pvary(jnp.zeros_like(x), axis_name)
        (_, outbuf), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
        return outbuf

    return run


def from_last_stage(x, axis_name: str = PIPE_AXIS):
    """Replicate a value held by the last pipeline stage to all stages
    (psum of a one-hot mask — a single small collective)."""
    idx = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    return lax.psum(jnp.where(idx == n - 1, x, jnp.zeros_like(x)), axis_name)


def split_microbatches(batch, n_microbatches: int):
    """(B, ...) → (n_micro, B // n_micro, ...). B must divide evenly —
    the identical-step-count discipline of the sharded loader
    (reference P1/03:197-200) extends to microbatches."""
    b = batch.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by {n_microbatches} microbatches"
        )
    return batch.reshape(n_microbatches, b // n_microbatches, *batch.shape[1:])
