"""Collective helpers — the layer that replaces Horovod's C++ core (N1).

The reference syncs gradients with ``hvd.DistributedOptimizer`` (ring
allreduce each step, P1/03:302), initializes consistently with
``BroadcastGlobalVariablesCallback(0)`` (P1/03:308) and averages epoch
metrics with ``MetricAverageCallback`` (P1/03:313). Here those are XLA
collectives inside traced code — compiler-scheduled, fused and
overlapped with compute, which is precisely the advantage of the
XLA/ICI path over an external NCCL engine.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpuflow.core.compat import typeof as _typeof
from tpuflow.parallel.mesh import DATA_AXIS


def pvary(x, axis_names) -> Any:
    """Tag x as varying over the given manual mesh axes — needed where
    shard_map type-checks branches/carries (lax.switch, lax.scan) and a
    constant (e.g. a zeros skip-value) must match a collective-produced
    value's varying-manual-axes. Idempotent: axes the value already
    varies over are skipped (pcast rejects varying→varying)."""
    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    have = getattr(_typeof(x), "vma", frozenset())
    axes = tuple(a for a in axes if a not in have)
    if not axes:
        return x
    try:
        return jax.lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return jax.lax.pvary(x, axes)
    except AttributeError:
        # jax 0.4.x: no varying-manual-axes tracking at all (shard_map
        # uses check_rep instead), so there is nothing to tag — the
        # value is already valid wherever newer JAX would demand a vma
        # annotation
        return x


def pvary_like(x, *refs) -> Any:
    """Tag x as varying over every manual axis the refs vary over (the
    general form: refs may vary over other mesh axes than the one a
    caller knows about, e.g. 'data' on a data x seq mesh)."""
    want = frozenset()
    for r in refs:
        want = want | getattr(_typeof(r), "vma", frozenset())
    have = getattr(_typeof(x), "vma", frozenset())
    missing = tuple(want - have)
    return pvary(x, missing) if missing else x


def pmean_tree(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """Mean-allreduce every leaf (grad sync ≙ DistributedOptimizer)."""
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def psum_tree(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), tree)


def broadcast_from_primary(tree: Any) -> Any:
    """Replicate host-0's values to all processes (outside jit).

    ≙ BroadcastGlobalVariablesCallback(0) (P1/03:305-308). With a single
    seeded init this is normally a no-op safety net; it matters when
    state was restored from a checkpoint on one host.
    """
    import jax.experimental.multihost_utils as mhu

    if jax.process_count() == 1:
        return tree
    return mhu.broadcast_one_to_all(tree)


def replicated_norm(tree: Any) -> jnp.ndarray:
    """Global L2 norm — used by the cross-process consistency check
    (the testable form of the broadcast-init invariant, SURVEY.md §5.2)."""
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))
