from tpuflow.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    data_sharding,
    replicated_sharding,
)
from tpuflow.parallel.collectives import (  # noqa: F401
    pmean_tree,
    psum_tree,
    broadcast_from_primary,
)
