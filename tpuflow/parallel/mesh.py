"""Device mesh construction — the TPU replacement for Horovod topology.

The reference's world is flat MPI ranks (hvd.rank()/size(),
P1/03_model_training_distributed.py:295-301). On TPU the topology is a
``jax.sharding.Mesh`` whose axes name the parallelism dimensions; XLA
lowers collectives onto ICI within a slice and DCN across slices
(SURVEY.md §5.8). v1 trains data-parallel (the only parallelism the
reference has, SURVEY.md §2c) but the mesh carries a ``model`` axis so
tensor-parallel sharding rules can land without re-plumbing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshSpec:
    """How to carve the device set into (data, model) axes."""

    data: int = -1  # -1 = all remaining devices
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        model = self.model
        data = self.data if self.data != -1 else n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != device count {n_devices}"
            )
        return data, model


def build_mesh(
    spec: MeshSpec = MeshSpec(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 2-D (data, model) mesh over ``devices`` (default: all).

    Device order follows jax.devices(), which on TPU reflects physical
    torus locality, so the fast-varying ``model`` axis rides the
    highest-bandwidth ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    data, model = spec.resolve(len(devices))
    arr = np.array(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def build_nd_mesh(
    axes: "dict[str, int]",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh with arbitrary named axes, e.g. {'data': 2, 'pipe': 2,
    'expert': 2} — for the parallelism dimensions beyond (data, model)
    (pipeline, expert, sequence). Axis order = dict order; put the
    fastest-communicating axis last (innermost ICI)."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {axes} != device count {len(devices)}")
    return Mesh(np.array(devices).reshape(sizes), tuple(axes.keys()))


def build_hybrid_mesh(
    dcn_axes: "dict[str, int]",
    ici_axes: "dict[str, int]",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: outer axes over DCN (across slices), inner axes
    over ICI (within a slice) — SURVEY.md §5.8's cross-slice story.

    Use data parallelism (or pipeline stages) on the DCN axes and
    bandwidth-hungry parallelism (tensor/sequence) on the ICI axes:
    XLA's collectives then keep all-gathers/reduce-scatters on the fast
    intra-slice fabric and only gradient-sized all-reduces cross DCN.
    On multi-slice TPU hardware this uses jax's topology-aware hybrid
    mesh; elsewhere (CPU meshes, single slice) it degrades to the plain
    reshape so the same code runs in tests.

    Example (2 slices of a v5e-256, DP across slices, TP inside):
        mesh = build_hybrid_mesh({"data": 2}, {"model": 8, "replica": 32})
    """
    devices = list(devices if devices is not None else jax.devices())
    if set(dcn_axes) & set(ici_axes):
        raise ValueError(
            f"axis names shared between DCN and ICI: "
            f"{sorted(set(dcn_axes) & set(ici_axes))}"
        )
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    sizes = list(dcn_axes.values()) + list(ici_axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"hybrid mesh {dcn_axes}x{ici_axes} != device count {len(devices)}"
        )
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices > 1:
        arr = _hybrid_device_array(dcn_axes, ici_axes, devices)
        assert list(arr.shape) == sizes, (arr.shape, sizes)
        return Mesh(arr, names)
    return build_nd_mesh({**dcn_axes, **ici_axes}, devices)


def _hybrid_device_array(dcn_axes, ici_axes, devices) -> np.ndarray:
    """Topology-aware (dcn..., ici...) device array for a multi-slice
    mesh. create_hybrid_device_mesh wants mesh_shape and dcn_mesh_shape
    at the SAME rank (elementwise product = the final mesh shape): pad
    each side with 1s so the returned array already has the target
    shape with DCN axes leading — no reshape (a reshape here would
    interleave devices across slices on the DCN axes)."""
    from jax.experimental import mesh_utils

    return mesh_utils.create_hybrid_device_mesh(
        [1] * len(dcn_axes) + list(ici_axes.values()),
        list(dcn_axes.values()) + [1] * len(ici_axes),
        devices=devices,
    )


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over the data axis (leading dim split)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def is_typed_prng_key(x) -> bool:
    """True for typed jax.random keys (extended prng_key dtype)."""
    from jax import dtypes as _dtypes

    return hasattr(x, "dtype") and _dtypes.issubdtype(
        getattr(x, "dtype", None), _dtypes.prng_key
    )


def put_replicated(x, sharding: NamedSharding):
    """Place one HOST-IDENTICAL *global* array under ``sharding``.

    ``x`` is the full global value, identical on every process (seeded
    init / shared checkpoint files — the broadcast-init invariant
    P1/03:305-308). Single-process (fully addressable mesh): plain
    device_put. Multi-process: ``device_put`` rejects non-addressable
    shardings, so each addressable shard is sliced out of the global
    array by index (``make_array_from_callback``) — correct for
    replicated AND partitioned specs (e.g. restoring a ZeRO/FSDP
    TrainState, where each process owns a slice of the optimizer
    state). Typed PRNG keys travel as raw key data and are re-wrapped
    on device.
    """
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    if is_typed_prng_key(x):
        if sharding.spec != P() and any(sharding.spec):
            raise NotImplementedError(
                "multi-process placement of PARTITIONED typed PRNG keys "
                f"is not supported (spec {sharding.spec}); keys in "
                "TrainState are replicated"
            )
        data = np.asarray(jax.device_get(jax.random.key_data(x)))
        g = jax.make_array_from_process_local_data(sharding, data)
        from tpuflow.obs.executables import registered_jit

        return registered_jit(
            jax.random.wrap_key_data, key="mesh.wrap_key_data",
            out_shardings=sharding,
        )(g)
    data = np.asarray(jax.device_get(x))
    return jax.make_array_from_callback(
        data.shape, sharding, lambda idx: data[idx]
    )


def replicate_tree(tree, mesh: Mesh):
    """Fully replicate a host-identical pytree across ``mesh`` (multi-
    process-safe; see put_replicated)."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: put_replicated(x, sh), tree)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def world_size(mesh: Mesh) -> int:
    return int(np.prod(list(mesh.shape.values())))
