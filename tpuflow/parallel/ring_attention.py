"""Ring attention: exact sequence-parallel attention over a mesh axis.

The long-context capability (first-class in the TPU build; absent from
the reference, which is a fixed-224px vision workload — SURVEY.md §5.7):
Q/K/V are sharded along the sequence dimension over a mesh axis; K/V
shards rotate around the ring via ``lax.ppermute`` (XLA lowers this to
neighbor ICI transfers) while each device computes blockwise flash
attention of its resident Q shard against the visiting K/V shard,
merging partial softmax results with the log-sum-exp trick.

Memory stays O(local shard) in both passes: the backward is a ring-level
``custom_vjp`` that RE-ROTATES K/V (recomputation) and lets each
dK/dV accumulator travel with its shard — after ``n`` rotations the
gradients arrive back at their home device. No full-sequence tensor is
ever materialized on any device.

Per-shard compute uses the Pallas flash kernels from
``tpuflow.ops.attention`` (interpret mode off-TPU, so CPU tests run the
real kernels).

Use inside ``shard_map`` with the sequence axis manual, e.g.::

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None),
    )(q, k, v)
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpuflow.core.compat import axis_size as _axis_size
from tpuflow.ops.attention import (
    _NEG_BIG,
    _Cfg,
    _bwd_impl,
    _bwd_ref,
    _fwd,
    _fwd_ref,
    _static_scale,
)


from tpuflow.parallel.collectives import pvary as _pvary  # noqa: E402
from tpuflow.parallel.collectives import pvary_like as _pvary_like  # noqa: E402


class _RingCfg(NamedTuple):
    axis_name: str
    n: int  # ring size (static)
    causal: bool
    scale: float
    block_q: int
    block_k: int
    s_valid: int  # unpadded LOCAL sequence length (uniform shards)
    interpret: bool
    layout: str = "contiguous"  # or "striped" (balanced causal ring)

    def block_cfg(self, causal: bool, shift: int = 0) -> _Cfg:
        return _Cfg(
            causal=causal,
            scale=self.scale,
            block_q=self.block_q,
            block_k=self.block_k,
            sq_valid=self.s_valid,
            skv_valid=self.s_valid,
            interpret=self.interpret,
            causal_shift=shift,
        )


def _rotate(x, axis_name: str, n: int):
    """Send to the next ring neighbor (i → i+1 mod n)."""
    return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def _merge(o1, lse1, o2, lse2):
    """Combine two partial softmax results via their log-sum-exps."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    l = w1 + w2
    safe = jnp.where(l > 0, l, 1.0)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / safe[..., None]
    return o, m + jnp.log(safe)


def _fwd_mode(rcfg: _RingCfg, q, k, v, mode):
    """Block attention under a traced visibility mode.

    mode 0 = skip (future shard under causal), 1 = full, 2 = diagonal
    (own shard / earlier-striped shard: inclusive causal mask), 3 =
    strict diagonal (later-striped shard: col < row — striped layout's
    balanced-causal visits).
    """
    bh, s, d = q.shape

    # off-TPU the Pallas HLO interpreter can't evaluate vma-carrying
    # operands, so the block math runs as its jnp reference (equivalence
    # kernel<->reference is covered by tests/test_ops.py)
    fwd = _fwd_ref if rcfg.interpret else _fwd

    def skip(_):
        return (
            _pvary_like(jnp.zeros((bh, s, d), q.dtype), q, k, v),
            _pvary_like(jnp.full((bh, s), _NEG_BIG, jnp.float32), q, k, v),
        )

    def full(_):
        return fwd(rcfg.block_cfg(False), q, k, v)

    def diag(_):
        return fwd(rcfg.block_cfg(True), q, k, v)

    def diag_strict(_):
        return fwd(rcfg.block_cfg(True, shift=-1), q, k, v)

    return lax.switch(mode, [skip, full, diag, diag_strict], None)


def _bwd_mode(rcfg: _RingCfg, q, k, v, o, lse, do, mode):
    bwd = _bwd_ref if rcfg.interpret else _bwd_impl

    def skip(_):
        return (
            _pvary_like(jnp.zeros(q.shape, q.dtype), q, k, v, o, lse, do),
            _pvary_like(jnp.zeros(k.shape, k.dtype), q, k, v, o, lse, do),
            _pvary_like(jnp.zeros(v.shape, v.dtype), q, k, v, o, lse, do),
        )

    def full(_):
        return bwd(rcfg.block_cfg(False), q, k, v, o, lse, do)

    def diag(_):
        return bwd(rcfg.block_cfg(True), q, k, v, o, lse, do)

    def diag_strict(_):
        return bwd(rcfg.block_cfg(True, shift=-1), q, k, v, o, lse, do)

    return lax.switch(mode, [skip, full, diag, diag_strict], None)


def _mode_at(rcfg: _RingCfg, my, t: int):
    """Visibility of the shard held at ring step t (origin (my-t) mod n).

    Contiguous layout: earlier shards are FULLY visible, later shards
    fully masked — device 0 does 1 visit of work while device n-1 does
    n (the causal ring imbalance: wall time ~n full visits for ~n/2 of
    average work). Striped layout (shard d holds global tokens d, d+n,
    d+2n, ...): EVERY pairwise visit is half-visible — inclusive causal
    over local indices when the visiting shard started earlier
    (src < my, or the own shard), STRICT causal when it started later —
    so all devices do equal ~half-visits every step and the causal wall
    time is ~n/2 (the Striped Attention balance)."""
    if not rcfg.causal:
        return jnp.int32(1)
    src = (my - t) % rcfg.n
    if rcfg.layout == "striped":
        if t == 0:
            return jnp.int32(2)
        return jnp.where(src < my, 2, 3).astype(jnp.int32)
    if t == 0:
        return jnp.int32(2)  # own shard: local causal
    return jnp.where(src < my, 1, 0).astype(jnp.int32)


def _ring_fwd_impl(rcfg: _RingCfg, q, k, v):
    my = lax.axis_index(rcfg.axis_name)
    acc_o = jnp.zeros(q.shape, jnp.float32)
    acc_lse = jnp.full(q.shape[:2], _NEG_BIG, jnp.float32)
    k_t, v_t = k, v
    for t in range(rcfg.n):
        o_b, lse_b = _fwd_mode(rcfg, q, k_t, v_t, _mode_at(rcfg, my, t))
        acc_o, acc_lse = _merge(acc_o, acc_lse, o_b.astype(jnp.float32), lse_b)
        if t < rcfg.n - 1:
            k_t = _rotate(k_t, rcfg.axis_name, rcfg.n)
            v_t = _rotate(v_t, rcfg.axis_name, rcfg.n)
    return acc_o.astype(q.dtype), acc_lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring_core(rcfg: _RingCfg, q, k, v):
    o, _ = _ring_fwd_impl(rcfg, q, k, v)
    return o


def _ring_core_fwd(rcfg: _RingCfg, q, k, v):
    o, lse = _ring_fwd_impl(rcfg, q, k, v)
    return o, (q, k, v, o, lse)


def _ring_core_bwd(rcfg: _RingCfg, res, do):
    q, k, v, o, lse = res
    my = lax.axis_index(rcfg.axis_name)
    dq = jnp.zeros(q.shape, jnp.float32)
    # (k, v) re-rotate (recomputation); (dk, dv) travel with their shard
    # and are home again after n rotations.
    k_t, v_t = k, v
    dk_t = jnp.zeros(k.shape, jnp.float32)
    dv_t = jnp.zeros(v.shape, jnp.float32)
    for t in range(rcfg.n):
        dq_c, dk_c, dv_c = _bwd_mode(
            rcfg, q, k_t, v_t, o, lse, do, _mode_at(rcfg, my, t)
        )
        dq = dq + dq_c.astype(jnp.float32)
        dk_t = dk_t + dk_c.astype(jnp.float32)
        dv_t = dv_t + dv_c.astype(jnp.float32)
        if t < rcfg.n - 1:  # k/v unused after the last contribution
            k_t = _rotate(k_t, rcfg.axis_name, rcfg.n)
            v_t = _rotate(v_t, rcfg.axis_name, rcfg.n)
        dk_t = _rotate(dk_t, rcfg.axis_name, rcfg.n)
        dv_t = _rotate(dv_t, rcfg.axis_name, rcfg.n)
    return dq.astype(q.dtype), dk_t.astype(k.dtype), dv_t.astype(v.dtype)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def striped_permutation(seq_len: int, n: int):
    """Original-index order of the STRIPED layout: applying
    ``x[..., striped_permutation(s, n), :]`` before contiguous sequence
    sharding gives shard ``d`` the global tokens ``d, d+n, d+2n, ...``
    — the round-robin assignment that balances causal ring attention
    (every pairwise shard visit is half-visible instead of
    all-or-nothing). Invert with :func:`inverse_permutation`."""
    import numpy as np

    if seq_len % n:
        raise ValueError(f"seq_len {seq_len} not divisible by ring size {n}")
    return np.arange(seq_len).reshape(seq_len // n, n).T.reshape(-1)


def inverse_permutation(perm):
    import numpy as np

    return np.argsort(np.asarray(perm))


def ring_prefill_layout(seq_len: int, n: int, layout: str = "striped"):
    """The (permute, unpermute) index pair a sequence-parallel PREFILL
    pass applies around the ring (ISSUE 13 — the serve tier's
    ring-prefill offload in :func:`tpuflow.infer.generate.
    ring_prefill_kv`): tokens permute BEFORE contiguous sharding, the
    harvested per-layer K/V unpermute back to logical token order
    before landing into KV pages. ``'striped'`` (default) balances the
    causal ring — a one-shot prompt pass is exactly the workload the
    striped schedule halves (~n/2 visits of wall time vs ~n,
    Brandon et al. 2023); ``'contiguous'`` returns identity (None,
    None). ``seq_len`` must divide by ``n`` (the caller pads the
    prompt to its pow2 bucket, which every pow2 ring size divides)."""
    if layout not in ("contiguous", "striped"):
        raise ValueError(
            f"layout must be contiguous|striped, got {layout!r}")
    if layout == "contiguous":
        return None, None
    perm = striped_permutation(seq_len, n)
    return perm, inverse_permutation(perm)


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    layout: str = "contiguous",
):
    """Sequence-parallel attention on local ``(batch, heads, seq_shard,
    head_dim)`` shards; must run inside shard_map/pjit with ``axis_name``
    manual. Differentiable; exact (not approximate) attention.

    ``causal`` treats the global sequence as the shards laid out per
    ``layout``: ``'contiguous'`` — shard ``d`` holds tokens
    ``[d·s, (d+1)·s)`` (concatenation in mesh-axis order);
    ``'striped'`` — shard ``d`` holds tokens ``d, d+n, d+2n, ...``
    (the caller pre-permutes with :func:`striped_permutation`), which
    balances the causal work across the ring: every visit is a half-
    masked diagonal instead of full-or-nothing, so wall time is ~n/2
    visits instead of n (Striped Attention).

    GQA note: q/k/v must carry EQUAL head counts here — a GQA model
    expands K/V before entering the ring (models/transformer.py). A
    native grouped ring would shrink each ppermute hop's payload by the
    group factor (the per-hop compute already supports kv_group via the
    flash kernels); it is deliberately not wired yet because the
    interpret-mode reference path and the ring's custom VJP both assume
    uniform shard shapes — future work, noted rather than risked.
    """
    if layout not in ("contiguous", "striped"):
        raise ValueError(f"layout must be contiguous|striped, got {layout!r}")
    if q.ndim != 4:
        raise ValueError(f"expected (batch, heads, seq, head_dim), got {q.shape}")
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError("ring attention requires uniform q/k/v shard shapes")
    b, h, s, d = q.shape
    n = _axis_size(axis_name)
    if interpret is None:
        from tpuflow.core.hw import is_tpu_backend

        interpret = not is_tpu_backend()
    # uniform shards ⇒ one block size; collapse BEFORE computing padding
    # so the padded length is always a multiple of the final block
    block = min(block_q, block_k, max(8, s))
    block_q = block_k = block
    pad = (-s) % block
    rcfg = _RingCfg(
        axis_name=axis_name,
        n=n,
        causal=causal,
        scale=_static_scale(scale, d),
        block_q=block_q,
        block_k=block_k,
        s_valid=s,
        interpret=bool(interpret),
        layout=layout,
    )

    from tpuflow.ops.attention import _pad_seq

    def prep(x):
        return _pad_seq(x.reshape(b * h, s, d), block)

    o = _ring_core(rcfg, prep(q), prep(k), prep(v))
    return o[:, :s].reshape(b, h, s, d)
