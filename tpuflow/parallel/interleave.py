"""Interleaved (virtual-stage) 1F1B pipeline schedule builder.

Non-interleaved 1F1B (tpuflow.parallel.pipeline.pipeline_1f1b) cuts the
network into one contiguous stage per device, so every pipeline flush
pays a bubble of ``~2*(n_devices-1)`` stage-sized ops. Interleaving
(Megatron-LM's virtual-stage schedule) cuts the network into
``n_devices * v`` chunks laid out ROUND-ROBIN — device ``d`` holds
chunks ``d, d+n, d+2n, ...`` — so each schedule op is ``1/v`` of a
device's layers and the flush bubble shrinks to ``~2*(n_devices-1)``
CHUNK-sized ops: v× less idle time for the same microbatch count.

The round-robin layout is what makes this SPMD-friendly on TPU: stage
``s`` lives on device ``s % n``, so EVERY hop ``s -> s+1`` — including
the wrap from ``(chunk c, device n-1)`` to ``(chunk c+1, device 0)`` —
is the same neighbor transfer: one forward ``lax.ppermute(+1)`` and one
backward ``ppermute(-1)`` per schedule slot riding the ICI ring.

Schedule granularity is ONE op per slot (a chunk forward OR a chunk
backward), not a rigid forward+backward pair per tick: the drain phase
is pure backwards and a paired tick would idle its forward half there,
re-inflating the bubble by ~2·n·v slots and erasing most of the
interleaving win (measured, not hypothetical — the paired variant of
this builder scheduled n=4,v=2,m=8 in 26 pair-ticks ≈ 52 slots vs 38
slots here). Each device follows the Megatron op order: ``w_d`` warmup
forwards, then strict 1F1B ``F,B`` alternation, then ``w_d`` cooldown
backwards, with ``w_d = 2*(n-d-1) + (v-1)*n``.

Control flow stays compiler-friendly (no data-dependent Python): the
schedule is precomputed HERE, on the host, as dense per-(slot, device)
integer tables — op kind, chunk, microbatch, residual-buffer slot, and
the routing of the activation/gradient arriving over the ring — by
simulating the dependency graph slot by slot. The device program
(`tpuflow.parallel.pipeline.pipeline_interleaved`) is then a
``lax.scan`` over slots that gathers its row of the tables. Simulating
rather than transcribing a closed form buys two things: the builder
VERIFIES every dependency, transfer latency, and buffer-slot lifetime
(a malformed schedule cannot leave this module), and it measures the
actual bubble so tests pin the claimed ~v× win.

The reference has no pipeline parallelism at all (SURVEY.md §2c); this
module is part of the beyond-reference scale surface.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = ["InterleavedSchedule", "build_interleaved_schedule"]

F, B = 0, 1  # op kinds


@dataclass
class InterleavedSchedule:
    """Dense schedule tables for the interleaved-1F1B device program.

    All arrays are shaped ``(n_ticks, n_devices)`` (one row per
    schedule slot); ``*_valid`` are bool, the rest int32. ``chunk``
    indexes a device's local chunks (``0..v-1``; global stage =
    ``chunk*n + device``). ``buf`` indexes the per-chunk
    activation/residual buffer — one slot serves the arriving
    activation, the saved forward input, and the arriving backward
    gradient of one (stage, microbatch), whose lifetimes nest.
    """

    n_devices: int
    n_chunks: int          # v = virtual stages per device
    n_micro: int
    n_ticks: int           # schedule slots
    n_buf: int             # activation/residual buffer depth per chunk
    op_valid: np.ndarray   # a real op this slot (False = bubble)
    op_kind: np.ndarray    # F or B
    op_chunk: np.ndarray
    op_micro: np.ndarray
    op_buf: np.ndarray
    # routing of the activation arriving over the forward ring this
    # slot (sent by the left neighbor's forward op last slot)
    arecv_valid: np.ndarray
    arecv_chunk: np.ndarray
    arecv_buf: np.ndarray
    # routing of the gradient arriving over the backward ring this slot
    grecv_valid: np.ndarray
    grecv_chunk: np.ndarray
    grecv_buf: np.ndarray
    bubble_ops: int = 0    # idle (slot, device) cells
    notes: dict = field(default_factory=dict)

    @property
    def bubble_fraction(self) -> float:
        total = self.n_ticks * self.n_devices
        return self.bubble_ops / total if total else 0.0


def _op_sequence(n: int, v: int, m_total: int, device: int):
    """Megatron interleaved op order for one device: ``w`` warmup
    forwards, strict F,B alternation, ``w`` cooldown backwards —
    ``w = 2*(n-device-1) + (v-1)*n`` capped at the total forward count.
    Forward ops walk microbatches in groups of ``n``, chunks ascending
    within a group; backward ops the same with chunks DESCENDING.
    Returns a list of (kind, chunk, micro)."""
    total = m_total * v

    def fwd(k):  # k-th forward op
        g, j = divmod(k, n * v)
        return (F, j // n, g * n + j % n)

    def bwd(k):
        g, j = divmod(k, n * v)
        return (B, v - 1 - j // n, g * n + j % n)

    w = min(total, 2 * (n - device - 1) + (v - 1) * n)
    seq = [fwd(k) for k in range(w)]
    fi, bi = w, 0
    while fi < total:
        seq.append(fwd(fi))
        seq.append(bwd(bi))
        fi += 1
        bi += 1
    seq.extend(bwd(k) for k in range(bi, total))
    return seq


def build_interleaved_schedule(
    n_devices: int, n_chunks: int, n_micro: int,
    forward_only: bool = False,
) -> InterleavedSchedule:
    """Simulate the interleaved-1F1B dependency graph and emit tables.

    Model (mirrors the device program exactly):

    - one slot = every device runs at most ONE op: a chunk forward or a
      chunk backward (the last stage's backward recomputes its forward
      and the loss head from the saved input, so it needs no same-slot
      coupling with the forward);
    - an activation/gradient produced at slot ``t`` crosses the ring
      and is usable by the neighbor from slot ``t+1``;
    - each device executes its ops IN the Megatron order of
      :func:`_op_sequence`, stalling (a bubble slot) until the pending
      op's input has arrived.

    In-order execution cannot deadlock — every dependency points to an
    op earlier in some device's sequence (the sequences are linear
    extensions of the op DAG) — and the builder re-verifies every
    emitted table plus buffer-slot reuse before returning.
    """
    n, v, m_total = n_devices, n_chunks, n_micro
    if n < 1 or v < 1:
        raise ValueError(f"need n_devices>=1, n_chunks>=1; got {n}, {v}")
    if m_total < n or m_total % n:
        raise ValueError(
            f"interleaved schedule needs n_micro divisible by n_devices "
            f"(microbatch groups of {n}); got n_micro={m_total}"
        )
    s_total = n * v
    if forward_only:
        # eval/inference: just the in-order forward ops (used by
        # pipeline_interleaved_fwd; buffer slots free after the read)
        seqs = [
            [op for op in _op_sequence(n, v, m_total, d) if op[0] == F]
            for d in range(n)
        ]
    else:
        seqs = [_op_sequence(n, v, m_total, d) for d in range(n)]
    ptr = [0] * n
    NOT_YET = 1 << 30
    # avail_f[s, m]: first slot F(s, m)'s input is on-device;
    # avail_b[s, m]: first slot B(s, m)'s seed gradient is on-device
    avail_f = np.full((s_total, m_total), NOT_YET, np.int64)
    avail_f[0, :] = 0  # stage 0 embeds its microbatch locally
    avail_b = np.full((s_total, m_total), NOT_YET, np.int64)
    f_exec = np.full((s_total, m_total), -1, np.int64)
    b_exec = np.full((s_total, m_total), -1, np.int64)

    rows = []  # per slot: list of (valid, kind, chunk, micro) per device
    done = 0
    total_ops = sum(len(s) for s in seqs)
    bubble = 0
    t = 0
    limit = 8 * (2 * m_total * v + 4 * s_total) + 64  # divergence guard
    while done < total_ops:
        if t > limit:
            raise AssertionError(
                f"schedule simulation did not converge by slot {t} "
                f"(n={n}, v={v}, m={m_total}) — scheduler bug"
            )
        row = []
        for d in range(n):
            cell = (False, F, 0, 0)
            if ptr[d] < len(seqs[d]):
                kind, c, m = seqs[d][ptr[d]]
                s = c * n + d
                ready = (
                    avail_f[s, m] <= t if kind == F else avail_b[s, m] <= t
                )
                if ready:
                    cell = (True, kind, c, m)
                    if kind == F:
                        f_exec[s, m] = t
                        if s + 1 < s_total:
                            avail_f[s + 1, m] = t + 1
                        else:
                            # loss head runs inside the backward op,
                            # recomputing from the saved input — ready
                            # the very next slot, no transfer
                            avail_b[s, m] = t + 1
                    else:
                        assert 0 <= f_exec[s, m] <= t, (s, m, t)
                        b_exec[s, m] = t
                        if s > 0:
                            avail_b[s - 1, m] = t + 1
                    ptr[d] += 1
                    done += 1
                else:
                    bubble += 1
            else:
                bubble += 1
            row.append(cell)
        rows.append(row)
        t += 1
    n_ticks = t
    if forward_only:
        # no backwards: a buffer slot frees the moment its forward
        # reads it, and there is no gradient ring traffic
        b_exec = f_exec.copy()

    # ---- buffer-slot assignment ------------------------------------------
    # One slot per (stage, micro) covers three nested lifetimes:
    #   activation arrives       at avail_f[s, m]  (stage 0: f_exec)
    #   forward reads + residual at f_exec[s, m]
    #   gradient arrives         at avail_b[s, m]
    #   backward consumes, freed at b_exec[s, m]
    # Greedy first-free-slot per stage over those intervals.
    buf_of = np.zeros((s_total, m_total), np.int64)
    n_buf = 1
    for s in range(s_total):
        free_at = []  # per-slot last occupied tick
        for m in range(m_total):
            start = f_exec[s, m] if s == 0 else avail_f[s, m]
            end = b_exec[s, m]
            assert 0 <= start <= end, (s, m, start, end)
            for i, fa in enumerate(free_at):
                if fa < start:
                    buf_of[s, m] = i
                    free_at[i] = end
                    break
            else:
                buf_of[s, m] = len(free_at)
                free_at.append(end)
        n_buf = max(n_buf, len(free_at))

    # ---- dense tables -----------------------------------------------------
    shape = (n_ticks, n)
    op_valid = np.zeros(shape, bool)
    op_kind = np.zeros(shape, np.int32)
    op_chunk = np.zeros(shape, np.int32)
    op_micro = np.zeros(shape, np.int32)
    op_buf = np.zeros(shape, np.int32)
    for tt, row in enumerate(rows):
        for d, (valid, kind, c, m) in enumerate(row):
            op_valid[tt, d] = valid
            op_kind[tt, d] = kind
            op_chunk[tt, d] = c
            op_micro[tt, d] = m
            if valid:
                op_buf[tt, d] = buf_of[c * n + d, m]

    # Activation sent by F(s, m) at slot t lands on device (s+1)%n at
    # t+1, destined for (chunk_of(s+1), buf(s+1, m)); gradient sent by
    # B(s, m) lands on (s-1)%n at t+1 for (chunk_of(s-1), buf(s-1, m)).
    arv = np.zeros(shape, bool)
    arc = np.zeros(shape, np.int32)
    arb = np.zeros(shape, np.int32)
    grv = np.zeros(shape, bool)
    grc = np.zeros(shape, np.int32)
    grb = np.zeros(shape, np.int32)
    for s in range(s_total):
        for m in range(m_total):
            tf, tb = f_exec[s, m], b_exec[s, m]
            if s + 1 < s_total:
                arv[tf + 1, (s + 1) % n] = True
                arc[tf + 1, (s + 1) % n] = (s + 1) // n
                arb[tf + 1, (s + 1) % n] = buf_of[s + 1, m]
            if s > 0 and not forward_only:
                grv[tb + 1, (s - 1) % n] = True
                grc[tb + 1, (s - 1) % n] = (s - 1) // n
                grb[tb + 1, (s - 1) % n] = buf_of[s - 1, m]

    sched = InterleavedSchedule(
        n_devices=n, n_chunks=v, n_micro=m_total, n_ticks=n_ticks,
        n_buf=n_buf,
        op_valid=op_valid, op_kind=op_kind, op_chunk=op_chunk,
        op_micro=op_micro, op_buf=op_buf,
        arecv_valid=arv, arecv_chunk=arc, arecv_buf=arb,
        grecv_valid=grv, grecv_chunk=grc, grecv_buf=grb,
        bubble_ops=int(bubble),
        notes={
            "ideal_slots": 2 * m_total * v,
            "megatron_bound_slots": 2 * m_total * v + 2 * (n - 1),
            # the non-interleaved pipeline_1f1b runs m + 2(n-1) paired
            # ticks of v-chunk work = this many chunk-op slots:
            "noninterleaved_equiv_slots": 2 * (m_total + 2 * (n - 1)) * v,
        },
    )
    _verify(sched, f_exec, b_exec, avail_f, buf_of, forward_only)
    return sched


def _verify(sched: InterleavedSchedule, f_exec, b_exec, avail_f,
            buf_of, forward_only: bool) -> None:
    """Independent re-check of the emitted tables, read back the way
    the device program will consume them."""
    n, v, m_total = sched.n_devices, sched.n_chunks, sched.n_micro
    s_total = n * v
    assert (f_exec >= 0).all() and (b_exec >= 0).all()
    assert int(
        (sched.op_valid & (sched.op_kind == F)).sum()
    ) == s_total * m_total
    assert int(
        (sched.op_valid & (sched.op_kind == B)).sum()
    ) == (0 if forward_only else s_total * m_total)
    # bubble slots are always emitted as kind F — the device program's
    # backward branch relies on this (it runs only on REAL ops, so it
    # carries no invalid-op guard; the cheaper forward branch absorbs
    # the idle slots)
    assert (sched.op_valid | (sched.op_kind == F)).all()
    for s in range(s_total):
        for m in range(m_total):
            if s > 0:  # +1-slot ring transfer latency, both directions
                assert f_exec[s, m] >= f_exec[s - 1, m] + 1, (s, m)
            if forward_only:
                continue
            if s < s_total - 1:
                assert b_exec[s, m] >= b_exec[s + 1, m] + 1, (s, m)
            else:
                assert b_exec[s, m] >= f_exec[s, m] + 1, (s, m)
            assert f_exec[s, m] < b_exec[s, m], (s, m)
    # buffer-slot lifetimes never overlap within a stage's buffer
    for s in range(s_total):
        intervals: dict = {}
        for m in range(m_total):
            start = f_exec[s, m] if s == 0 else avail_f[s, m]
            end = b_exec[s, m]
            for (a, b) in intervals.get(buf_of[s, m], ()):
                assert end < a or start > b, (
                    f"buffer collision at stage {s}: ({start},{end}) vs "
                    f"({a},{b})"
                )
            intervals.setdefault(buf_of[s, m], []).append((start, end))
    assert sched.n_buf <= m_total
