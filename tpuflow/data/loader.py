"""Sharded streaming loader: table → device-ready numpy batches (C5, N5/N8).

The Petastorm equivalent. The reference materializes dataframes to a
Parquet cache dir and streams them as an infinite, sharded tf.data
stream (``make_spark_converter`` / ``make_tf_dataset(batch_size,
cur_shard, shard_count)``, reference
P1/03_model_training_distributed.py:137-144,332-337). Semantics kept:

- ``num_epochs=None`` ⇒ infinite stream so every worker sees identical
  batch counts; an epoch is a fixed step count (P1/03:197-200,350-351);
- shard by (cur_shard, shard_count) with identical shard sizes;
- cache-dir materialization + ``delete()`` cleanup (P1/03:425-426);
- drop-remainder static batch shapes (XLA requires static shapes).

The decode hot path runs in the native C++ plane (tpuflow.native) on a
background producer thread, so host decode overlaps device compute.
"""

from __future__ import annotations

import os
import queue
import threading
from queue import Empty as _QueueEmpty
import uuid
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from tpuflow.data.table import Table
from tpuflow.native import decode_resize_batch


def take_shard_rows(
    rb: pa.RecordBatch, gidx: int, shard: Tuple[int, int]
) -> Optional[pa.RecordBatch]:
    """Rows of ``rb`` whose GLOBAL row index (``gidx`` + local position)
    belongs to shard ``(cur, n)`` under round-robin (modulo) assignment.

    THE shard convention, shared by every consumer — the training
    loader and streaming batch inference — so a convention change can
    never desync them. Returns None when no rows land in the shard.
    """
    cur, n_shards = shard
    if not (0 <= cur < n_shards):
        raise ValueError(f"bad shard {shard}")
    if n_shards == 1:
        return rb
    local = np.arange(gidx, gidx + rb.num_rows)
    keep = np.nonzero(local % n_shards == cur)[0]
    if not len(keep):
        return None
    return rb.take(pa.array(keep))


class _StreamError:
    """Producer-thread exception in transit to the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class Dataset:
    """Iterable of {'image': uint8 [B,H,W,3], 'label': int32 [B]} batches.

    One shard of a table: rows are assigned round-robin by global row
    index so shard sizes differ by at most 1 and every epoch pass is
    deterministic given (seed, epoch).
    """

    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        img_height: int = 224,
        img_width: int = 224,
        shard: Tuple[int, int] = (0, 1),
        infinite: bool = True,
        shuffle: bool = True,
        seed: int = 0,
        num_decode_workers: int = 8,
        prefetch: int = 2,
        content_col: str = "content",
        label_col: str = "label_idx",
        drop_remainder: bool = True,
        start_epoch: int = 0,
    ):
        self.files = list(files)
        self.batch_size = batch_size
        self.img_height = img_height
        self.img_width = img_width
        self.cur_shard, self.shard_count = shard
        if not (0 <= self.cur_shard < self.shard_count):
            raise ValueError(f"bad shard {shard}")
        self.infinite = infinite
        self.shuffle = shuffle
        self.seed = seed
        self.num_decode_workers = num_decode_workers
        self.prefetch = max(1, prefetch)
        self.content_col = content_col
        self.label_col = label_col
        self.drop_remainder = drop_remainder
        # epoch the NEXT iterator starts shuffling from — per-epoch
        # orders are seeded by (seed, epoch), so a resumed run sets this
        # to its initial_epoch and sees the epochs it has NOT trained on
        # instead of replaying the stream from epoch 0
        self.start_epoch = start_epoch
        # Load shard rows once: JPEG bytes are small (compressed); for the
        # workshop-scale datasets this is the fast path. Row-group
        # streaming would slot in here for beyond-memory tables. Only this
        # shard's rows are materialized — record batches are sliced with a
        # mask before any Python-object conversion.
        self._contents: list = []
        self._labels: list = []
        gidx = 0
        for f in self.files:
            pf = pq.ParquetFile(f)
            for rb in pf.iter_batches(batch_size=1024, columns=[content_col, label_col]):
                sub = take_shard_rows(
                    rb, gidx, (self.cur_shard, self.shard_count)
                )
                if sub is not None:
                    self._contents.extend(sub.column(0).to_pylist())
                    self._labels.extend(int(x) for x in sub.column(1).to_pylist())
                gidx += rb.num_rows
        self._total_rows = gidx
        if self.infinite and len(self._contents) < (
            self.batch_size if self.drop_remainder else 1
        ):
            raise ValueError(
                f"shard {self.cur_shard}/{self.shard_count} has "
                f"{len(self._contents)} rows — fewer than batch_size="
                f"{self.batch_size}; an infinite stream would produce no "
                f"batches (deadlock). Lower batch_size/shard_count or "
                f"repartition the table (≙ reference P1/03:109-111)."
            )

    def __len__(self) -> int:
        """Number of examples in THIS shard."""
        return len(self._contents)

    @property
    def total_rows(self) -> int:
        """Rows in the whole (unsharded) table — use for step accounting:
        steps_per_epoch = total_rows // (batch × world_size) (P1/03:350-351)."""
        return self._total_rows

    def steps_per_epoch(self) -> int:
        """Global step count — identical on EVERY shard by construction
        (total // (batch × shards)), so all workers run the same number
        of collective steps per epoch (P1/03:350-351). Per-shard row
        counts may differ by 1; the infinite stream papers over that
        exactly as Petastorm's num_epochs=None does (P1/03:197-200)."""
        return max(1, self._total_rows // (self.batch_size * self.shard_count))

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self._contents)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch, self.cur_shard))
            rng.shuffle(idx)
        return idx

    def _produce(self, out_q: "queue.Queue", stop: threading.Event) -> None:
        def put(item) -> bool:
            # Blocking put that still observes consumer abandonment, so an
            # abandoned iterator never leaks this thread.
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        epoch = self.start_epoch
        bs = self.batch_size
        try:
            while not stop.is_set():
                order = self._epoch_order(epoch)
                n = len(order)
                end = (n // bs) * bs if self.drop_remainder else n
                for start in range(0, end, bs):
                    sel = order[start : start + bs]
                    jpegs = [self._contents[i] for i in sel]
                    images, _ok = decode_resize_batch(
                        jpegs,
                        self.img_height,
                        self.img_width,
                        num_threads=self.num_decode_workers,
                    )
                    labels = np.asarray(
                        [self._labels[i] for i in sel], dtype=np.int32
                    )
                    if not put({"image": images, "label": labels}):
                        return
                epoch += 1
                if not self.infinite:
                    break
        except BaseException as e:  # propagate to the consumer, don't
            put(_StreamError(e))  # let an 'infinite' stream end quietly
            return
        finally:
            put(None)  # sentinel; dropped only if the consumer is gone

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        out_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        t = threading.Thread(target=self._produce, args=(out_q, stop), daemon=True)
        t.start()
        try:
            while True:
                item = out_q.get()
                if item is None:
                    return
                if isinstance(item, _StreamError):
                    raise RuntimeError(
                        "data stream producer failed"
                    ) from item.exc
                yield item
        finally:
            stop.set()
            # drain so the producer can observe stop and exit
            try:
                while out_q.get_nowait() is not None:
                    pass
            except _QueueEmpty:
                pass


class Converter:
    """Materialized cache of (content, label) columns (≙ Petastorm
    ``SparkDatasetConverter``, P1/03:137-144)."""

    def __init__(self, cache_path: str, files: Sequence[str], num_rows: int):
        self.cache_path = cache_path
        self.files = list(files)
        self.num_rows = num_rows

    def __len__(self) -> int:
        return self.num_rows

    def make_dataset(
        self,
        batch_size: int,
        cur_shard: int = 0,
        shard_count: int = 1,
        **kwargs,
    ) -> Dataset:
        """≙ converter.make_tf_dataset(batch_size, cur_shard, shard_count)
        (P1/03:332-337)."""
        return Dataset(
            self.files,
            batch_size=batch_size,
            shard=(cur_shard, shard_count),
            **kwargs,
        )

    def delete(self) -> None:
        """≙ converter.delete() (P1/03:425-426)."""
        import shutil

        shutil.rmtree(self.cache_path, ignore_errors=True)


def make_converter(
    table: Table,
    cache_dir: str,
    columns: Sequence[str] = ("content", "label_idx"),
    min_partitions: Optional[int] = None,
) -> Converter:
    """Materialize ``columns`` of ``table`` into a Parquet cache dir.

    ``min_partitions`` ≙ df.repartition(world_size) before distributed
    feeding (P1/03:109-111): ensures at least that many part files so
    every shard has data.
    """
    data = table.read(columns=columns)
    cache_path = os.path.join(cache_dir, f"conv-{uuid.uuid4().hex[:12]}")
    os.makedirs(cache_path, exist_ok=True)
    n = data.num_rows
    parts = max(1, min_partitions or 1)
    rows_per = max(1, -(-n // parts))
    files = []
    i = 0
    for start in range(0, n, rows_per):
        p = os.path.join(cache_path, f"part-{i:05d}.parquet")
        pq.write_table(data.slice(start, rows_per), p, compression="none")
        files.append(p)
        i += 1
    return Converter(cache_path, files, n)


def make_dataset(
    table: Table,
    batch_size: int,
    shard: Tuple[int, int] = (0, 1),
    **kwargs,
) -> Dataset:
    """Directly stream a table without cache materialization."""
    return Dataset(table.files(), batch_size=batch_size, shard=shard, **kwargs)
