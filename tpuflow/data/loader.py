"""Sharded streaming loader: table → device-ready numpy batches (C5, N5/N8).

The Petastorm equivalent. The reference materializes dataframes to a
Parquet cache dir and streams them as an infinite, sharded tf.data
stream (``make_spark_converter`` / ``make_tf_dataset(batch_size,
cur_shard, shard_count)``, reference
P1/03_model_training_distributed.py:137-144,332-337). Semantics kept:

- ``num_epochs=None`` ⇒ infinite stream so every worker sees identical
  batch counts; an epoch is a fixed step count (P1/03:197-200,350-351);
- shard by (cur_shard, shard_count) with identical shard sizes;
- cache-dir materialization + ``delete()`` cleanup (P1/03:425-426);
- drop-remainder static batch shapes (XLA requires static shapes).

Two residency modes:

- **in-memory** (default): the shard's compressed JPEG bytes are
  materialized once — the fast path for workshop-scale data;
- **streaming** (``streaming=True``): Petastorm's actual reason to
  exist — "data too big for single-machine memory" (P1/03:32-34,
  197-205). Only Parquet METADATA is read at init; per epoch, row
  groups are visited in a seeded shuffled order on a reader thread and
  rows pass through a bounded shuffle buffer, so host memory is
  O(shuffle_buffer + one row group) regardless of table size. Shuffle
  is deterministic given (seed, epoch, shard) in both modes (orders
  differ between modes).

The decode hot path runs in the native C++ plane (tpuflow.native) on a
two-stage background pipeline (row assembly → decode; the native call
releases the GIL, so Parquet reads and Python batch assembly overlap
the decode) — host work overlaps device compute — and
with ``reuse_buffers=True`` writes into a small ring of reused output
buffers (no per-batch ~38MB allocation at 256x224²; safe when the
consumer copies batches to an accelerator promptly, because at most
``prefetch`` batches are in flight and each buffer's reuse period is
``prefetch + 3``). Reuse stays OFF by default: on the CPU backend JAX
can alias numpy arrays zero-copy into device buffers, where reuse
would corrupt in-flight batches — the TPU training path turns it on
(workflows auto-enables it on TPU backends).
"""

from __future__ import annotations

import os
import queue
import threading
from queue import Empty as _QueueEmpty
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from tpuflow.data.table import Table
from tpuflow.native import decode_resize_batch


def take_shard_rows(
    rb: "pa.RecordBatch | pa.Table", gidx: int, shard: Tuple[int, int]
) -> "Optional[pa.RecordBatch | pa.Table]":
    """Rows of ``rb`` whose GLOBAL row index (``gidx`` + local position)
    belongs to shard ``(cur, n)`` under round-robin (modulo) assignment.

    THE shard convention, shared by every consumer — the training
    loader and streaming batch inference — so a convention change can
    never desync them. Returns None when no rows land in the shard.
    """
    cur, n_shards = shard
    if not (0 <= cur < n_shards):
        raise ValueError(f"bad shard {shard}")
    if n_shards == 1:
        return rb
    local = np.arange(gidx, gidx + rb.num_rows)
    keep = np.nonzero(local % n_shards == cur)[0]
    if not len(keep):
        return None
    return rb.take(pa.array(keep))


class _StreamError:
    """Producer-thread exception in transit to the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class Dataset:
    """Iterable of {'image': uint8 [B,H,W,3], 'label': int32 [B]} batches.

    One shard of a table: rows are assigned round-robin by global row
    index so shard sizes differ by at most 1 and every epoch pass is
    deterministic given (seed, epoch).
    """

    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        img_height: int = 224,
        img_width: int = 224,
        shard: Tuple[int, int] = (0, 1),
        infinite: bool = True,
        shuffle: bool = True,
        seed: int = 0,
        num_decode_workers: int = 8,
        prefetch: int = 2,
        content_col: str = "content",
        label_col: str = "label_idx",
        drop_remainder: bool = True,
        start_epoch: int = 0,
        streaming: bool = False,
        shuffle_buffer: int = 2048,
        reuse_buffers: bool = False,
        cache_decoded: "bool | str" = False,
    ):
        self.files = list(files)
        self.batch_size = batch_size
        self.img_height = img_height
        self.img_width = img_width
        self.cur_shard, self.shard_count = shard
        if not (0 <= self.cur_shard < self.shard_count):
            raise ValueError(f"bad shard {shard}")
        self.infinite = infinite
        self.shuffle = shuffle
        self.seed = seed
        self.num_decode_workers = num_decode_workers
        self.prefetch = max(1, prefetch)
        self.content_col = content_col
        self.label_col = label_col
        self.drop_remainder = drop_remainder
        # epoch the NEXT iterator starts shuffling from — per-epoch
        # orders are seeded by (seed, epoch), so a resumed run sets this
        # to its initial_epoch and sees the epochs it has NOT trained on
        # instead of replaying the stream from epoch 0
        self.start_epoch = start_epoch
        self.streaming = streaming
        self.shuffle_buffer = max(1, shuffle_buffer)
        self.reuse_buffers = reuse_buffers
        if cache_decoded not in (False, True, "memmap"):
            raise ValueError(
                f"cache_decoded must be False, True, or 'memmap', got "
                f"{cache_decoded!r}"
            )
        if cache_decoded and streaming:
            raise ValueError(
                "cache_decoded needs stable shard-local row indices — "
                "incompatible with streaming=True (whose reservoir "
                "reshuffles row identity per epoch)"
            )
        # decoded-row cache: epoch 2+ skips JPEG decode entirely and
        # assembles batches by memcpy from cached uint8 rows.
        #   True      — host-RAM dict (rows x H x W x 3 bytes of RSS;
        #               tf_flowers at 224^2: ~275 MB)
        #   'memmap'  — disk-backed np.memmap beside the source files:
        #               flat RSS (pages ride the OS cache), PERSISTENT
        #               across Dataset instances and runs (decode-once
        #               per shard x geometry — epoch 1 of the NEXT run
        #               is already memcpy), one file per shard so
        #               processes never collide. A uint8 flag sidecar
        #               records absent/ok/failed per row, so corrupt
        #               rows stay remembered across runs too.
        # The right trade when epochs revisit the same rows and host
        # decode is the bottleneck (SURVEY.md §7 hard part 1).
        self.cache_decoded = cache_decoded
        self._decoded_cache: Dict[int, np.ndarray] = {}
        self._mm_rows = None  # np.memmap (N, H, W, 3) u8, lazy
        self._mm_flags = None  # np.memmap (N,) u8: 0=absent 1=ok 2=bad
        # observability for the bounded-memory guarantee (tests)
        self.peak_buffered_rows = 0
        self.decode_calls = 0  # rows actually sent to the native decoder
        # corrupt-row OCCURRENCES seen (each substituted by a valid row
        # of the same batch — see _substitute_failures). In cache_decoded
        # mode remembered bad rows re-count EVERY epoch (the counter is
        # per-substitution, not per-file) — read unique_decode_failures
        # for the number of distinct corrupt files
        self.decode_failures = 0
        self._decode_failed: set = set()

        self._contents: list = []
        self._labels: list = []
        # (file, row_group_index, global_start_row, num_rows)
        self._rg_index: List[Tuple[str, int, int, int]] = []
        gidx = 0
        if streaming:
            # metadata-only scan: row counts per row group, zero data read
            for f in self.files:
                md = pq.ParquetFile(f).metadata
                for rg in range(md.num_row_groups):
                    n = md.row_group(rg).num_rows
                    self._rg_index.append((f, rg, gidx, n))
                    gidx += n
        else:
            # Load shard rows once: JPEG bytes are small (compressed);
            # for workshop-scale datasets this is the fast path. Only
            # this shard's rows are materialized — record batches are
            # sliced with a mask before any Python-object conversion.
            for f in self.files:
                pf = pq.ParquetFile(f)
                for rb in pf.iter_batches(
                    batch_size=1024, columns=[content_col, label_col]
                ):
                    sub = take_shard_rows(
                        rb, gidx, (self.cur_shard, self.shard_count)
                    )
                    if sub is not None:
                        self._contents.extend(sub.column(0).to_pylist())
                        self._labels.extend(
                            int(x) for x in sub.column(1).to_pylist()
                        )
                    gidx += rb.num_rows
        self._total_rows = gidx
        if self.infinite and len(self) < (
            self.batch_size if self.drop_remainder else 1
        ):
            raise ValueError(
                f"shard {self.cur_shard}/{self.shard_count} has "
                f"{len(self)} rows — fewer than batch_size="
                f"{self.batch_size}; an infinite stream would produce no "
                f"batches (deadlock). Lower batch_size/shard_count or "
                f"repartition the table (≙ reference P1/03:109-111)."
            )

    def __len__(self) -> int:
        """Number of examples in THIS shard."""
        if not self.streaming:
            return len(self._contents)
        # arithmetic count of g in [0, total) with g % n == cur
        total, cur, n = self._total_rows, self.cur_shard, self.shard_count
        return (total - cur + n - 1) // n if total > cur else 0

    @property
    def total_rows(self) -> int:
        """Rows in the whole (unsharded) table — use for step accounting:
        steps_per_epoch = total_rows // (batch × world_size) (P1/03:350-351)."""
        return self._total_rows

    def steps_per_epoch(self) -> int:
        """Global step count — identical on EVERY shard by construction
        (total // (batch × shards)), so all workers run the same number
        of collective steps per epoch (P1/03:350-351). Per-shard row
        counts may differ by 1; the infinite stream papers over that
        exactly as Petastorm's num_epochs=None does (P1/03:197-200)."""
        return max(1, self._total_rows // (self.batch_size * self.shard_count))

    # ---- row iteration (per residency mode) ------------------------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self._contents)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch, self.cur_shard))
            rng.shuffle(idx)
        return idx

    def _iter_rows_mem(self, epoch: int, stop: threading.Event):
        """Yields (row_index, content, label) — the index keys the
        decoded-row cache."""
        order = self._epoch_order(epoch)
        for i in order:
            if stop.is_set():
                return
            yield int(i), self._contents[i], self._labels[i]

    def _iter_rows_stream(self, epoch: int, stop: threading.Event):
        """Row-group-shuffled, shuffle-buffered row stream.

        A reader thread pulls row groups (in a (seed, epoch)-seeded
        order) and shard-filters them; this thread drains them through
        a bounded reservoir popped at seeded-random positions — the
        Petastorm recipe: approximate global shuffle, exact per-epoch
        determinism, memory O(shuffle_buffer + row group).
        """
        rng = np.random.default_rng(
            (self.seed, epoch, self.cur_shard, 0xB0F)
        )
        rg_order = np.arange(len(self._rg_index))
        if self.shuffle:
            rng.shuffle(rg_order)

        rg_q: "queue.Queue" = queue.Queue(maxsize=2)
        done = threading.Event()  # consumer finished/abandoned this epoch

        def halted() -> bool:
            return stop.is_set() or done.is_set()

        def rput(item) -> bool:
            while not halted():
                try:
                    rg_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def read_rgs():
            pf_cache: Dict[str, pq.ParquetFile] = {}
            try:
                for rgi in rg_order:
                    if halted():
                        return
                    f, rg, g0, _n = self._rg_index[rgi]
                    pf = pf_cache.get(f)
                    if pf is None:
                        pf = pf_cache[f] = pq.ParquetFile(f)
                    tbl = pf.read_row_group(
                        rg, columns=[self.content_col, self.label_col]
                    )
                    sub = take_shard_rows(
                        tbl, g0, (self.cur_shard, self.shard_count)
                    )
                    rows = []
                    if sub is not None:
                        rows = list(
                            zip(
                                sub.column(0).to_pylist(),
                                (int(x) for x in sub.column(1).to_pylist()),
                            )
                        )
                    if not rput(rows):
                        return
            except BaseException as e:
                rput(_StreamError(e))
                return
            finally:
                rput(None)  # sentinel (skipped only when halted)

        reader = threading.Thread(target=read_rgs, daemon=True)
        reader.start()
        buf: list = []
        try:
            draining = False
            while True:
                if not draining:
                    if stop.is_set():
                        return
                    try:
                        item = rg_q.get(timeout=0.1)
                    except _QueueEmpty:
                        continue
                    if item is None:
                        draining = True
                        continue
                    if isinstance(item, _StreamError):
                        raise item.exc
                    if not self.shuffle:
                        # no reservoir needed: rows pass through in
                        # exact table order (rg_order is unshuffled too)
                        for row in item:
                            yield row
                        continue
                    buf.extend(item)
                    if len(buf) > self.peak_buffered_rows:
                        self.peak_buffered_rows = len(buf)
                    while len(buf) >= self.shuffle_buffer:
                        j = int(rng.integers(len(buf)))
                        buf[j], buf[-1] = buf[-1], buf[j]
                        yield buf.pop()
                else:
                    if not buf:
                        return
                    if stop.is_set():
                        return
                    j = int(rng.integers(len(buf)))
                    buf[j], buf[-1] = buf[-1], buf[j]
                    yield buf.pop()
        finally:
            # retire the reader: it observes ``done`` inside rput/halted
            # within 0.1s whether it is blocked on a full queue or mid-read
            done.set()

    # ---- batch production ------------------------------------------------

    def _decode_out(self, pool: List[Optional[np.ndarray]], slot: int):
        if not self.reuse_buffers:
            return None
        if pool[slot] is None:
            pool[slot] = np.empty(
                (self.batch_size, self.img_height, self.img_width, 3),
                np.uint8,
            )
        return pool[slot]

    def _ensure_memmap(self):
        """Lazily open (or create) the shard's decoded-row memmap pair.

        The filename carries shard + geometry + a DIGEST of the file
        list (basenames, sizes, row count): two Datasets over different
        file subsets/orders rooted in the same directory must never
        alias one cache — np.memmap(mode='r+') silently extends or
        prefix-maps on size mismatch, so a name collision would serve
        wrong pixels with no error. First-touch creation runs under an
        O_CREAT|O_EXCL lock file: without it, two same-shard processes
        racing the exists-check could each rename fresh zeroed files
        and one would then write rows into an unlinked inode while its
        flags landed in the survivor (flag=ok over never-written rows).
        """
        if self._mm_rows is not None:
            return
        import hashlib
        import tempfile
        import time as _time

        n = len(self._contents)
        h, w = self.img_height, self.img_width
        dig = hashlib.blake2b(digest_size=6)
        for f in self.files:
            dig.update(os.path.basename(f).encode())
            dig.update(str(os.path.getsize(f)).encode())
        dig.update(str(n).encode())
        base = os.path.join(
            os.path.dirname(os.path.abspath(self.files[0])),
            f"decoded_{self.cur_shard}of{self.shard_count}_{h}x{w}_"
            f"{dig.hexdigest()}",
        )
        rows_path, flags_path = base + ".u8", base + ".flags"
        deadline = _time.time() + 60.0
        while not (os.path.exists(rows_path)
                   and os.path.exists(flags_path)):
            try:
                lock_fd = os.open(base + ".lock",
                                  os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if _time.time() > deadline:
                    raise TimeoutError(
                        f"memmap cache lock {base}.lock held for >60s — "
                        "stale lock from a crashed first-touch? remove "
                        "it to rebuild the cache"
                    )
                _time.sleep(0.05)
                continue
            try:
                d = os.path.dirname(rows_path)
                if not os.path.exists(rows_path):
                    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
                    with os.fdopen(fd, "wb") as f:
                        f.truncate(n * h * w * 3)
                    os.replace(tmp, rows_path)
                if not os.path.exists(flags_path):
                    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
                    with os.fdopen(fd, "wb") as f:
                        f.write(b"\x00" * n)
                    os.replace(tmp, flags_path)
            finally:
                os.close(lock_fd)
                os.unlink(base + ".lock")
        self._mm_rows = np.memmap(rows_path, dtype=np.uint8, mode="r+",
                                  shape=(n, h, w, 3))
        self._mm_flags = np.memmap(flags_path, dtype=np.uint8, mode="r+",
                                   shape=(n,))

    def _decode_memmap(self, idxs, jpegs, out):
        """Memmap twin of :meth:`_decode_cached`: rows live in the
        disk-backed cache (decode-once per shard x geometry x file
        digest, across Dataset instances AND runs), flags record
        ok/failed per row. Deliberately vectorized rather than sharing
        the dict path's per-row loop — one fancy-index gather per
        batch is the memcpy-speed win the mode exists for. The
        producer thread is the only writer in this process; the
        digest-keyed per-shard filename keeps other datasets and
        processes off this file."""
        self._ensure_memmap()
        ia = np.asarray(idxs, np.int64)
        fl = self._mm_flags[ia]
        missing = np.flatnonzero(fl == 0)
        if len(missing):
            self.decode_calls += int(len(missing))
            fresh, fok = decode_resize_batch(
                [jpegs[int(j)] for j in missing],
                self.img_height,
                self.img_width,
                num_threads=self.num_decode_workers,
            )
            self._mm_rows[ia[missing]] = fresh
            self._mm_flags[ia[missing]] = np.where(
                np.asarray(fok, bool), 1, 2
            ).astype(np.uint8)
            fl = self._mm_flags[ia]
        images = (
            out
            if out is not None
            else np.empty(
                (len(idxs), self.img_height, self.img_width, 3), np.uint8
            )
        )
        images[: len(idxs)] = self._mm_rows[ia]
        ok = (fl != 2).astype(np.uint8)
        self._decode_failed.update(int(i) for i in ia[fl == 2])
        return images, ok

    def _decode_cached(self, idxs, jpegs, out):
        """Assemble a batch from the decoded-row cache, decoding only
        rows not yet cached (epoch 1 fills it; epoch 2+ is pure memcpy).
        Cached rows come from fresh decode outputs (never the reuse
        ring), so they stay valid for the Dataset's lifetime. Returns
        (images, ok) — failed rows stay remembered so every epoch's
        batch substitution sees them, not just the one that decoded."""
        if self.cache_decoded == "memmap":
            return self._decode_memmap(idxs, jpegs, out)
        missing = [
            j for j, i in enumerate(idxs) if i not in self._decoded_cache
        ]
        if missing:
            self.decode_calls += len(missing)
            fresh, fok = decode_resize_batch(
                [jpegs[j] for j in missing],
                self.img_height,
                self.img_width,
                num_threads=self.num_decode_workers,
            )
            for k, j in enumerate(missing):
                self._decoded_cache[idxs[j]] = fresh[k]
                if not fok[k]:
                    self._decode_failed.add(idxs[j])
        images = (
            out
            if out is not None
            else np.empty(
                (len(idxs), self.img_height, self.img_width, 3), np.uint8
            )
        )
        ok = np.ones((len(idxs),), np.uint8)
        for j, i in enumerate(idxs):
            images[j] = self._decoded_cache[i]
            if i in self._decode_failed:
                ok[j] = 0
        return images, ok

    @property
    def unique_decode_failures(self) -> Optional[int]:
        """Number of DISTINCT corrupt source rows seen — the headline
        corruption metric (``decode_failures`` counts substitution
        occurrences, which re-count remembered rows every epoch in
        cache_decoded mode). ``None`` when ``cache_decoded=False``:
        streaming decode has no row-identity memory, so uniqueness is
        unknowable there."""
        return len(self._decode_failed) if self.cache_decoded else None

    def _substitute_failures(self, images, labels, ok) -> None:
        """Replace corrupt rows (ok=0) with a valid row of the SAME
        batch — image and label together. A zero image under a real
        label is silent label noise (the wild-corpus case the C++
        error path exists for: truncated/CMYK/garbage files); a
        bootstrap-resample of the batch is distribution-neutral and
        keeps shapes static for jit. An all-corrupt batch stays zeroed
        (nothing to substitute) — the counter still records it."""
        bad = np.flatnonzero(ok == 0)
        if not len(bad):
            return
        self.decode_failures += int(len(bad))
        good = np.flatnonzero(ok != 0)
        if not len(good):
            return
        for j, g in zip(bad, np.resize(good, len(bad))):
            images[j] = images[g]
            labels[j] = labels[g]

    @staticmethod
    def _stage_put(q: "queue.Queue", item, stop: threading.Event) -> bool:
        """Blocking put that still observes consumer abandonment, so an
        abandoned iterator never leaks pipeline threads."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _assemble(self, raw_q: "queue.Queue", stop: threading.Event) -> None:
        """Stage 1: row iteration → raw (jpegs, labels) batches.

        Runs concurrently with stage 2 so Parquet reads + Python batch
        assembly overlap the native decode (which releases the GIL) —
        the tf.data-style pipelined host path (N5). Single FIFO per
        stage keeps batch order deterministic.
        """
        epoch = self.start_epoch
        bs = self.batch_size
        try:
            while not stop.is_set():
                if self.streaming:
                    rows = (
                        (None, c, l)
                        for c, l in self._iter_rows_stream(epoch, stop)
                    )
                else:
                    rows = self._iter_rows_mem(epoch, stop)
                idxs: list = []
                jpegs: list = []
                labels: list = []
                emitted = 0
                # cap batches when drop_remainder so every epoch emits
                # exactly len(self)//bs batches in BOTH residency modes
                max_batches = len(self) // bs if self.drop_remainder else None
                for idx, content, label in rows:
                    idxs.append(idx)
                    jpegs.append(content)
                    labels.append(label)
                    if len(jpegs) == bs:
                        if not self._stage_put(
                            raw_q, (idxs, jpegs, labels), stop
                        ):
                            return
                        idxs, jpegs, labels = [], [], []
                        emitted += 1
                        if max_batches is not None and emitted >= max_batches:
                            break
                if jpegs and not self.drop_remainder and not stop.is_set():
                    if not self._stage_put(raw_q, (idxs, jpegs, labels), stop):
                        return
                epoch += 1
                if not self.infinite:
                    break
        except BaseException as e:  # propagate to the consumer, don't
            self._stage_put(raw_q, _StreamError(e), stop)  # end quietly
            return
        finally:
            self._stage_put(raw_q, None, stop)  # sentinel

    def _decode_stage(
        self, raw_q: "queue.Queue", out_q: "queue.Queue", stop: threading.Event
    ) -> None:
        """Stage 2: native decode+resize of raw batches, in FIFO order."""
        # ring of reused decode buffers: at most ``prefetch`` batches sit
        # in the queue + 1 at the consumer, so a period of prefetch + 3
        # never overwrites a batch still in flight (the extra slot is
        # headroom for an async H2D transfer still reading the oldest).
        # The trainers' staging depth follows THIS ``prefetch`` knob
        # (Trainer._staging_depth, capped for HBM), and superstep block
        # staging copies each pulled batch into its stacked block
        # IMMEDIATELY (_stage_superstep) — never more than one
        # un-copied batch at the consumer, exactly what this pool
        # sizing assumes.
        pool: List[Optional[np.ndarray]] = [None] * (self.prefetch + 3)
        slot = 0
        try:
            while True:
                if stop.is_set():
                    return
                try:
                    item = raw_q.get(timeout=0.1)
                except _QueueEmpty:
                    continue
                if item is None or isinstance(item, _StreamError):
                    self._stage_put(out_q, item, stop)
                    return
                idxs, jpegs, labels = item
                out = None
                if len(jpegs) == self.batch_size:
                    out = self._decode_out(pool, slot)
                    slot = (slot + 1) % len(pool)
                if self.cache_decoded and idxs and idxs[0] is not None:
                    images, ok = self._decode_cached(idxs, jpegs, out)
                else:
                    self.decode_calls += len(jpegs)
                    images, ok = decode_resize_batch(
                        jpegs,
                        self.img_height,
                        self.img_width,
                        num_threads=self.num_decode_workers,
                        out=out,
                    )
                labels = np.asarray(labels, np.int32)
                self._substitute_failures(images, labels, ok)
                if not self._stage_put(
                    out_q,
                    {"image": images, "label": labels},
                    stop,
                ):
                    return
        except BaseException as e:
            self._stage_put(out_q, _StreamError(e), stop)
            return

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        raw_q: "queue.Queue" = queue.Queue(maxsize=2)
        out_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        t1 = threading.Thread(
            target=self._assemble, args=(raw_q, stop), daemon=True
        )
        t2 = threading.Thread(
            target=self._decode_stage, args=(raw_q, out_q, stop), daemon=True
        )
        t1.start()
        t2.start()
        try:
            while True:
                item = out_q.get()
                if item is None:
                    return
                if isinstance(item, _StreamError):
                    raise RuntimeError(
                        "data stream producer failed"
                    ) from item.exc
                yield item
        finally:
            stop.set()
            # drain so the pipeline threads can observe stop and exit
            for q in (out_q, raw_q):
                try:
                    while q.get_nowait() is not None:
                        pass
                except _QueueEmpty:
                    pass


class Converter:
    """Materialized cache of (content, label) columns (≙ Petastorm
    ``SparkDatasetConverter``, P1/03:137-144)."""

    def __init__(self, cache_path: str, files: Sequence[str], num_rows: int):
        self.cache_path = cache_path
        self.files = list(files)
        self.num_rows = num_rows

    def __len__(self) -> int:
        return self.num_rows

    def make_dataset(
        self,
        batch_size: int,
        cur_shard: int = 0,
        shard_count: int = 1,
        **kwargs,
    ) -> Dataset:
        """≙ converter.make_tf_dataset(batch_size, cur_shard, shard_count)
        (P1/03:332-337)."""
        return Dataset(
            self.files,
            batch_size=batch_size,
            shard=(cur_shard, shard_count),
            **kwargs,
        )

    def delete(self) -> None:
        """≙ converter.delete() (P1/03:425-426)."""
        import shutil

        shutil.rmtree(self.cache_path, ignore_errors=True)


def make_converter(
    table: Table,
    cache_dir: str,
    columns: Sequence[str] = ("content", "label_idx"),
    min_partitions: Optional[int] = None,
) -> Converter:
    """Materialize ``columns`` of ``table`` into a Parquet cache dir.

    ``min_partitions`` ≙ df.repartition(world_size) before distributed
    feeding (P1/03:109-111): ensures at least that many part files so
    every shard has data.
    """
    data = table.read(columns=columns)
    cache_path = os.path.join(cache_dir, f"conv-{uuid.uuid4().hex[:12]}")
    os.makedirs(cache_path, exist_ok=True)
    n = data.num_rows
    parts = max(1, min_partitions or 1)
    rows_per = max(1, -(-n // parts))
    files = []
    i = 0
    for start in range(0, n, rows_per):
        p = os.path.join(cache_path, f"part-{i:05d}.parquet")
        pq.write_table(data.slice(start, rows_per), p, compression="none")
        files.append(p)
        i += 1
    return Converter(cache_path, files, n)


def make_dataset(
    table: Table,
    batch_size: int,
    shard: Tuple[int, int] = (0, 1),
    **kwargs,
) -> Dataset:
    """Directly stream a table without cache materialization."""
    return Dataset(table.files(), batch_size=batch_size, shard=shard, **kwargs)
