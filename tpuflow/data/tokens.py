"""Beyond-memory token streaming for LM training (peer of C5/N8).

The image pipeline earned a streaming loader (``Dataset(streaming=True)``,
tpuflow.data.loader — the Petastorm rationale of P1/03:32-34: train on
data that does not fit in RAM); this module applies the same discipline
to the LM family tpuflow makes first-class. A tokenized corpus lives on
disk as fixed-shape binary shards and is streamed through a bounded
reservoir, so host RSS is O(shuffle_rows + chunk) regardless of corpus
size.

Storage format (written by :func:`write_token_shards`): a directory of
``tokens-%05d.bin`` files (raw little-endian int32, row-major
``(rows, seq_len)``) plus ``manifest.json`` recording ``seq_len``,
per-shard row counts and the dtype. Raw binary + explicit seek/read
into a REUSED scratch buffer — not ``np.load(mmap_mode=...)`` — because
mmap'd pages touched during an epoch stay resident until memory
pressure, which defeats a flat-RSS guarantee the tests can assert.

Semantics shared with the image loader (tpuflow.data.loader):

- **shard convention**: global row index ``g`` belongs to shard
  ``g % shard_count`` (``take_shard_rows``'s round-robin rule).
- **deterministic shuffle**: shard-file order and the reservoir are
  seeded by ``(seed, epoch, cur_shard)``, so resume at ``start_epoch``
  replays the exact batch order (≙ loader._epoch_order).
- **lockstep steps**: ``steps_per_epoch = total_rows // (batch_rows ×
  shard_count)`` — identical on every process, so collective steps
  never desync (P1/03:350-351).

The shuffle is a single-pass bounded reservoir (fill ``shuffle_rows``
rows, then yield a random occupant and replace it with the next
incoming row — tf.data's shuffle-buffer algorithm): uniform enough for
training, O(shuffle_rows) memory, deterministic under the seeded rng.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

_MANIFEST = "manifest.json"
_DTYPE = "int32"


def write_token_shards(
    tokens: Union[np.ndarray, Sequence[np.ndarray]],
    out_dir: str,
    rows_per_shard: int = 8192,
) -> str:
    """Write ``(N, seq_len)`` int32 token rows (one array or a sequence
    of row-block arrays, e.g. a generator over tokenizer output) as a
    sharded binary corpus. Returns ``out_dir``. Appends are not
    supported — a corpus version is immutable once written (same
    discipline as tpuflow.data.table versions)."""
    os.makedirs(out_dir, exist_ok=True)
    if os.path.exists(os.path.join(out_dir, _MANIFEST)):
        raise FileExistsError(
            f"{out_dir} already holds a token corpus (immutable once "
            "written); write a new directory instead"
        )
    # stream the blocks — materializing a generator would defeat the
    # beyond-host-RAM purpose (a corpus larger than RAM must flush
    # shard by shard, holding at most rows_per_shard rows)
    blocks = iter([tokens]) if isinstance(tokens, np.ndarray) else iter(tokens)
    try:
        first = np.asarray(next(blocks))
    except StopIteration:
        raise ValueError("no token rows to write") from None
    seq_len = int(first.shape[1])
    shard_rows: List[int] = []
    cur: List[np.ndarray] = []
    cur_n = 0

    def _flush():
        nonlocal cur, cur_n
        if not cur_n:
            return
        arr = np.ascontiguousarray(
            np.concatenate(cur, axis=0), dtype=np.dtype(_DTYPE).newbyteorder("<")
        )
        path = os.path.join(out_dir, f"tokens-{len(shard_rows):05d}.bin")
        with open(path, "wb") as f:
            f.write(arr.tobytes())
        shard_rows.append(int(arr.shape[0]))
        cur, cur_n = [], 0

    import itertools

    for blk in itertools.chain([first], blocks):
        blk = np.asarray(blk)
        if blk.ndim != 2 or blk.shape[1] != seq_len:
            raise ValueError(
                f"all blocks must be (rows, {seq_len}); got {blk.shape}"
            )
        start = 0
        while start < blk.shape[0]:
            take = min(rows_per_shard - cur_n, blk.shape[0] - start)
            cur.append(blk[start : start + take])
            cur_n += take
            start += take
            if cur_n == rows_per_shard:
                _flush()
    _flush()
    manifest = {
        "seq_len": seq_len,
        "dtype": _DTYPE,
        "shard_rows": shard_rows,
        "total_rows": int(sum(shard_rows)),
    }
    tmp = os.path.join(out_dir, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(out_dir, _MANIFEST))  # atomic publish
    return out_dir


class TokenDataset:
    """Memory-bounded, shard-aware stream of ``(batch_rows, seq_len)``
    int32 batches over a :func:`write_token_shards` corpus.

    ``shard=None`` auto-wires to ``(jax.process_index(),
    jax.process_count())`` — the trainer-facing default; pass an
    explicit ``(cur, count)`` for tests or custom topologies.
    ``batch_rows`` is the rows yielded PER PROCESS per step (the
    trainer's ``batch_size // process_count``).
    """

    def __init__(
        self,
        corpus_dir: str,
        batch_rows: int,
        *,
        shard: Optional[Tuple[int, int]] = None,
        seed: int = 0,
        shuffle: bool = True,
        shuffle_rows: int = 4096,
        read_chunk_rows: int = 1024,
    ):
        with open(os.path.join(corpus_dir, _MANIFEST)) as f:
            m = json.load(f)
        self.dir = corpus_dir
        self.seq_len = int(m["seq_len"])
        self.shard_rows: List[int] = [int(r) for r in m["shard_rows"]]
        self.total_rows = int(m["total_rows"])
        if shard is None:
            import jax

            shard = (jax.process_index(), jax.process_count())
        self.cur_shard, self.shard_count = shard
        if not (0 <= self.cur_shard < self.shard_count):
            raise ValueError(f"bad shard {shard}")
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive, got {batch_rows}")
        self.batch_rows = int(batch_rows)
        self.seed = seed
        self.shuffle = shuffle
        self.shuffle_rows = max(int(shuffle_rows), self.batch_rows)
        self.read_chunk_rows = int(read_chunk_rows)
        if self.steps_per_epoch() < 1:
            raise ValueError(
                f"corpus has {self.total_rows} rows < one global batch "
                f"({self.batch_rows} x {self.shard_count} processes)"
            )

    # ---- accounting ------------------------------------------------------

    def __len__(self) -> int:
        """Rows in THIS shard (arithmetic count of g % n == cur)."""
        total, cur, n = self.total_rows, self.cur_shard, self.shard_count
        return (total - cur + n - 1) // n if total > cur else 0

    def steps_per_epoch(self) -> int:
        """Identical on every shard — lockstep collective step count."""
        return self.total_rows // (self.batch_rows * self.shard_count)

    # ---- streaming -------------------------------------------------------

    def _iter_shard_rows(
        self, shard_idx: int, scratch: np.ndarray
    ) -> Iterator[np.ndarray]:
        """This process's rows of one shard file, streamed in
        ``read_chunk_rows`` chunks through ``scratch`` (one reused
        buffer — the no-allocation-per-chunk discipline of the image
        loader's reuse ring). Yields row VIEWS into scratch: consumers
        copy (the reservoir does)."""
        rows = self.shard_rows[shard_idx]
        g0 = sum(self.shard_rows[:shard_idx])  # global index of row 0
        row_bytes = self.seq_len * 4
        path = os.path.join(self.dir, f"tokens-{shard_idx:05d}.bin")
        with open(path, "rb", buffering=0) as f:
            for start in range(0, rows, self.read_chunk_rows):
                n = min(self.read_chunk_rows, rows - start)
                buf = scratch[:n]
                f.seek(start * row_bytes)
                got = f.readinto(memoryview(buf).cast("B"))
                if got != n * row_bytes:
                    raise IOError(
                        f"{path}: short read at row {start} "
                        f"({got} != {n * row_bytes} bytes)"
                    )
                g = g0 + start + np.arange(n)
                keep = np.nonzero(g % self.shard_count == self.cur_shard)[0]
                for i in keep:
                    yield buf[i]

    def iter_epoch(self, epoch: int) -> Iterator[np.ndarray]:
        """Yield ``steps_per_epoch`` batches of ``(batch_rows, seq_len)``
        for one epoch — deterministic in ``(seed, epoch, cur_shard)``."""
        rng = np.random.default_rng((self.seed, epoch, self.cur_shard))
        order = np.arange(len(self.shard_rows))
        if self.shuffle:
            rng.shuffle(order)
        scratch = np.empty(
            (self.read_chunk_rows, self.seq_len),
            np.dtype(_DTYPE).newbyteorder("<"),
        )
        reservoir = np.empty((self.shuffle_rows, self.seq_len), np.int32)
        filled = 0
        batch = np.empty((self.batch_rows, self.seq_len), np.int32)
        in_batch = 0
        emitted = 0
        budget = self.steps_per_epoch()

        def _emit_ready() -> bool:
            return in_batch == self.batch_rows

        def _rows():
            for si in order:
                yield from self._iter_shard_rows(int(si), scratch)

        for row in _rows():
            if emitted == budget:
                break
            if self.shuffle and filled < self.shuffle_rows:
                reservoir[filled] = row
                filled += 1
                continue
            if self.shuffle:
                j = int(rng.integers(filled))
                batch[in_batch] = reservoir[j]
                reservoir[j] = row
            else:
                batch[in_batch] = row
            in_batch += 1
            if _emit_ready():
                yield batch.copy()
                emitted += 1
                in_batch = 0
        # drain the reservoir (shuffled) for the remaining budget
        if self.shuffle and emitted < budget and filled:
            drain = rng.permutation(filled)
            for j in drain:
                batch[in_batch] = reservoir[j]
                in_batch += 1
                if _emit_ready():
                    yield batch.copy()
                    emitted += 1
                    in_batch = 0
                    if emitted == budget:
                        break
        if emitted < budget:
            raise RuntimeError(
                f"shard {self.cur_shard}/{self.shard_count}: produced "
                f"{emitted}/{budget} batches — corpus shrank under us?"
            )
