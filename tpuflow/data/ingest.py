"""Binary image ingest → bronze table (C2).

≙ ``spark.read.format('binaryFile').option(pathGlobFilter='*.jpg',
recursiveFileLookup=True).load(path).sample(fraction)`` followed by an
uncompressed Delta write (reference P1/01_data_prep.py:61-95). Produces
the same logical schema: path / modificationTime / length / content.
"""

from __future__ import annotations

import fnmatch
import os
import random
from typing import List, Optional

import pyarrow as pa

from tpuflow.data.table import Table


def _glob_files(root: str, pattern: str, recursive: bool) -> List[str]:
    out = []
    if recursive:
        for dirpath, _dirnames, filenames in os.walk(root):
            for fn in filenames:
                if fnmatch.fnmatch(fn, pattern):
                    out.append(os.path.join(dirpath, fn))
    else:
        for fn in os.listdir(root):
            p = os.path.join(root, fn)
            if os.path.isfile(p) and fnmatch.fnmatch(fn, pattern):
                out.append(p)
    return sorted(out)  # deterministic order


def ingest_images(
    source_dir: str,
    table: Table,
    glob: str = "*.jpg",
    recursive: bool = True,
    sample_fraction: float = 1.0,
    seed: int = 12,
    compression: Optional[str] = None,
) -> int:
    """Read image files into ``table`` (bronze). Returns row count.

    ``sample_fraction`` mirrors ``.sample(fraction=0.5)`` used to speed the
    workshop up (P1/01:65). Compression defaults to None — uncompressed,
    the reference's choice for binary columns (P1/01:91-92).
    """
    files = _glob_files(source_dir, glob, recursive)
    if sample_fraction < 1.0:
        rng = random.Random(seed)
        files = [f for f in files if rng.random() < sample_fraction]
    paths, mtimes, lengths, contents = [], [], [], []
    for f in files:
        st = os.stat(f)
        with open(f, "rb") as fh:
            data = fh.read()
        paths.append(os.path.abspath(f))
        mtimes.append(st.st_mtime)
        lengths.append(len(data))
        contents.append(data)
    tbl = pa.table(
        {
            "path": pa.array(paths, pa.string()),
            "modificationTime": pa.array(mtimes, pa.float64()),
            "length": pa.array(lengths, pa.int64()),
            "content": pa.array(contents, pa.binary()),
        }
    )
    table.write(tbl, compression=compression)
    return tbl.num_rows
