"""Columnar transforms: label extract / split / label index (C3-C4).

≙ the pandas-UDF label parsing (reference P1/01_data_prep.py:124-136),
``randomSplit([0.9, 0.1], seed=42)`` (:162) and the sorted-distinct
label→index map applied as a second UDF (:178-197). Implemented as
vectorized Arrow/NumPy column ops — no per-row Python in the hot path.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


def add_label_from_path(t: pa.Table, path_col: str = "path") -> pa.Table:
    """Label = name of the file's parent directory (≙ get_label_udf,
    P1/01:125-130: ``path.split('/')[-2]``)."""
    paths = t.column(path_col).to_pylist()
    labels = [os.path.basename(os.path.dirname(p)) for p in paths]
    return t.append_column("label", pa.array(labels, pa.string()))


def build_label_index(t: pa.Table, label_col: str = "label") -> Dict[str, int]:
    """Sorted distinct labels → contiguous indices (≙ P1/01:179-182)."""
    uniq = sorted(set(pc.unique(t.column(label_col)).to_pylist()))
    return {lbl: i for i, lbl in enumerate(uniq)}


def index_labels(
    t: pa.Table, label_to_idx: Dict[str, int], label_col: str = "label"
) -> pa.Table:
    """Append integer ``label_idx`` column (≙ get_label_idx_udf, P1/01:187-197)."""
    idx = [label_to_idx[l] for l in t.column(label_col).to_pylist()]
    return t.append_column("label_idx", pa.array(idx, pa.int64()))


def random_split(
    t: pa.Table, fractions: Tuple[float, float] = (0.9, 0.1), seed: int = 42
) -> Tuple[pa.Table, pa.Table]:
    """Seeded row split (≙ randomSplit([0.9, 0.1], seed=42), P1/01:162)."""
    n = t.num_rows
    rng = np.random.default_rng(seed)
    u = rng.random(n)
    cut = fractions[0] / (fractions[0] + fractions[1])
    left_mask = u < cut
    left = t.filter(pa.array(left_mask))
    right = t.filter(pa.array(~left_mask))
    return left, right
