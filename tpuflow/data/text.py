"""Byte-level BPE tokenization — the text half of the LM data plane.

Closes the loop the LM family previously left to the user ("corpus
tokenization is upstream of this framework"): raw text → ByteBPE →
fixed-length token rows → :func:`tpuflow.data.tokens.write_token_shards`
→ TokenDataset → LMTrainer. The reference has no text pipeline at all
(its data plane is JPEG images, SURVEY.md §2); this is part of the
beyond-reference LM surface.

The heavy paths (training's pair counting, encoding's agenda merge) run
in C++ (tpuflow/native/bpe.cpp, ctypes-bound, built on first use) with
a pure-Python fallback implementing the SAME algorithm — parity between
the two is pinned by tests/test_text.py, and the fallback keeps every
code path runnable without a toolchain.

Recipe (GPT-2-family, simplified to pure bytes): base vocabulary = the
256 bytes, merge i creates token ``256 + i``; the byte stream
pretokenizes into pieces starting at each space/newline (the separator
prefixes the next piece) and merges never cross piece boundaries;
training counts pairs over the UNIQUE-piece frequency table; ties break
to the lowest pair for determinism.
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np


def _pieces(data: bytes) -> Iterable[bytes]:
    """Split at each space/newline, separator attached to what follows
    — MUST match for_each_piece in native/bpe.cpp."""
    start = 0
    for i in range(1, len(data)):
        if data[i : i + 1] in (b" ", b"\n"):
            yield data[start:i]
            start = i
    if len(data) > start:
        yield data[start:]


def _train_py(data: bytes, n_merges: int) -> List[Tuple[int, int]]:
    from collections import Counter

    freq = Counter(_pieces(data))
    seqs = [list(p) for p in freq]
    counts = list(freq.values())
    merges: List[Tuple[int, int]] = []
    for mi in range(n_merges):
        pc: "Counter[Tuple[int, int]]" = Counter()
        for s, c in zip(seqs, counts):
            for j in range(len(s) - 1):
                pc[(s[j], s[j + 1])] += c
        if not pc:
            break
        # most frequent; deterministic lowest-pair tie break
        best, best_n = min(
            pc.items(), key=lambda kv: (-kv[1], kv[0])
        )
        if best_n < 2:
            break
        merges.append(best)
        nt = 256 + mi
        a, b = best
        for s in seqs:
            j, w = 0, []
            while j < len(s):
                if j + 1 < len(s) and s[j] == a and s[j + 1] == b:
                    w.append(nt)
                    j += 2
                else:
                    w.append(s[j])
                    j += 1
            s[:] = w
    return merges


def _encode_py(data: bytes, merges: Sequence[Tuple[int, int]]) -> List[int]:
    rank = {tuple(m): i for i, m in enumerate(merges)}
    memo: dict = {}
    out: List[int] = []
    for piece in _pieces(data):
        toks = memo.get(piece)
        if toks is None:
            seq = list(piece)
            while len(seq) >= 2:
                best = None
                for j in range(len(seq) - 1):
                    r = rank.get((seq[j], seq[j + 1]))
                    if r is not None and (best is None or r < best):
                        best = r
                if best is None:
                    break
                a, b = merges[best]
                nt = 256 + best
                j, w = 0, []
                while j < len(seq):
                    if j + 1 < len(seq) and seq[j] == a and seq[j + 1] == b:
                        w.append(nt)
                        j += 2
                    else:
                        w.append(seq[j])
                        j += 1
                seq = w
            toks = memo[piece] = seq
        out.extend(toks)
    return out


def _as_bytes(text: Union[str, bytes]) -> bytes:
    return text.encode("utf-8") if isinstance(text, str) else bytes(text)


class ByteBPE:
    """Byte-level BPE tokenizer (vocab = 256 bytes + learned merges)."""

    def __init__(self, merges: Sequence[Tuple[int, int]]):
        self.merges: List[Tuple[int, int]] = [
            (int(a), int(b)) for a, b in merges
        ]
        self.vocab_size = 256 + len(self.merges)
        # token id → byte string (merge expansion)
        tab: List[bytes] = [bytes([i]) for i in range(256)]
        for a, b in self.merges:
            tab.append(tab[a] + tab[b])
        self._table = tab
        # native encoder handle (rank map + piece memo persist ACROSS
        # encode calls — a stream of small documents amortizes both);
        # created lazily, freed with the tokenizer
        self._pairs_np = np.asarray(
            self.merges, np.uint32
        ).reshape(-1, 2) if self.merges else np.zeros((0, 2), np.uint32)
        self._enc_handle = None
        self._finalizer = None

    def _native_encoder(self, lib):
        if self._enc_handle is None:
            import weakref

            handle = lib.tf_bpe_encoder_new(
                self._pairs_np.ctypes.data_as(ctypes.c_void_p),
                len(self.merges),
            )
            self._enc_handle = handle
            self._finalizer = weakref.finalize(
                self, lib.tf_bpe_encoder_free, handle
            )
        return self._enc_handle

    # ---- training --------------------------------------------------------

    @classmethod
    def train(
        cls,
        corpus: Union[str, bytes, Iterable[Union[str, bytes]]],
        vocab_size: int = 512,
        max_bytes: int = 8 << 20,
    ) -> "ByteBPE":
        """Learn ``vocab_size - 256`` merges from the corpus (a string/
        bytes or an iterable of them, e.g. a file-reading generator).
        Training reads at most ``max_bytes`` (BPE statistics saturate
        quickly; the standard subsample-to-train practice). May learn
        fewer merges when nothing repeats (tiny corpora)."""
        if vocab_size <= 256:
            raise ValueError(f"vocab_size must exceed 256, got {vocab_size}")
        if isinstance(corpus, (str, bytes)):
            corpus = [corpus]
        buf = bytearray()
        for chunk in corpus:
            buf += _as_bytes(chunk)
            if len(buf) >= max_bytes:
                break
        data = bytes(buf[:max_bytes])
        if not data:
            raise ValueError("empty training corpus")
        n_merges = vocab_size - 256

        from tpuflow.native import bpe_lib

        lib = bpe_lib()
        if lib is None:
            return cls(_train_py(data, n_merges))
        out = np.empty((n_merges, 2), np.uint32)
        learned = lib.tf_bpe_train(
            data, len(data), n_merges,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return cls([tuple(map(int, p)) for p in out[:learned]])

    # ---- encode / decode -------------------------------------------------

    def encode(self, text: Union[str, bytes]) -> np.ndarray:
        """Token ids (int32). A token stream never exceeds the byte
        count, so the native path preallocates exactly len(data)."""
        data = _as_bytes(text)
        if not data:
            return np.zeros((0,), np.int32)
        from tpuflow.native import bpe_lib

        lib = bpe_lib()
        if lib is None:
            return np.asarray(_encode_py(data, self.merges), np.int32)
        out = np.empty((len(data),), np.uint32)
        n = lib.tf_bpe_encoder_encode(
            self._native_encoder(lib), data, len(data),
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out[:n].astype(np.int32)

    def decode(self, ids: Sequence[int]) -> bytes:
        """Exact inverse of encode (byte-level BPE is lossless)."""
        t = self._table
        return b"".join(t[int(i)] for i in np.asarray(ids).reshape(-1))

    # ---- persistence -----------------------------------------------------

    def __getstate__(self):
        # the native encoder handle/finalizer cannot cross process
        # boundaries (ProcessTrials objectives may close over a
        # tokenizer); the merges fully define the tokenizer
        return {"merges": self.merges}

    def __setstate__(self, state):
        self.__init__(state["merges"])

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "tpuflow-bytebpe-v1",
                       "merges": self.merges}, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPE":
        with open(path) as f:
            obj = json.load(f)
        if obj.get("format") != "tpuflow-bytebpe-v1":
            raise ValueError(f"{path} is not a ByteBPE file")
        return cls([tuple(m) for m in obj["merges"]])


def _encode_any(tokenizer, text: Union[str, bytes]) -> np.ndarray:
    """Normalize any tokenizer's encode output to int32 ids.

    Accepts :class:`ByteBPE` (bytes-native), a HuggingFace
    ``tokenizers.Tokenizer`` (returns an Encoding with ``.ids``), or a
    ``transformers`` tokenizer (returns a list of ints) — the three
    encode() shapes in this container."""
    if isinstance(tokenizer, ByteBPE):
        return tokenizer.encode(text)
    if isinstance(text, bytes):
        text = text.decode("utf-8", "surrogateescape")
    out = tokenizer.encode(text)
    ids = getattr(out, "ids", out)
    return np.asarray(ids, np.int32)


def tokenize_corpus(
    texts: Iterable[Union[str, bytes]],
    tokenizer,
    out_dir: str,
    seq_len: int,
    rows_per_shard: int = 8192,
    eot_id: Optional[int] = None,
) -> str:
    """Text stream → fixed-length token rows → sharded corpus on disk
    (the writer streams; nothing is held whole). Documents are
    concatenated (optionally separated by ``eot_id``) and packed into
    ``(rows, seq_len)`` int32 rows, ragged tail dropped — the standard
    next-token-training packing. Train on it with
    ``TrainConfig(packed_eos_id=eot_id)``: LMTrainer then derives
    segment masks + per-document rotary positions on device, so packed
    documents never attend across each other. ``tokenizer`` is a
    :class:`ByteBPE`
    or any HuggingFace ``tokenizers``/``transformers`` tokenizer (see
    :func:`_encode_any`). Returns the corpus dir for
    :class:`tpuflow.data.tokens.TokenDataset`."""
    from tpuflow.data.tokens import write_token_shards

    if seq_len < 2:
        raise ValueError("seq_len must be at least 2")

    def _blocks():
        carry = np.zeros((0,), np.int32)
        for text in texts:
            ids = _encode_any(tokenizer, text)
            if eot_id is not None:
                ids = np.concatenate(
                    [ids, np.asarray([eot_id], np.int32)]
                )
            carry = np.concatenate([carry, ids])
            n_rows = len(carry) // seq_len
            if n_rows:
                yield carry[: n_rows * seq_len].reshape(n_rows, seq_len)
                carry = carry[n_rows * seq_len :]

    return write_token_shards(_blocks(), out_dir,
                              rows_per_shard=rows_per_shard)
