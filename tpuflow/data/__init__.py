from tpuflow.data.table import Table, TableStore  # noqa: F401
from tpuflow.data.ingest import ingest_images  # noqa: F401
from tpuflow.data.transforms import (  # noqa: F401
    add_label_from_path,
    build_label_index,
    index_labels,
    random_split,
)
from tpuflow.data.loader import Dataset, make_dataset  # noqa: F401
from tpuflow.data.tokens import TokenDataset, write_token_shards  # noqa: F401
from tpuflow.data.text import ByteBPE, tokenize_corpus  # noqa: F401
